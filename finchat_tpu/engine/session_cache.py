"""Session KV cache: a byte-budgeted host-RAM tier for cross-turn prefix resume.

The reference is a multi-turn chatbot whose every Kafka message re-fetches the
whole conversation history and re-prefills it from token zero
(serve/app.py process_message), so turn-N TTFT grows linearly with history
even though the engine computed that exact KV last turn. The shared-prefix
entries (scheduler ``_PrefixEntry``) only cover the constant system-prompt
head shared by ALL conversations; this module adds the per-conversation tier
below it — the hierarchical KV management that serving stacks built on paged
attention standardize on (Ragged Paged Attention, arXiv:2604.15464; long-
sequence state streaming, SnapStream, arXiv:2511.03092):

- OFFLOAD: when a sequence retires normally (eos/length), the scheduler
  snapshots its KV pages device→host (``InferenceEngine.offload_pages``)
  BEFORE the pages are freed, keyed by ``conversation_id``.
- RESUME: when the conversation's next turn arrives, admission matches the
  new prompt against the stored token stream — longest common token prefix,
  floored to page granularity — allocates fresh device pages, copies the
  matched pages host→device (``InferenceEngine.restore_pages``), and starts
  prefill at the matched offset.
- DIVERGENCE TRUNCATION: a turn whose history was edited (or re-rendered
  differently) matches only up to the divergence point; the entry is
  truncated there so stale KV can never be served.
- COMPOSITION with the shared-prefix cache: an entry whose sequence rode a
  refcounted ``_PrefixEntry`` head records those device pages BY REFERENCE
  (holding a ref so retirement cannot free them) and snapshots only the
  sequence's OWN pages — the constant head is never copied to host and
  never duplicated on restore.
- LRU under a byte budget: host bytes are the sum of the entries' own-page
  snapshots; inserting past ``budget_bytes`` evicts least-recently-used
  conversations first.

Ownership contract (the allocator invariants of SURVEY §5.2 are untouched):
the cache NEVER owns device pages. Snapshots are host copies taken while the
retiring sequence still owns its pages; restores write into pages freshly
allocated to (and owned by) the admitted sequence. The only device pages an
entry points at are the shared-prefix head's, which stay owned by their
``__prefix_*__`` owner and are protected by the entry's reference count.

Everything here runs on the scheduler's host path (admission / retirement),
never inside a jitted step — the D2H/H2D copies are per-turn costs, not
per-token ones.
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable

import numpy as np

from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

# Cache-key convention, shared across layers: the agent keys each LLM
# role's entry separately (the two roles render DIFFERENT prompts for one
# conversation, so a shared key would cross-truncate every turn), and the
# fleet router must map any such key back to the conversation it belongs
# to — routing and migration are per-CONVERSATION, entries are per-ROLE.
SESSION_KEY_ROLES = ("tool", "resp")


def session_key(conversation_id: str, role: str) -> str:
    """The session-cache key for one LLM role of a conversation."""
    return f"{conversation_id}#{role}"


def conversation_of(key: str) -> str:
    """Inverse of :func:`session_key` for routing: the conversation a
    cache key (or a handle's ``conversation_id``) belongs to. Keys without
    a recognised role suffix — direct scheduler submissions, benches —
    are their own conversation."""
    base, sep, role = key.rpartition("#")
    return base if sep and role in SESSION_KEY_ROLES else key


# Snapshot layout throughout this module: a (k, v, k_scales | None,
# v_scales | None) tuple of host arrays, each [L, n_pages, ...] — the
# gather_pages_host / scatter_pages_device contract (engine/kv_cache.py).


def _snap_nbytes(snap: tuple | None) -> int:
    if snap is None:
        return 0
    return sum(int(a.nbytes) for a in snap if a is not None)


def concat_snaps(head: tuple | None, n_head_pages: int, tail: tuple | None) -> tuple | None:
    """The first ``n_head_pages`` pages of ``head`` followed by all of
    ``tail`` — the incremental-offload splice: a retiring turn reuses the
    previous entry's host bytes for pages it restored (and never rewrote)
    and only the pages written this turn arrive as a fresh D2H ``tail``.
    Always copies, so the result never aliases the (soon-dropped) head."""
    if n_head_pages == 0 or head is None:
        return tail
    sliced = tuple(a[:, :n_head_pages] if a is not None else None for a in head)
    if tail is None:
        return tuple(
            np.ascontiguousarray(a) if a is not None else None for a in sliced
        )
    return tuple(
        np.concatenate([a, b], axis=1) if a is not None else None
        for a, b in zip(sliced, tail)
    )


def _slice_snap(snap: tuple | None, n_pages: int) -> tuple | None:
    """First ``n_pages`` pages of a snapshot, compacted so truncation
    actually releases host RAM (a view would pin the full buffer)."""
    if snap is None or n_pages == 0:
        return None
    return tuple(
        np.ascontiguousarray(a[:, :n_pages]) if a is not None else None
        for a in snap
    )


@dataclass
class SessionEntry:
    """One retired conversation's resumable KV.

    ``token_ids`` holds the ``n_tokens`` tokens whose KV the entry covers —
    always a whole-page multiple, split as ``[0, prefix_len)`` living in the
    referenced shared-prefix pages and ``[prefix_len, n_tokens)`` in the
    host snapshot. ``prefix_entry`` (a scheduler ``_PrefixEntry`` or None)
    carries one reference held for the entry's lifetime; the cache's
    ``on_drop`` callback is where the scheduler releases it.
    """

    conversation_id: str
    token_ids: np.ndarray  # int32 [n_tokens]
    prefix_entry: Any | None = None
    prefix_pages: list[int] = field(default_factory=list)  # device page ids, referenced
    prefix_len: int = 0  # tokens covered by prefix_pages (page multiple)
    snap: tuple | None = None  # host page arrays covering [prefix_len, n_tokens)

    @property
    def n_tokens(self) -> int:
        return len(self.token_ids)

    @property
    def nbytes(self) -> int:
        return _snap_nbytes(self.snap)

    def own_pages_for(self, matched: int, page_size: int) -> int:
        """How many snapshot pages a ``matched``-token resume restores."""
        return max(0, matched - self.prefix_len) // page_size


class SessionKVCache:
    """Host-RAM LRU of ``SessionEntry`` keyed by conversation id.

    Single-task by design (the scheduler loop is the only caller), so no
    locking; the byte budget counts host snapshot bytes only — referenced
    shared-prefix pages live in device HBM under their own owner and are
    already accounted there.
    """

    def __init__(self, budget_bytes: int, page_size: int,
                 on_drop: Callable[[SessionEntry], None] | None = None,
                 metrics=None):
        assert budget_bytes > 0 and page_size > 0
        self.budget_bytes = budget_bytes
        self.page_size = page_size
        self._on_drop = on_drop
        # a fleet replica passes METRICS.labeled(replica=...) so its cache
        # series separate from its siblings'; default is the global registry
        self.metrics = metrics if metrics is not None else METRICS
        self._entries: OrderedDict[str, SessionEntry] = OrderedDict()
        self._resident_bytes = 0
        self._publish_gauges()

    # --- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._entries)

    @property
    def resident_bytes(self) -> int:
        return self._resident_bytes

    def get(self, conversation_id: str) -> SessionEntry | None:
        return self._entries.get(conversation_id)

    def _publish_gauges(self) -> None:
        self.metrics.set_gauge("finchat_session_cache_resident_bytes", self._resident_bytes)
        self.metrics.set_gauge("finchat_session_cache_entries", len(self._entries))

    # --- write path ------------------------------------------------------
    def put(self, entry: SessionEntry) -> bool:
        """Insert (replacing any previous entry for the conversation),
        then LRU-evict others until the byte budget holds. Returns False —
        and drops nothing — when the entry alone exceeds the budget."""
        if entry.nbytes > self.budget_bytes:
            logger.warning(
                "session cache: entry for %s (%d bytes) exceeds budget %d; not stored",
                entry.conversation_id, entry.nbytes, self.budget_bytes,
            )
            return False
        old = self._entries.pop(entry.conversation_id, None)
        if old is not None:
            self._drop(old)
        self._entries[entry.conversation_id] = entry
        self._resident_bytes += entry.nbytes
        while self._resident_bytes > self.budget_bytes:
            victim_id, victim = next(iter(self._entries.items()))
            del self._entries[victim_id]
            self._drop(victim)
            self.metrics.inc("finchat_session_cache_evictions_total")
            logger.debug("session cache: evicted %s (LRU, %d bytes)",
                         victim_id, victim.nbytes)
        self._publish_gauges()
        return True

    def discard(self, conversation_id: str) -> None:
        entry = self._entries.pop(conversation_id, None)
        if entry is not None:
            self._drop(entry)
            self._publish_gauges()

    def clear(self) -> None:
        for entry in list(self._entries.values()):
            self._drop(entry)
        self._entries.clear()
        self._publish_gauges()

    def discard_if(self, pred: Callable[[SessionEntry], bool]) -> int:
        """Drop every entry matching ``pred``; returns how many. Used by
        prefix retirement: an entry referencing a retired head pins that
        head's DEVICE pages (the whole point of the refcount), but after a
        rollover the head can never match again — idle conversations would
        otherwise pin retired-head HBM indefinitely."""
        victims = [e for e in self._entries.values() if pred(e)]
        for entry in victims:
            del self._entries[entry.conversation_id]
            self._drop(entry)
        if victims:
            self._publish_gauges()
        return len(victims)

    def _drop(self, entry: SessionEntry) -> None:
        self._resident_bytes -= entry.nbytes
        entry.snap = None
        if self._on_drop is not None:
            self._on_drop(entry)

    # --- cross-replica migration (serve/fleet.py; ISSUE 6) ---------------
    def export_entry(self, conversation_id: str) -> dict | None:
        """Portable, device-independent image of one conversation's entry
        for cross-replica handoff: token ids + the host snapshot arrays.
        The referenced shared-prefix DEVICE pages are NOT exportable — the
        payload carries only ``prefix_len`` (the head's tokens are
        ``token_ids[:prefix_len]``) so the importer can re-link against
        its OWN live registration of the same head
        (scheduler ``import_session_entry``). Snapshot arrays are shared
        by reference, never mutated in place (truncation replaces them),
        so export is O(1) — no host memcpy of the KV bytes. The entry
        stays resident here; the caller discards it once adopted."""
        entry = self._entries.get(conversation_id)
        if entry is None or entry.n_tokens == 0:
            return None
        return {
            "conversation_id": conversation_id,
            "token_ids": np.array(entry.token_ids, copy=True),
            "prefix_len": int(entry.prefix_len),
            "snap": entry.snap,
        }

    def import_entry(self, payload: dict, *, prefix_entry: Any | None = None,
                     prefix_pages: list[int] | None = None) -> bool:
        """Adopt an exported entry. ``prefix_entry``/``prefix_pages`` is
        the importer's OWN live twin of the exported shared head —
        resolved, validated, and refcounted by the scheduler — covering
        exactly ``payload['prefix_len']`` tokens; both empty only when
        the payload has no head. Returns ``put``'s verdict (the caller
        un-references the head on False, mirroring ``_maybe_offload``)."""
        prefix_len = int(payload["prefix_len"])
        assert (prefix_len == 0) == (prefix_entry is None)
        entry = SessionEntry(
            conversation_id=payload["conversation_id"],
            token_ids=np.asarray(payload["token_ids"], np.int32),
            prefix_entry=prefix_entry,
            prefix_pages=list(prefix_pages or []),
            prefix_len=prefix_len,
            snap=payload["snap"],
        )
        return self.put(entry)

    # --- read path -------------------------------------------------------
    def match(self, conversation_id: str, prompt_ids: list[int]) -> tuple[SessionEntry | None, int]:
        """Longest resumable prefix of ``prompt_ids`` held for this
        conversation: the common token prefix with the entry, floored to
        whole pages, capped so at least one prompt token remains to prefill
        (the admission commit needs real last-token logits — same rule as
        the shared-prefix matcher). A hit refreshes LRU recency.

        Divergence is handled HERE, eagerly: if the new turn's tokens split
        from the stored stream before its end, the entry is truncated to
        the common prefix — the tail belongs to a history this conversation
        no longer has, so it could only ever serve stale KV."""
        entry = self._entries.get(conversation_id)
        if entry is None or not prompt_ids:
            return None, 0
        page = self.page_size
        prompt = np.asarray(prompt_ids, np.int32)
        n = min(entry.n_tokens, len(prompt))
        neq = np.nonzero(entry.token_ids[:n] != prompt[:n])[0]
        common = int(neq[0]) if neq.size else n
        if common < entry.n_tokens:
            self._truncate(entry, (common // page) * page)
            if entry.n_tokens == 0:
                return None, 0
        cap = ((len(prompt) - 1) // page) * page
        matched = min((common // page) * page, cap)
        if matched <= 0:
            return None, 0
        self._entries.move_to_end(conversation_id)
        return entry, matched

    def _truncate(self, entry: SessionEntry, n_tokens: int) -> None:
        """Cut an entry down to a page-aligned token count (divergence).
        An entry truncated to nothing is dropped entirely."""
        assert n_tokens % self.page_size == 0 and n_tokens <= entry.n_tokens
        self.metrics.inc("finchat_session_cache_truncations_total")
        before = entry.nbytes
        entry.token_ids = entry.token_ids[:n_tokens]
        if n_tokens <= entry.prefix_len:
            # the divergence falls inside the shared head: keep only the
            # matched whole head pages (still referenced, still read-only)
            entry.prefix_len = n_tokens
            entry.prefix_pages = entry.prefix_pages[: n_tokens // self.page_size]
            entry.snap = None
        else:
            entry.snap = _slice_snap(
                entry.snap, (n_tokens - entry.prefix_len) // self.page_size
            )
        self._resident_bytes += entry.nbytes - before
        if entry.n_tokens == 0:
            del self._entries[entry.conversation_id]
            self._drop(entry)
        self._publish_gauges()
