"""Continuous-batching scheduler.

Replaces the reference's "one message at a time per worker" concurrency model
(``main.py:131-159``, SURVEY §2.3) with many sequences multiplexed onto one
model replica:

- Admission: pending sequences are admitted when a slot AND enough KV pages
  for prompt + max_new_tokens are available (no mid-flight OOM).
- Batched chunked prefill interleaved with decode: each loop iteration runs
  ONE prefill round — every prefilling sequence advances one chunk in a
  single [N, chunk] ``prefill_step`` (N padded to a power of two, so at
  most log2(max_seqs) compiled variants) — then one decode step for all
  active slots. A 64-session burst costs a handful of weight-reads instead
  of 64 serial ones, and long prompts cannot starve in-flight decodes
  (SURVEY §7.3 hard part 3).
- Pipelined decode (SURVEY §7.3 hard part 3, "low-latency token
  streaming"): decode step N+1 is dispatched to the device BEFORE step N's
  tokens are fetched, so the device never idles waiting for the host, and
  every device→host fetch runs in a worker thread so the asyncio loop
  (HTTP handlers, Kafka produces) never blocks on the chip. A sequence
  that hits EOS at step N wastes one speculative token at N+1; the host
  discards it. A grammar-constrained sequence needs its host-side pick
  written back before its next step, so it sits OUT the speculative step
  (inactive, trash-redirected) and rejoins the following one — advancing
  every other step while unconstrained streams keep full depth-2 cadence.
- Fused multi-step decode (``decode_loop_depth`` K > 1): slots needing no
  per-token host control ride ``decode_loop_step`` blocks — K decode
  iterations, on-device sampling, and the EOS stop mask inside ONE device
  dispatch, with the host fetching a ``[K, max_seqs]`` token block per
  round-trip instead of ``[max_seqs]`` per token. Composes with the
  depth-2 pipeline (block N+1 dispatched before block N is consumed).
  Grammar-constrained slots, spec-decode iterations, and slots within K
  tokens of their ``max_new_tokens``/page budget are demoted to
  single-step (mirroring the SPEC_MISS_DEMOTE machinery) and rejoin
  blocks when eligibility returns; slots that finish mid-block free-run
  into the trash page and their tail iterations are counted as waste.
- Unified packed ragged step (``engine.mixed_step``, default on; ISSUE
  10): when prefill work and in-flight decodes coexist, the iteration
  runs ONE ``ragged_mixed_step`` dispatch over a PACKED token buffer
  (ops/ragged_paged_attention.py) — every prefilling row advances a
  chunk, every decoding row a token, grammar-constrained rows return
  their logits for the host pick, spec-eligible rows verify a
  (1+Kd)-token draft block, and loop-eligible rows free-run a fused
  ``loop_depth-1`` tail, all with on-device sampling — instead of two or
  more serialized dispatches. Only ring/seq-sharded prefill rows demote
  the iteration to the split path below, which remains the
  golden-identical fallback (greedy streams are byte-identical either
  way; tests/test_mixed_step.py pins it); demotions are counted per
  reason in ``finchat_mixed_demotions_total``.
- Free-running device loop (``engine.freerun_rounds`` > 1; ISSUE 13):
  when the mixed path is live and no row needs a per-round host decision
  (no grammar-constrained rows, no live spec-proposal window — the
  ``_use_mixed``-style cap), up to ``freerun_rounds`` consecutive ragged
  rounds are CAPTURED into one device program
  (engine.ragged_multi_round): prefill descriptors for every round are
  pre-staged into a device-memory queue the rounds drain (completed
  prompts flip to on-device-sampled decode rows mid-run), EOS stops via
  the on-device ``row_live`` mask (budget stops are staged away), and
  per-round tokens land in an output ring the host drains OFF-LOOP while
  the device free-runs the next capture (depth-2). Host control returns
  only at membership epochs: any admit/evict/preempt/breaker event ends
  re-entry at a round boundary, residual ring tokens replay exactly once
  under the PR 5 epoch discipline, and the host-stepped round (and split
  path below it) remain the golden-identical fallbacks. Dispatches per
  ROUND drop to 1/freerun_rounds on the coexist counters.
- Session KV cache (engine/session_cache.py): sequences submitted with a
  ``conversation_id`` snapshot their KV pages device→host when they retire
  normally (eos/length, before the pages are freed) and the conversation's
  next turn resumes from the longest matching page-whole token prefix —
  restored pages + prefill starting at the matched offset — instead of
  re-prefilling the whole history. Composes with the shared-prefix entries
  below: a cached head is referenced (refcounted), never copied.
- Per-sequence failure isolation (SURVEY §5.3): an errored sequence is
  evicted, its pages freed, an error event emitted on its stream, and the
  engine keeps serving the others. The process-level watchdog of the
  reference becomes per-sequence.
- Resilience plane (ISSUE 5; ROBUSTNESS.md): recompute preemption
  (``_preempt`` — free a victim's slot and KV pages but keep prompt +
  generated tokens on the handle; re-admission re-prefills and resumes
  with zero duplicate or dropped tokens), used for page pressure (the
  lowest-priority victim yields instead of the head-of-line stalling) and
  as the recovery primitive of the engine circuit breaker
  (``breaker_threshold`` consecutive failed dispatch rounds → all live
  sequences preempt to host, the device state is torn down and rebuilt
  with weights retained, a half-open probe round re-admits). Deadline
  admission: pending requests past their deadline are shed pre-admission
  with a structured retryable error, admission orders
  earliest-deadline-first with a starvation guard, and ``submit`` rejects
  above ``max_queue_depth`` (backpressure instead of an unbounded queue).
- Invariants (SURVEY §5.2): the page allocator's ownership checks run at
  every free; slot bookkeeping is single-task (the step loop) by design.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field

from typing import TYPE_CHECKING

import jax.numpy as jnp
import numpy as np

from finchat_tpu.engine.engine import InferenceEngine, commit_first_token, prefill_step

if TYPE_CHECKING:  # engine must not import the agent layer at runtime
    from finchat_tpu.agent.constrained import TokenConstraint
from finchat_tpu.engine.kv_cache import (
    PageAllocationError,
    PageAllocator,
    pages_needed,
)
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS, Timer
from finchat_tpu.utils.tracing import TRACER, RequestSpan

logger = get_logger(__name__)


class OverloadedError(RuntimeError):
    """``submit`` rejected: the admission queue is at ``max_queue_depth``.
    Retryable by contract — the serving layer surfaces it as a structured
    retryable error chunk instead of an opaque failure."""

    code = "overloaded"
    retryable = True


@dataclass
class SequenceHandle:
    """Host-side record of one in-flight sequence; ``events`` receives
    ``{"type": "token", "token_id": int}``, then one terminal
    ``{"type": "done", "reason": ...}`` or ``{"type": "error", ...}``."""

    seq_id: str
    prompt_ids: list[int]
    sampling: SamplingParams
    constraint: TokenConstraint | None = None
    # session KV cache key: turns of the same conversation resume each
    # other's KV (engine/session_cache.py); None = no cross-turn caching
    conversation_id: str | None = None
    events: asyncio.Queue = field(default_factory=asyncio.Queue)
    slot: int = -1
    prefill_pos: int = 0  # prompt tokens already prefilled
    # full logical→physical page list assigned at admission (shared head
    # pages first, then owned pages) — retirement offload slices it
    page_list: list[int] = field(default_factory=list)
    # tokens covered by READ-ONLY referenced head pages (shared-prefix or
    # session-restored head); the slot's own writes start past this
    shared_len: int = 0
    # tokens whose KV was restored from a session-cache snapshot at
    # admission (0 = cold). Pages covering [shared_len, resumed_len) were
    # copied host→device and never rewritten, so retirement offload reuses
    # the previous entry's host bytes for them instead of a fresh D2H copy
    resumed_len: int = 0
    generated: int = 0
    # the scheduler currently driving this handle: set at submit and
    # REBOUND by a fleet drain adoption (serve/fleet.py) — cleanup paths
    # (generator cancel on disconnect/watchdog) hold a reference to the
    # ORIGINAL scheduler, and evicting there with the adopter's slot index
    # would corrupt the source's slot state; cancel() delegates to owner
    owner: object | None = None
    # prompt + delivered tokens — the prompt-lookup draft source when
    # speculative decoding is on (engine/spec.py); maintained by _deliver
    history: list[int] = field(default_factory=list)
    # incremental n-gram index over ``history`` (engine/spec.py NgramIndex),
    # created lazily by the spec decode path and kept in sync by _deliver —
    # proposing must be O(1) on the event loop, not a history rescan
    ngram_index: object | None = None
    # shared-prefix cache entry this sequence's page table references
    # (scheduler _PrefixEntry); refcounted so retirement can free safely
    prefix_entry: object | None = None
    # on the segmented seq-sharded prefill path (prefill_pos > 0 there
    # means "mid-ring", NOT "ride the chunked batch")
    ring_path: bool = False
    # retrieval/prefill overlap (submit_partial): ``prompt_ids`` is only
    # the prompt's STATIC PREFIX — prefill it, then PARK without
    # committing a first token until extend_prompt grafts the full
    # prompt (or the hold goes stale and is reaped)
    held: bool = False
    held_deadline: float = 0.0
    # the hold was extended into a full prompt: the remaining suffix MUST
    # keep the chunked prefill path (the seq-sharded ring paths assume
    # they owned the prompt from position 0 / their own segment schedule)
    grafted: bool = False
    # completion deadline on the scheduler's monotonic clock
    # (time.perf_counter); None = no deadline. Pending entries past it are
    # shed pre-admission; admission orders earliest-deadline-first; page
    # pressure preempts the latest-deadline victim for a strictly-earlier
    # candidate.
    deadline: float | None = None
    # bounded-KV serving (ISSUE 15; kv_cache.BoundedKVPolicy): tokens the
    # eviction policy dropped from this row's page list — whole pages
    # between the pinned sink and the surviving window; 0 = nothing
    # evicted. Host-deterministic metadata mirrored into the engine's
    # state.kv_gaps between dispatches (eviction waves update both sides
    # together, so every enqueued step sees a table and gap that agree).
    kv_gap: int = 0
    # kv_ctx value at this row's most recent eviction wave (0 = never
    # evicted): while kv_gap_pos exceeds the DELIVERED context, an
    # undelivered in-flight token was computed under an older gap — a
    # preempt taken inside that window recomputes it under the newer gap
    # (the page-pressure path never does: it drains in-flight first).
    kv_gap_pos: int = 0
    # host mirror of the slot's device context length AFTER every
    # DISPATCHED (not merely consumed) step — advanced at dispatch-build
    # time by each dispatch's deterministic context advance. This is the
    # eviction schedule's sole input: the wave runs between dispatches, so
    # kv_ctx at a wave is exactly the next dispatch's write position, and
    # the gap a token's dispatch sees becomes a PURE function of that
    # position — independent of pipeline depth, free-run capture depth,
    # or a preempt/replay boundary (the byte-identity contracts lean on
    # this; delivered-count-plus-inflight inference is phase-dependent).
    kv_ctx: int = 0
    # preempt-replay restore plane for bounded rows (ISSUE 15 satellite):
    # a host snapshot of the SURVIVING pages (sink + window, compacted,
    # page-whole) taken at preemption, so re-admission restores
    # byte-identical KV and re-prefills only the residual tail instead of
    # re-prefilling tokens the policy would immediately evict. None for
    # unbounded rows and rows that never evicted.
    bounded_snap: tuple | None = None
    bounded_snap_tokens: int = 0  # compacted tokens the snapshot covers
    # recompute preemptions survived (page pressure / breaker recovery) —
    # a preempted handle's prompt_ids become its full history and it
    # re-admits through the normal path
    preempted: int = 0
    # admission epoch: bumped by _preempt so a dispatch's membership
    # snapshot (captured as (slot, handle, epoch)) can tell a REPLAYED
    # incarnation from the one it was dispatched against — the same handle
    # can re-admit into the same slot while a stale step is still
    # unconsumed, and slot identity alone would double-deliver its token
    epoch: int = 0
    submitted_at: float = field(default_factory=time.perf_counter)
    first_token_at: float | None = None
    # host arrival time of the last delivered token — feeds the
    # finchat_inter_token_seconds histogram (labeled by whether the
    # emitting iteration also ran prefill work)
    last_token_at: float | None = None
    finished: bool = False
    # end-to-end trace id (utils/tracing.py — ISSUE 12): minted at ingress
    # (Kafka message_id / HTTP header) and threaded down through the agent
    # and generator; None = untraced (direct scheduler submissions)
    trace_id: str | None = None
    span: RequestSpan = None  # type: ignore[assignment]  # set in __post_init__

    def __post_init__(self) -> None:
        if self.span is None:
            self.span = RequestSpan(self.seq_id, trace_id=self.trace_id)
        if not self.history:
            self.history = list(self.prompt_ids)

    def _emit_first_token_metrics(self) -> None:
        if self.first_token_at is None:
            self.first_token_at = time.perf_counter()
            self.span.mark("first_token")
            METRICS.observe("finchat_ttft_seconds", self.first_token_at - self.submitted_at,
                            trace_id=self.trace_id)


@dataclass
class _InFlightStep:
    """A dispatched-but-unconsumed decode step (device arrays + the
    membership snapshot it was dispatched against; members carry the
    handle's admission epoch so a preempted-and-replayed incarnation
    never receives a stale token)."""

    tokens: object  # [max_seqs] int32, device
    logits: object | None  # [n_constrained, vocab] fp32 device slice, or None
    members: list[tuple[int, SequenceHandle, int]]
    constrained_slots: list[int]


@dataclass
class _InFlightBlock:
    """A dispatched-but-unconsumed fused decode block (decode_loop mode):
    one ``[K, max_seqs]`` device token block for the loop-eligible slots,
    plus the single ``decode_step`` covering the DEMOTED slots (grammar-
    constrained / within K of budget) dispatched in the same scheduler
    iteration, if any."""

    block_tokens: object  # [K, max_seqs] int32, device (-1 = no token)
    block_members: list[tuple[int, SequenceHandle, int]]
    step: _InFlightStep | None


@dataclass
class _InFlightRing:
    """A dispatched-but-unconsumed captured multi-round run (the
    free-running loop, ISSUE 13): the per-round token ring device arrays
    from ``engine.ragged_multi`` plus the staged plan's host bookkeeping.
    Members carry the admission epoch exactly like ``_InFlightStep`` —
    the PR 5 discipline is what makes an epoch boundary (admit / evict /
    preempt / breaker while the capture is mid-flight) safe: stale rows'
    ring tokens are discarded at drain time and the preempt-replay
    recomputes them, so delivery stays exactly-once."""

    tokens: object  # [F, R] int32, device — each armed row's round token
    n_emitted: object  # [F, R] int32, device (0 = mid-prompt chunk / dead)
    blocks: object  # [F, K-1, max_seqs] int32, device — fused tails
    rounds: int
    # (row, slot, owner, epoch, kind) — owner is a SequenceHandle for
    # "prefill"/"decode" rows, a _PrefixJob for "job" rows (no tokens)
    members: list
    armed: object  # np [F, R] staged arm mask — exactly-once replay ref
    loop_rounds: object  # np [F, max_seqs] staged fused-tail schedule
    completes_at: dict  # row -> round its prompt completes (first token)
    ahead: dict  # slot -> staged max emissions (budget accounting for
    #   the NEXT dispatch staged before this ring is consumed)


@dataclass
class _PrefixJob:
    """An in-progress chunked prefix registration (register_prefix_async):
    the head prefills one chunk per prefill round, riding the same batched
    ``prefill_step`` as admitted sequences, so decode steps interleave and
    a midnight refresh never stalls in-flight streams for the whole head
    (VERDICT r4 weak #6). Owns its pages and an engine slot until it
    completes (entry published) or fails (pages freed, future gets 0)."""

    ids: list[int]
    shared_len: int
    owner: str
    pages: list[int]
    slot: int
    future: asyncio.Future
    pos: int = 0


@dataclass
class _PrefixEntry:
    """One registered shared prompt head: its token ids, the pages holding
    its prefilled KV, and a live-reference count so retirement (e.g. the
    date inside the head rolled over) frees the pages only once no
    in-flight sequence's page table still points at them."""

    ids: list[int]
    pages: list[int]
    shared_len: int
    owner: str
    refs: int = 0
    retired: bool = False


class ContinuousBatchingScheduler:
    # spec-decode all-miss demotion thresholds (see __init__ comment):
    # demote after this many consecutive zero-accept verify steps...
    SPEC_MISS_DEMOTE = 4
    # ...and re-probe after this many pipelined steps
    SPEC_RETRY_EVERY = 16

    def __init__(self, engine: InferenceEngine, eos_id: int,
                 metrics=None, replica_id: str | None = None,
                 fabric=None):
        self.engine = engine
        self.eos_id = eos_id
        # fleet identity (serve/fleet.py): ``replica_id`` tags this
        # scheduler's fault-injection sites (so a chaos test can wedge ONE
        # replica) and ``metrics`` is a METRICS.labeled(replica=...) view
        # so every existing metric family comes out per-replica. Both
        # default to the single-engine behavior unchanged.
        self.replica_id = replica_id
        self.metrics = metrics if metrics is not None else METRICS
        cfg = engine.engine_cfg
        self.allocator = PageAllocator(cfg.num_pages)
        self.free_slots: list[int] = list(range(cfg.max_seqs))
        self.pending: deque[SequenceHandle] = deque()
        self.prefilling: deque[SequenceHandle] = deque()
        self.decoding: dict[int, SequenceHandle] = {}  # slot -> handle
        B = cfg.max_seqs
        self._temperature = np.zeros((B,), np.float32)
        self._top_p = np.ones((B,), np.float32)
        self._top_k = np.zeros((B,), np.int32)
        self._wakeup = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._running = False
        self._rng = np.random.default_rng(0)  # host-side constrained sampling
        # speculative decoding (engine/spec.py): > 0 switches the decode
        # path to depth-1 verify steps with Kd host-proposed drafts —
        # drafting needs the previous token on the HOST, which depth-2
        # pipelining by construction has not fetched yet
        self.spec_k = cfg.spec_tokens
        # all-miss demotion: depth-1 spec steps trade away the depth-2
        # device/host overlap, so sustained non-repetitive traffic (every
        # proposal empty or rejected) would pay that tax forever. After
        # SPEC_MISS_DEMOTE consecutive zero-accept steps the loop reverts
        # to the pipelined path for SPEC_RETRY_EVERY steps, then re-probes
        # (prompt-lookup hit rate changes as the answer starts quoting
        # retrieved rows, so a one-way demotion would miss the recovery).
        self._spec_miss_streak = 0
        self._spec_cooldown = 0
        # fused multi-step decode (engine decode_loop_step): K > 1 switches
        # the pipelined path to K-token blocks per dispatch for slots that
        # need no per-token host control; constrained / near-budget slots
        # are demoted to a single decode_step riding the same iteration,
        # and spec-decode iterations keep their own depth-1 verify cadence
        self.loop_depth = engine.decode_loop_depth
        self.metrics.set_gauge("finchat_decode_loop_depth", self.loop_depth)
        # unified packed ragged step (engine.mixed_step config): one
        # dispatch advances every prefilling row a chunk, every decoding
        # row a token, spec rows a verify block, and loop-eligible rows a
        # fused tail whenever both populations exist — see _use_mixed /
        # _ragged_round (ISSUE 10). Only ring-routed prefill demotes.
        self.mixed_enabled = bool(cfg.mixed_step)
        # demotion observability (ISSUE 10 satellite): every reason the
        # old padded mixed step demoted on is pre-seeded at zero, so the
        # erasure (spec/decode_loop/constrained stuck at 0, only ring — a
        # collective schedule — still firing) is visible per replica
        for reason in self.MIXED_DEMOTION_REASONS:
            self.metrics.inc("finchat_mixed_demotions_total", 0.0,
                             labels={"reason": reason})
        # whether the CURRENT loop iteration ran (or will run) prefill
        # work — the finchat_inter_token_seconds label distinguishing the
        # admission-stall case from steady decode
        self._iter_ran_prefill = False
        # dispatch-seam tally attributed to coexist iterations: every
        # model dispatch this scheduler enqueues bumps _dispatch_tally,
        # and the span from one coexist iteration's start to the next
        # accounting point lands in finchat_coexist_dispatches_total — so
        # dispatches-per-coexist-iteration (the bench --ragged-sweep
        # headline) is exact, not a racy window over global counters
        self._dispatch_tally = 0
        self._coexist_mark: int | None = None
        # free-running loop (ISSUE 13): consecutive ragged rounds captured
        # per dispatch (engine.freerun_rounds; 1 = host-stepped rounds).
        # _round_tally counts logical serving ROUNDS the same way
        # _dispatch_tally counts enqueued programs — a captured run books
        # F rounds for its one dispatch — and the same mark/attribute pair
        # lands both in the coexist counters, so the headline ratio
        # becomes dispatches per ROUND (< 1 once captures engage) measured
        # by the exact PR 10 attribution, not a new ad-hoc window.
        self.freerun_rounds = max(1, getattr(engine, "freerun_rounds", 1))
        self._round_tally = 0
        self._coexist_round_mark = 0
        if self.freerun_rounds > 1:
            # pre-seed the cap reasons (the _use_mixed demotion-counter
            # discipline): a capture that never caps is visible as zeros
            for reason in self.FREERUN_CAP_REASONS:
                self.metrics.inc("finchat_freerun_capped_total", 0.0,
                                 labels={"reason": reason})
        # trace-event track label (utils/tracing.py — ISSUE 12): one
        # Perfetto track per engine so a fleet's dispatch timelines stay
        # separable in one export
        self._trace_track = (
            f"replica-{replica_id}" if replica_id is not None else "engine"
        )
        # bounded-KV long-context serving (ISSUE 15): the engine's
        # sink+window policy (None = unbounded legacy). The
        # finchat_boundedkv_* family pre-seeds per replica — gauges show
        # the configured shape, the counters render from zero so the
        # first eviction wave (and any recompute fallback) is visible.
        self.bounded_kv = getattr(engine, "bounded_kv", None)
        _bp = self.bounded_kv
        self.metrics.set_gauge("finchat_boundedkv_sink_pages",
                               _bp.sink_pages if _bp else 0)
        self.metrics.set_gauge("finchat_boundedkv_window_pages",
                               _bp.window_pages if _bp else 0)
        self.metrics.inc("finchat_boundedkv_evicted_pages_total", 0.0)
        self.metrics.inc("finchat_boundedkv_bounded_sessions_total", 0.0)
        self.metrics.inc("finchat_boundedkv_recompute_fallbacks_total", 0.0)
        # quantized serving plane (ISSUE 14): the engine's quant mode as
        # one label on every dispatch trace event (timelines distinguish
        # bf16/int8/int4 dispatches), plus the finchat_quant_* family —
        # mode gauges (bits per weight / per KV element) and pre-seeded
        # fallback/envelope counters so a mode flip or a refused
        # cross-mode restore is visible from zero
        self._quant_label = getattr(engine, "quant_label", "bf16")
        _wbits = {"": None, "int8": 8, "int4": 4}.get(
            getattr(engine, "quant", ""))
        _elem_bits = 8 * np.dtype(engine.config.dtype).itemsize
        self.metrics.set_gauge("finchat_quant_weight_bits",
                               _wbits if _wbits else _elem_bits)
        self.metrics.set_gauge(
            "finchat_quant_kv_bits",
            8 if getattr(engine, "kv_quant", "") else _elem_bits,
        )
        self.metrics.inc("finchat_quant_dequant_fallbacks_total", 0.0)
        self.metrics.inc("finchat_quant_envelope_exceeded_total", 0.0)
        # fused dequant-matmul plane (ops/quant_matmul.py): the resolved
        # backend as a gauge (0=ref, 1=pallas-interpret, 2=pallas) plus
        # pre-seeded dispatch/fallback counters — fused engagement (or a
        # stacked-weight fallback) is visible from zero per replica
        _qm = getattr(engine, "qm_backend", "ref")
        self.metrics.set_gauge(
            "finchat_quantmatmul_backend",
            {"ref": 0, "pallas-interpret": 1, "pallas": 2}.get(_qm, 0),
        )
        self.metrics.inc("finchat_quantmatmul_fused_dispatches_total", 0.0)
        self.metrics.inc("finchat_quantmatmul_fallbacks_total", 0.0)
        # whether this engine's compiled steps route quantized matmuls
        # through the fused kernel — one bool for the dispatch tally below
        self._qm_fused = bool(
            getattr(engine, "quant", "") and _qm != "ref"
        )
        # shared-prefix KV cache: matched at admission so identical prompt
        # heads (the constant system prompt every conversation shares) are
        # prefilled ONCE per process instead of per request — see
        # register_prefix / retire_prefixes
        self._prefixes: list[_PrefixEntry] = []
        self._n_prefixes_ever = 0  # unique allocator owner ids
        self._prefix_jobs: deque[_PrefixJob] = deque()
        # log the top_k clamp once per distinct requested value — a
        # misconfigured client retries per message, and per-request warnings
        # would flood the log under load (the clamp itself still applies and
        # is counted in finchat_top_k_clamped_total)
        self._top_k_clamp_warned: set[int] = set()
        # --- resilience plane (ISSUE 5) ---------------------------------
        # engine circuit breaker: consecutive whole-round dispatch failures
        # per plane ("prefill" / "decode" — mixed and spec ride the decode
        # bucket) before the breaker trips and the device state is rebuilt.
        # 0 disables the breaker (legacy: a whole-round failure evicts its
        # in-flight sequences with an error).
        self.breaker_threshold = max(0, cfg.breaker_threshold)
        self.breaker_max_rebuilds = max(1, cfg.breaker_max_rebuilds)
        self.preemption_enabled = bool(cfg.preemption)
        self.edf_starvation_s = max(0.0, cfg.edf_starvation_seconds)
        self.max_queue_depth = max(0, cfg.max_queue_depth)
        # retrieval/prefill overlap (ISSUE 3): how long a parked hold may
        # wait for its extend_prompt before the scheduler reclaims its
        # slot+pages — retrieval is ms-scale (and the tool-streaming
        # plane takes holds at most one decision decode early), so a hold
        # this old means its owner died. engine.partial_hold_ttl_seconds.
        self.hold_ttl_s = max(0.0, cfg.partial_hold_ttl_seconds)
        self._fail_streaks = {"prefill": 0, "decode": 0}
        self._rebuilds_without_success = 0
        self._breaker_tripped_at: float | None = None
        # which plane tripped the breaker: only a successful round of THAT
        # plane closes it (a decode-wedged engine keeps prefilling fine —
        # prefill successes must not mask the wedge or reset the
        # consecutive-rebuild give-up counter)
        self._breaker_bucket: str | None = None
        # callbacks run after an engine rebuild (the serving layer uses one
        # to re-register its shared prompt heads — the rebuild dropped them)
        self.on_rebuild: list = []
        # --- fleet hooks (serve/fleet.py; ISSUE 6) ----------------------
        # drain sink: when set, a breaker trip offers every live/pending
        # handle (preempted to host first — prompt+generated tokens on the
        # handle, device-free) plus its conversation's exported
        # session-cache bytes to the sink instead of riding out the
        # rebuild here; the sink returns True when a sibling replica
        # adopted the stream. Signature: (handle, session_payload) -> bool.
        self.drain_sink = None
        # callbacks fired when the breaker gives up (the supervisor marks
        # this replica OUT and schedules a respawn)
        self.on_give_up: list = []
        # breaker give-up state: True from give-up until revive() —
        # the fleet router stops routing here while set
        self.gave_up = False
        # True while a trip-path rebuild runs in its worker thread
        # (_trip_breaker): the rebuild replaces the page table under the
        # engine, so register_prefix_async must not write a row into the
        # doomed table mid-flight (the row would be lost and the head
        # would prefill against trash pages). Best-effort contract:
        # callers get 0 and the periodic refresh retries.
        self._rebuilding = False
        # breaker state gauge: 0 closed, 1 open (rebuilding), 2 half-open
        # (rebuilt, awaiting the first successful probe round)
        self.metrics.set_gauge("finchat_breaker_state", 0)
        # warm-state fabric (engine/warm_fabric.py — ISSUE 17): when set,
        # this replica's session tier is the fleet's SHARED disk tier,
        # shared prompt heads restore from / publish to the fabric instead
        # of re-prefilling per replica, and the cache keeps the fabric's
        # global holder index current. None = the per-replica PR 7 layout.
        self.fabric = fabric
        # disaggregated serving (serve/disagg.py — ISSUE 17): the fleet
        # attaches its DisaggCoordinator to SERVING-pool schedulers only;
        # submit routes cold prompt prefills through it when set
        self.disagg = None
        # pod plane (serve/pod.py — ISSUE 20): the app attaches its
        # PodCoordinator; submit asks it to pull a conversation's session
        # bytes from a liaison peer when nothing local can resume it warm
        self.pod = None
        if fabric is not None:
            # fabric accounting is per calling replica (R5: pre-seeded so
            # the zero state is visible): hits/misses at head registration
            # and shared-tier session restore, refusals on cross-mode RAM
            # head snapshots (disk-record refusals count on the tier's own
            # replica="fabric" view)
            self.metrics.inc("finchat_fabric_hits_total", 0.0)
            self.metrics.inc("finchat_fabric_misses_total", 0.0)
            self.metrics.inc("finchat_fabric_import_refused_total", 0.0)
        # session KV cache (engine/session_cache.py): host-RAM tier keyed by
        # conversation_id; None = disabled. The on_drop hook is where entry
        # references on shared-prefix pages are released.
        self.session_cache = None
        if cfg.session_cache and cfg.session_cache_bytes > 0:
            from finchat_tpu.engine.session_cache import (
                SessionDiskTier,
                SessionKVCache,
            )

            # durability plane (ISSUE 7): disk spill tier under the RAM
            # LRU — entries write through to checksummed record files and
            # a RAM miss at admission falls back to disk, so a restarted
            # process resumes conversations warm. Fleet replicas get
            # sibling subdirectories (replica ids are stable across
            # restarts, and migration handles the cross-replica moves) —
            # unless the warm-state fabric is on, in which case every
            # replica shares the fabric's ONE tier (ISSUE 17) and any
            # replica restores any conversation.
            disk = None
            disk_path = getattr(cfg, "session_cache_disk_path", "")
            if fabric is not None:
                disk = fabric.tier
            elif disk_path:
                if replica_id is not None:
                    import os as _os

                    disk_path = _os.path.join(disk_path, f"replica-{replica_id}")
                try:
                    disk = SessionDiskTier(
                        disk_path, cfg.session_cache_disk_bytes,
                        metrics=self.metrics,
                        # records written under the other page-pool dtype
                        # are refused (counted), never scattered (ISSUE 14)
                        kv_quant=engine.kv_quant,
                    )
                except Exception as e:  # durability is best-effort
                    logger.error("session disk tier unavailable at %s: %s",
                                 disk_path, e)
            self.session_cache = SessionKVCache(
                cfg.session_cache_bytes, page_size=cfg.page_size,
                on_drop=self._session_drop, metrics=self.metrics, disk=disk,
                fabric=fabric, fabric_replica=replica_id,
            )

    # --- public API -----------------------------------------------------
    async def start(self) -> None:
        # rebind to the CURRENT loop: an Event pins itself to the loop that
        # first awaits it, so a stop/start cycle across asyncio.run calls
        # (tests, serving restarts) would otherwise raise "bound to a
        # different event loop"
        self._wakeup = asyncio.Event()
        self._running = True
        # warmup-matrix observability (ISSUE 10 satellite): re-emit the
        # engine's compiled-variant tally through this scheduler's metrics
        # view, so fleet replicas label it per replica like every other
        # per-engine family (0 until the engine has been warmed)
        self.metrics.set_gauge(
            "finchat_warmup_compiled_variants",
            getattr(self.engine, "compiled_variants", 0),
        )
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        self._running = False
        self._wakeup.set()
        if self._task:
            await self._task
        for job in list(self._prefix_jobs):  # shutdown mid-registration
            self._fail_prefix_job(job)

    async def submit(
        self,
        seq_id: str,
        prompt_ids: list[int],
        sampling: SamplingParams,
        constraint: TokenConstraint | None = None,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> SequenceHandle:
        if not prompt_ids:
            raise ValueError("empty prompt")
        if self.max_queue_depth > 0 and len(self.pending) >= self.max_queue_depth:
            # backpressure: reject NEW load above the bound with a
            # retryable error instead of queueing unboundedly (preempted
            # sequences bypass submit — they are live streams, not load)
            self.metrics.inc("finchat_overload_rejections_total")
            raise OverloadedError(
                f"admission queue full ({len(self.pending)} >= "
                f"{self.max_queue_depth}); retry with backoff"
            )
        max_len = self.engine.max_pages_per_seq * self.engine.page_size
        if (len(prompt_ids) + sampling.max_new_tokens > max_len
                and self.bounded_kv is None):
            # bounded-KV serving lifts this bound: the eviction policy
            # caps page occupancy at sink+window regardless of context
            # length, which is the whole point (ISSUE 15)
            raise ValueError(
                f"sequence {seq_id}: prompt {len(prompt_ids)} + max_new "
                f"{sampling.max_new_tokens} exceeds max length {max_len}"
            )
        from finchat_tpu.engine.sampler import CANDIDATES

        if sampling.top_k > CANDIDATES:
            if sampling.top_k not in self._top_k_clamp_warned:
                self._top_k_clamp_warned.add(sampling.top_k)
                logger.warning(
                    "sequence %s: top_k=%d exceeds the sampler candidate cap %d; "
                    "clamping (logged once per distinct top_k — further requests "
                    "are clamped silently and counted in "
                    "finchat_top_k_clamped_total; see SamplingParams truncation "
                    "contract)",
                    seq_id, sampling.top_k, CANDIDATES,
                )
            self.metrics.inc("finchat_top_k_clamped_total")
            import dataclasses as _dc

            sampling = _dc.replace(sampling, top_k=CANDIDATES)
        if self.disagg is not None and conversation_id:
            # disaggregated serving (ISSUE 17): a cold prompt prefills on
            # the prefill pool and its KV arrives through the session
            # tier BEFORE admission, so the match below resumes from it.
            # Best-effort: any failure just leaves the local prefill path.
            try:
                await self.disagg.maybe_prefill(
                    self, prompt_ids, conversation_id, trace_id=trace_id
                )
            except Exception as e:
                logger.error("disagg handoff for %s failed: %s",
                             conversation_id, e)
                self.metrics.inc("finchat_disagg_fallbacks_total",
                                 labels={"reason": "prefill_error"})
        if self.pod is not None and conversation_id:
            # pod plane (ISSUE 20): a conversation inherited from another
            # host pulls its newest session record over the liaison BEFORE
            # admission, so the match below resumes from it warm. Every
            # failure inside is a counted cold start, never an error here.
            try:
                await self.pod.maybe_pull(self, conversation_id,
                                          trace_id=trace_id)
            except Exception as e:
                logger.error("pod session pull for %s failed: %s",
                             conversation_id, e)
        handle = SequenceHandle(
            seq_id=seq_id, prompt_ids=list(prompt_ids), sampling=sampling,
            constraint=constraint, conversation_id=conversation_id,
            deadline=deadline, owner=self, trace_id=trace_id,
        )
        self.pending.append(handle)
        self.metrics.set_gauge("finchat_queue_depth", len(self.pending))
        self._wakeup.set()
        return handle

    async def submit_partial(
        self,
        seq_id: str,
        prefix_ids: list[int],
        sampling: SamplingParams,
        conversation_id: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
    ) -> SequenceHandle | None:
        """Start prefilling a prompt whose TAIL is not known yet (the
        retrieval/prefill overlap path): ``prefix_ids`` is the static
        leading part of the final prompt (system head + context + history
        — everything upstream of the retrieval graft point). The sequence
        admits and prefills normally but PARKS when the prefix is done
        instead of committing a first token; ``extend_prompt`` grafts the
        full prompt in when retrieval returns and prefill continues from
        the parked position. Returns None when the prefix can't ride this
        path (empty, over budget, or seq-sharded-ring eligible — the ring
        prefill owns its prompt end-to-end); callers fall back to a plain
        ``submit`` of the full prompt.
        """
        if not prefix_ids:
            return None
        max_len = self.engine.max_pages_per_seq * self.engine.page_size
        if len(prefix_ids) + sampling.max_new_tokens > max_len:
            return None  # the full prompt could never fit either
        if self.engine._use_ring_prefill(len(prefix_ids)):
            return None
        handle = await self.submit(
            seq_id, prefix_ids, sampling, conversation_id=conversation_id,
            deadline=deadline, trace_id=trace_id,
        )
        # no await ran between submit() appending to pending and here (the
        # scheduler loop is a separate task), so the hold flags are set
        # before admission can see the handle
        handle.held = True
        handle.held_deadline = time.perf_counter() + self.hold_ttl_s
        self.metrics.inc("finchat_partial_holds_total")
        return handle

    def extend_prompt(self, handle: SequenceHandle, full_ids: list[int]) -> bool:
        """Graft the full prompt onto a parked/prefilling hold. Returns
        False — leaving the hold untouched, the caller cancels and falls
        back to a plain submit — when the graft would invalidate what was
        already prefilled (``full_ids`` does not extend the held prefix,
        e.g. history was windowed away after the hold was taken) or the
        extra KV pages can't be had."""
        if handle.finished or not handle.held:
            return False
        prefix = handle.prompt_ids
        if len(full_ids) <= len(prefix) or full_ids[: len(prefix)] != prefix:
            self.metrics.inc("finchat_partial_fallbacks_total")
            return False
        max_len = self.engine.max_pages_per_seq * self.engine.page_size
        if (len(full_ids) + handle.sampling.max_new_tokens > max_len
                and self.bounded_kv is None):
            self.metrics.inc("finchat_partial_fallbacks_total")
            return False
        if handle.slot >= 0:
            total = pages_needed(
                len(full_ids) + handle.sampling.max_new_tokens,
                self.engine.page_size,
            )
            if self.bounded_kv is not None:
                total = min(total, self.bounded_kv.budget_pages)
            extra = total - len(handle.page_list)
            if extra > 0:
                if total > self.engine.max_pages_per_seq or not self.allocator.can_allocate(extra):
                    self.metrics.inc("finchat_partial_fallbacks_total")
                    return False
                new_pages = self.allocator.allocate(handle.seq_id, extra)
                handle.page_list = handle.page_list + new_pages
                self.engine.set_page_table_rows({handle.slot: handle.page_list})
        handle.prompt_ids = list(full_ids)
        handle.history = list(full_ids)
        handle.held = False
        handle.grafted = True
        self.metrics.inc("finchat_partial_grafts_total")
        self._wakeup.set()
        return True

    def _tally_dispatch(self) -> None:
        """Count one enqueued device program (the PR 10 coexist
        attribution); engines whose compiled steps route quantized matmuls
        through the fused kernel also book it on
        finchat_quantmatmul_fused_dispatches_total — every model dispatch
        in that configuration reads packed weights."""
        self._dispatch_tally += 1
        if self._qm_fused:
            self.metrics.inc("finchat_quantmatmul_fused_dispatches_total")

    def _trace_dispatch(self, kind: str, rows: list, *,
                        ts: float | None = None,
                        dur: float | None = None) -> None:
        """Record one model dispatch in the trace ring (ISSUE 12): which
        ``[slot, trace_id, mode]`` rows rode it, so a request's exported
        timeline shows every dispatch that carried its rows even when many
        requests share one ragged dispatch. Host data only — the rows come
        from the membership/descriptor bookkeeping the round already built,
        so the event adds zero device syncs (finchat-lint R2). Callers
        guard with ``TRACER.enabled`` so the row list is never built for
        nothing."""
        TRACER.event("dispatch", ts=ts, dur=dur, track=self._trace_track,
                     args={"kind": kind, "n": self._dispatch_tally,
                           "quant": self._quant_label, "rows": rows})

    def _ring_routed(self, handle: SequenceHandle) -> bool:
        """Does this prefilling handle take the seq-sharded ring path this
        round (prefill_ring / prefill_ring_segment) rather than the chunked
        batch? The ONE routing predicate shared by _prefill_round and the
        mixed-step eligibility check, so they cannot drift. (A grafted hold
        stays chunked even if the full prompt is ring-length: both ring
        paths assume they scheduled the prompt from position 0.)

        Bounded-KV rows (ISSUE 15) NEVER ring-route: the seq-sharded
        steps write KV at absolute positions (no ``kv_gaps`` awareness)
        and a segment's write burst exceeds the eviction wave's chunk
        reserve — either would corrupt a budget-sized page list. Bounded
        long prompts ride chunked prefill instead (packed when decode
        coexists, split rounds otherwise), whose C-token rows bound
        activation memory the way the segment schedule did."""
        return (
            self.bounded_kv is None
            and self.engine._use_ring_prefill(len(handle.prompt_ids))
            and not handle.grafted
            and (handle.prefill_pos == 0 or handle.ring_path
                 or handle.prefix_entry is not None)
        )

    @staticmethod
    def _parked(handle: SequenceHandle) -> bool:
        """A parked overlap hold: prefix prefilled, awaiting extend_prompt
        — not prefill work, never part of a dispatched round. The ONE
        predicate shared by the round builders, the round-failure handler,
        and the idle check, so they cannot drift."""
        return handle.held and handle.prefill_pos >= len(handle.prompt_ids)

    def _prefill_work(self) -> bool:
        """True when a prefill round has something to advance — parked
        holds are NOT work, so an otherwise idle loop can sleep on the
        wakeup event instead of spinning."""
        return any(not self._parked(h) for h in self.prefilling)

    def _reap_stale_holds(self) -> None:
        now = time.perf_counter()
        for handle in list(self.prefilling):
            if handle.held and now > handle.held_deadline:
                logger.warning(
                    "partial hold %s expired after %.0fs without extend_prompt; "
                    "reclaiming its slot and pages", handle.seq_id, self.hold_ttl_s,
                )
                self.metrics.inc("finchat_partial_stale_reaps_total")
                self._evict(handle, "error", error="partial hold expired")
        for handle in list(self.pending):
            if handle.held and now > handle.held_deadline:
                self.metrics.inc("finchat_partial_stale_reaps_total")
                self.pending.remove(handle)
                handle.finished = True
                handle.span.finish()
                handle.events.put_nowait(
                    {"type": "error", "message": "partial hold expired"}
                )

    def register_prefix(self, prompt_ids: list[int]) -> int:
        """Prefill a shared prompt head ONCE and serve its KV to every
        later request that starts with it (reference parity argument: the
        system prompt — 1.3-4.5k byte tokens rendered per request,
        ``llm_agent.py:14-17`` — is identical for every conversation, so
        re-prefilling it per request is pure waste; this is what makes the
        TTFT target reachable under prompt-heavy RAG traffic).

        Shares whole pages only (a partially-filled page would be written
        by the owning sequence's appends); the remainder re-prefills per
        request. Returns the shared token length (0 = nothing registered).
        Call while the engine is idle (startup) or when a slot is free.
        """
        prep = self._prefix_prep(prompt_ids)
        if not isinstance(prep, tuple):
            return prep  # 0 (unregistrable) or an existing entry's length
        ids, shared_len, owner, pages, slot = prep
        if self._fabric_restore_head(ids, shared_len, pages):
            # warm-state fabric hit (ISSUE 17): the head's KV scattered
            # straight into the reserved pages — no prefill dispatches,
            # and the slot reservation was never used
            self.free_slots.append(slot)
            self._prefixes.append(_PrefixEntry(ids, pages, shared_len, owner))
            return shared_len
        try:
            self.engine.set_page_table_row(slot, pages)
            self.engine.prefill(slot, ids)  # fills exactly the shared pages
        except Exception:
            self.allocator.free(owner, pages)
            raise
        finally:
            try:
                self.engine.reset_slot(slot)
            except Exception as e:
                # the reservation must come back even when the reset (a
                # device op on a possibly-wedged engine) raises — an
                # escaping raise here would skip the slot return and mask
                # the original failure (finchat-lint R3)
                logger.error("slot reset failed after prefix prefill: %s", e)
            self.free_slots.append(slot)
        self._prefixes.append(_PrefixEntry(ids, pages, shared_len, owner))
        self._fabric_store_head(ids, pages)
        logger.info("prefix cache: registered %d shared tokens (%d pages)",
                    shared_len, len(pages))
        return shared_len

    def _fabric_restore_head(self, ids: list[int], shared_len: int,
                             pages: list[int]) -> bool:
        """Try to serve a head registration from the warm-state fabric
        (ISSUE 17): a hit scatters the fleet-shared snapshot into the
        reserved ``pages`` with one H2D copy instead of re-running the
        prefill. Counts hit/miss/refusal on THIS replica's metrics; a
        cross-mode snapshot is refused (scattering it would value-cast
        into garbage KV — the import_session_entry discipline)."""
        if self.fabric is None:
            return False
        snap = self.fabric.load_head(ids)
        if snap is None:
            self.metrics.inc("finchat_fabric_misses_total")
            return False
        from finchat_tpu.engine.session_cache import snap_kv_mode

        if snap_kv_mode(snap) != self.engine.kv_quant:
            self.metrics.inc("finchat_fabric_import_refused_total")
            return False
        try:
            t0 = time.perf_counter()
            self.engine.restore_pages(pages, snap)
        except Exception as e:
            logger.error("fabric head restore failed (%d tokens): %s — "
                         "falling back to local prefill", shared_len, e)
            return False
        self.metrics.inc("finchat_fabric_hits_total")
        self.metrics.observe("finchat_fabric_restore_seconds",
                             time.perf_counter() - t0)
        if TRACER.enabled:
            TRACER.event("fabric_hit", track="fabric",
                         args={"kind": "head", "tokens": shared_len})
        logger.info("prefix cache: head (%d shared tokens) restored from "
                    "the warm fabric", shared_len)
        return True

    def _fabric_store_head(self, ids: list[int], pages: list[int]) -> None:
        """Publish a freshly-prefilled head fleet-wide (best-effort: the
        fabric is an optimization, registration already succeeded)."""
        if self.fabric is None:
            return
        try:
            self.fabric.store_head(ids, self.engine.offload_pages(pages))
        except Exception as e:
            logger.error("fabric head publish failed: %s", e)

    def _prefix_prep(self, prompt_ids: list[int]):
        """Shared admission logic for both register_prefix variants: size
        the whole-page head, dedupe against live entries, reserve pages and
        an engine slot. Returns an int (0 = unregistrable / no capacity, or
        an already-registered entry's shared length) or the reservation
        tuple ``(ids, shared_len, owner, pages, slot)``."""
        page = self.engine.page_size
        n_pages = min(len(prompt_ids) // page, self.engine.max_pages_per_seq)
        if self.bounded_kv is not None:
            # bounded rows reference at most the SINK-sized lead of a
            # shared head (the admission clamp — head pages pin whole, so
            # anything past the sink could never be referenced): pages
            # registered beyond it would sit in the pool unread forever.
            # The verify_boundedkv drive caught the full-length variant
            # starving admission outright: two full prompt heads consumed
            # 87 of 96 pool pages and the bounded rows waited on pages
            # no one could ever free.
            n_pages = min(n_pages, self.bounded_kv.sink_pages)
        if n_pages <= 0:
            return 0
        shared_len = n_pages * page
        ids = list(prompt_ids[:shared_len])
        for entry in self._prefixes:
            if not entry.retired and entry.shared_len == shared_len and entry.ids == ids:
                return shared_len  # already registered
        for job in self._prefix_jobs:
            if job.shared_len == shared_len and job.ids == ids:
                return 0  # registration already in flight; caller may retry
        if not self.allocator.can_allocate(n_pages) or not self.free_slots:
            logger.warning("prefix cache: no pages/slot free; not registering")
            return 0
        owner = f"__prefix_{self._n_prefixes_ever}__"
        self._n_prefixes_ever += 1
        pages = self.allocator.allocate(owner, n_pages)
        slot = self.free_slots.pop()
        return ids, shared_len, owner, pages, slot

    async def register_prefix_async(self, prompt_ids: list[int]) -> int:
        """register_prefix for a RUNNING scheduler: the head prefills one
        chunk per prefill round instead of one monolithic inline prefill,
        so in-flight decode streams keep advancing (a decode step
        interleaves with every round — the midnight refresh stops being a
        multi-second stall for every live stream). Resolves to the shared
        token length, 0 on failure (registration is best-effort by
        contract, same as the sync path)."""
        if not self._running:
            return self.register_prefix(prompt_ids)  # engine idle: inline
        if self._rebuilding:
            # the trip-path rebuild is replacing the page table in a
            # worker thread; a row written now would be silently dropped
            # and the head would prefill against trash pages. Best-effort:
            # the refresh loop retries after the rebuild.
            return 0
        prep = self._prefix_prep(prompt_ids)
        if not isinstance(prep, tuple):
            return prep
        ids, shared_len, owner, pages, slot = prep
        if self._fabric_restore_head(ids, shared_len, pages):
            # fabric hit (ISSUE 17): one H2D scatter, no prefill rounds —
            # the chunked-job machinery (and its decode interleaving
            # rationale) is moot when nothing prefills
            self.free_slots.append(slot)
            self._prefixes.append(_PrefixEntry(ids, pages, shared_len, owner))
            return shared_len
        job = _PrefixJob(
            ids=ids, shared_len=shared_len, owner=owner, pages=pages,
            slot=slot, future=asyncio.get_running_loop().create_future(),
        )
        try:
            self.engine.set_page_table_row(slot, pages)
        except Exception:
            # return the reservation (slot + pages) — a transient device
            # error here must not leak them (the refresh loop retries)
            self.allocator.free(owner, pages)
            self.free_slots.append(slot)
            raise
        self._prefix_jobs.append(job)
        self._wakeup.set()
        return await job.future

    def _fail_prefix_job(self, job: _PrefixJob) -> None:
        self._prefix_jobs.remove(job)
        self.allocator.free(job.owner, job.pages)
        try:
            self.engine.reset_slot(job.slot)
        except Exception as e:
            # reset_slot is a device op and the device may be the very
            # reason this job is failing: log, don't propagate — the job
            # is already off _prefix_jobs, so an escaping exception would
            # skip the remaining jobs in unguarded callers
            # (_fail_prefill_round, stop) and kill the scheduler loop,
            # stranding their awaiters forever
            logger.error("reset_slot during prefix-job failure: %s", e)
        # the slot must come back and the future must resolve regardless,
        # or register_prefix_async's awaiter hangs (no later pass can
        # resolve a job that is no longer listed)
        self.free_slots.append(job.slot)
        if not job.future.done():
            job.future.set_result(0)

    def retire_prefixes(self) -> None:
        """Stop matching every registered prefix (the caller is about to
        register fresh heads — e.g. the embedded date rolled over). Pages
        free immediately when unreferenced, else when the last in-flight
        sequence using them releases (_release). Session-cache entries
        referencing a retired head are purged here too: post-rollover
        prompts diverge inside the head, so such an entry can never resume
        again — keeping it would pin the retired head's device pages for
        as long as an idle conversation stays under the host budget."""
        for entry in self._prefixes:
            entry.retired = True
        if self.session_cache is not None:
            self.session_cache.discard_if(
                lambda e: e.prefix_entry is not None and e.prefix_entry.retired
            )
        self._reap_prefixes()

    def _reap_prefixes(self) -> None:
        for entry in list(self._prefixes):
            if entry.retired and entry.refs == 0:
                self.allocator.free(entry.owner, entry.pages)
                self._prefixes.remove(entry)

    def _match_prefix(self, prompt_ids: list[int]) -> tuple["_PrefixEntry | None", int]:
        """Longest live registered prefix usable for this prompt: whole
        shared pages only, and at least one prompt token must remain to
        prefill (the commit needs real last-token logits)."""
        page = self.engine.page_size
        cap = ((len(prompt_ids) - 1) // page) * page
        best: tuple[_PrefixEntry | None, int] = (None, 0)
        for entry in self._prefixes:
            if entry.retired:
                continue
            usable = min(entry.shared_len, cap)
            if usable > best[1] and prompt_ids[:usable] == entry.ids[:usable]:
                best = (entry, usable)
        return best

    def cancel(self, handle: SequenceHandle) -> None:
        """Client went away (e.g. watchdog timeout): evict and free."""
        if handle.finished:
            return
        if handle.owner is not None and handle.owner is not self:
            # a fleet drain adopted this handle elsewhere: its slot/pages
            # live on the adopter now — evicting HERE with the adopter's
            # slot index would free an unrelated stream's slot
            handle.owner.cancel(handle)
            return
        if handle in self.pending:
            self.pending.remove(handle)
            self._finish(handle, "cancelled")
            return
        self._evict(handle, "cancelled")

    # --- internals ------------------------------------------------------
    @staticmethod
    def _remaining_new(handle: SequenceHandle) -> int:
        """Tokens this sequence may still generate — what its KV allocation
        must cover beyond the prompt. Equals ``max_new_tokens`` for a fresh
        submission; a preempted replay's prompt already CONTAINS its
        generated tokens, so sizing by the full budget would over-reserve
        by exactly that amount."""
        return max(1, handle.sampling.max_new_tokens - handle.generated)

    def _admission_pages(self, handle: SequenceHandle) -> int:
        """KV pages an admission must cover for this handle (shared head
        included): the COMPACTED prompt+budget requirement — a bounded
        replay's ``kv_gap`` tokens have no pages — capped at the bounded
        sink+window budget, where the eviction waves keep occupancy
        (ISSUE 15; the satellite bugfix: the pre-bounded sizing allocated
        and re-prefilled pages the policy would immediately evict)."""
        n = len(handle.prompt_ids) + self._remaining_new(handle) - handle.kv_gap
        total = pages_needed(n, self.engine.page_size)
        if self.bounded_kv is not None:
            total = min(total, self.bounded_kv.budget_pages)
        return total

    def _shed_expired(self) -> None:
        """Deadline load shedding: pending requests past their deadline are
        dropped PRE-admission with a structured retryable error — admitting
        them would spend prefill compute on an answer the caller has
        already given up on. Live streams are never shed: a preempted
        handle was admitted once and owes its client the rest of the
        stream, so it replays regardless of deadline."""
        if not self.pending:
            return
        now = time.perf_counter()
        for handle in list(self.pending):
            if (handle.deadline is not None and now > handle.deadline
                    and handle.generated == 0 and not handle.preempted):
                self.pending.remove(handle)
                self.metrics.inc("finchat_sheds_total")
                TRACER.anomaly("shed", handle.trace_id,
                               args={"seq_id": handle.seq_id,
                                     "replica": self.replica_id})
                handle.finished = True
                handle.span.finish()
                handle.events.put_nowait({
                    "type": "error",
                    "message": "deadline exceeded before admission; retry with backoff",
                    "code": "deadline_exceeded",
                    "retryable": True,
                })
        self.metrics.set_gauge("finchat_queue_depth", len(self.pending))

    def _prepare_pending(self) -> None:
        """Shed expired entries, then order the queue for admission:
        earliest deadline first (deadline-less entries last, FIFO among
        themselves) with a starvation guard — an entry that has waited
        longer than ``edf_starvation_seconds`` jumps ahead of deadline
        order (FIFO among the starved), so a stream of tight-deadline
        arrivals cannot starve a far-deadline request forever. A pure
        FIFO workload (no deadlines anywhere) is left untouched. Runs up
        to thrice per loop iteration (preemption plan, post-drain
        re-plan, admission) by design: the queue is bounded by
        max_queue_depth and timsort on an already-ordered deque is ~O(n),
        so re-establishing the order beats threading staleness flags
        through the loop."""
        self._shed_expired()
        if len(self.pending) <= 1 or all(h.deadline is None for h in self.pending):
            return
        now = time.perf_counter()

        def key(h: SequenceHandle):
            if now - h.submitted_at > self.edf_starvation_s:
                return (0, 0.0)  # starved: ahead of EDF, FIFO (stable sort)
            return (1, h.deadline if h.deadline is not None else float("inf"))

        self.pending = deque(sorted(self.pending, key=key))

    def _admit(self) -> None:
        self._prepare_pending()
        admitted: dict[int, list[int]] = {}
        ctx_rows: dict[int, int] = {}
        gap_rows: dict[int, int] = {}
        page = self.engine.page_size
        while self.pending and self.free_slots:
            handle = self.pending[0]
            total = self._admission_pages(handle)
            if total > self.engine.max_pages_per_seq:
                break  # head-of-line waits for pages (rejected at submit anyway)
            bsnap = handle.bounded_snap
            if bsnap is not None:
                # bounded preempt-replay (ISSUE 15 satellite): restore the
                # SURVIVING sink+window pages byte-identically from the
                # preemption snapshot and re-prefill only the residual
                # tail. No prefix/session matching — the snapshot already
                # holds the head region, and the evicted tokens between
                # sink and window have no pages to match against.
                ring = False
                session_eligible = False
                entry, shared_len = None, 0
                s_entry, s_matched = None, 0
                head_pages: list[int] = []
                ref_entry = None
                n_restore = -(-handle.bounded_snap_tokens // page)
                resume_pos = handle.bounded_snap_tokens + handle.kv_gap
                restore_snap = bsnap
                resume_gap = handle.kv_gap
            else:
                # a MONOLITHIC ring prefill assumes position 0, so a prefix
                # hit would force such a prompt onto the chunked path —
                # trading away the activation-memory safety the ring exists
                # for; skip matching there. SEGMENTED ring (ring_segment_
                # tokens > 0) composes: the first segment simply starts at
                # shared_len with the cached head folded as prefix, so long
                # RAG prompts keep the system-head TTFT saving.
                ring = (self.bounded_kv is None
                        and self.engine._use_ring_prefill(len(handle.prompt_ids)))
                if ring and self.engine.ring_segment_tokens() == 0:
                    entry, shared_len = None, 0
                else:
                    entry, shared_len = self._match_prefix(handle.prompt_ids)
                if (self.bounded_kv is not None
                        and shared_len > self.bounded_kv.sink_tokens):
                    # bounded rows reference at most the SINK-sized lead
                    # of a shared head: head pages pin whole (they are
                    # refcounted read-only references — the eviction wave
                    # cannot free them), so a head deeper than the sink
                    # would pin more pages than the budget can ever make
                    # room around (the verify_boundedkv drive reproduced
                    # exactly that: a 25-page system head under a 14-page
                    # budget left nothing evictable). The sink IS the
                    # bounded home of the constant head; the rest
                    # re-prefills and evicts like any other context.
                    shared_len = self.bounded_kv.sink_tokens
                # session tier: a per-conversation resume takes over whenever
                # it matches deeper than the constant shared head (it contains
                # the head as its own leading pages). Ring-eligible prompts
                # keep the SP prefill path untouched — only the head
                # composition above applies there.
                s_entry, s_matched = (None, 0)
                session_eligible = (
                    self.session_cache is not None and handle.conversation_id and not ring
                )
                if session_eligible:
                    if self.session_cache.get(handle.conversation_id) is None:
                        # RAM miss falls through to the disk tier (ISSUE 7):
                        # the record re-enters through import_session_entry
                        # (head re-link + refcount), then match() below applies
                        # the usual token comparison and divergence truncation
                        self._restore_session_from_disk(handle.conversation_id)
                    s_entry, s_matched = self.session_cache.match(
                        handle.conversation_id, handle.prompt_ids
                    )
                    if s_entry is None or s_matched <= shared_len:
                        s_entry, s_matched = None, 0
                    if s_entry is not None and self.bounded_kv is None:
                        if s_entry.kv_gap:
                            # a gapped entry (written under a bounded
                            # policy, arriving here via disk restore or a
                            # fleet import after the policy was turned
                            # off) has no eviction machinery to live
                            # under on this engine — cold-start instead
                            s_entry, s_matched = None, 0
                    elif (s_entry is not None
                            and (s_entry.prefix_len > self.bounded_kv.sink_tokens
                                 or pages_needed(s_matched - s_entry.kv_gap, page)
                                 > self.bounded_kv.budget_pages)):
                        # a resume whose head reference or restored pages
                        # exceed the bounded budget cannot be laid out
                        # (entries written by THIS bounded engine fit by
                        # construction; pre-policy or unbounded-sibling
                        # imports may not) — cold-start instead
                        s_entry, s_matched = None, 0
                if s_entry is not None:
                    # shared head pages referenced (never copied); the pages
                    # past the head restore from the host snapshot below
                    head_pages = s_entry.prefix_pages[: min(s_matched, s_entry.prefix_len) // page]
                    n_restore = s_entry.own_pages_for(s_matched, page)
                    ref_entry = s_entry.prefix_entry if head_pages else None
                    resume_pos = s_matched
                    restore_snap = s_entry.snap
                    # a bounded entry resumes with its sink+window intact
                    # (ISSUE 15): the gap travels with the snapshot and the
                    # slot picks up decode exactly where retirement left it
                    resume_gap = s_entry.kv_gap
                else:
                    head_pages = entry.pages[: shared_len // page] if entry else []
                    n_restore = 0
                    ref_entry = entry
                    resume_pos = shared_len
                    restore_snap = None
                    resume_gap = 0
            need = total - len(head_pages)
            if not self.allocator.can_allocate(need):
                break  # head-of-line waits for pages
            self.pending.popleft()
            slot = self.free_slots.pop()
            pages = self.allocator.allocate(handle.seq_id, need)
            if n_restore:
                try:
                    inject("session.restore", seq_id=handle.seq_id)
                    with Timer(self.metrics, "finchat_session_restore_seconds"):
                        self.engine.restore_pages(pages[:n_restore], restore_snap)
                    self.metrics.inc("finchat_session_cache_restored_tokens_total",
                                resume_pos)
                except Exception as e:
                    # a failed restore must not kill the stream OR leak the
                    # allocation: return the pages cleanly and fall back to
                    # a cold start through the plain shared-prefix plan
                    logger.error("session cache restore failed for %s: %s",
                                 handle.seq_id, e)
                    self.allocator.free(handle.seq_id, pages)
                    if bsnap is not None:
                        # bounded replay demotes to a full-history
                        # recompute: the surviving-page bytes are gone, so
                        # the gap resets and the whole history re-prefills
                        # (post-window tokens may diverge — counted)
                        handle.bounded_snap = None
                        handle.bounded_snap_tokens = 0
                        handle.kv_gap = 0
                        self.metrics.inc(
                            "finchat_boundedkv_recompute_fallbacks_total")
                        total = self._admission_pages(handle)
                    s_entry = None  # the admission below is the prefix plan
                    resume_gap = 0
                    head_pages = entry.pages[: shared_len // page] if entry else []
                    ref_entry = entry
                    resume_pos = shared_len
                    need = total - len(head_pages)
                    n_restore = 0
                    if not self.allocator.can_allocate(need):
                        # cold plan needs more pages than the resume did:
                        # requeue at the head and wait like any other
                        self.pending.appendleft(handle)
                        self.free_slots.append(slot)
                        break
                    pages = self.allocator.allocate(handle.seq_id, need)
                else:
                    if bsnap is not None:
                        handle.bounded_snap = None
                        handle.bounded_snap_tokens = 0
            if session_eligible:
                # counted only for an admission that actually went through
                # its plan — a page-starved head-of-line retry or a failed
                # restore (demoted to a cold start above) must not inflate
                # the hit rate
                self.metrics.inc("finchat_session_cache_hits_total" if s_entry is not None
                            else "finchat_session_cache_misses_total")
            # shared/restored head pages lead (logical pages 0..): the slot
            # reads them read-only — its own writes all land at positions >=
            # resume_pos, i.e. in its own pages
            admitted[slot] = head_pages + pages
            handle.page_list = admitted[slot]
            handle.shared_len = len(head_pages) * page
            handle.resumed_len = resume_pos if s_entry is not None else 0
            handle.kv_gap = resume_gap
            handle.kv_ctx = resume_pos
            if resume_gap:
                gap_rows[slot] = resume_gap
            if ref_entry is not None:
                ref_entry.refs += 1
                handle.prefix_entry = ref_entry
            if resume_pos:
                ctx_rows[slot] = resume_pos
                handle.prefill_pos = resume_pos
                if s_entry is None and bsnap is None:
                    self.metrics.inc("finchat_prefix_hits_total")
                    self.metrics.inc("finchat_prefix_tokens_saved_total", shared_len)
            handle.slot = slot
            handle.span.mark("admitted")
            if handle.constraint is None:
                self._temperature[slot] = handle.sampling.temperature
                self._top_p[slot] = handle.sampling.top_p
                self._top_k[slot] = handle.sampling.top_k
            # constrained slots keep the non-truncating defaults: their
            # device-sampled token is always discarded for the host-side
            # grammar pick (_constrained_pick), and a truncating top_p/top_k
            # here would knock the WHOLE batch off the sampler's exact
            # full-vocab fast path (sampler.py sample())
            self.prefilling.append(handle)
            logger.debug("admitted %s into slot %d (%d pages)", handle.seq_id, slot, need)
        if admitted:
            # ONE device update for the whole admission burst — per-slot
            # eager updates cost ~15 ms each on remote-tunnel backends
            self.engine.set_page_table_rows(admitted)
            if ctx_rows:
                self.engine.set_context_lens_rows(ctx_rows)
            if gap_rows:
                self.engine.set_kv_gap_rows(gap_rows)
            self.metrics.set_gauge("finchat_queue_depth", len(self.pending))

    def _finish(self, handle: SequenceHandle, reason: str) -> None:
        handle.finished = True
        handle.span.finish()
        handle.events.put_nowait({"type": "done", "reason": reason})

    def _release(self, handle: SequenceHandle) -> None:
        if handle.slot >= 0:
            pages = self.allocator.owned_by(handle.seq_id)
            if pages:
                self.allocator.free(handle.seq_id, pages)
            try:
                self.engine.reset_slot(handle.slot)
            except Exception as e:
                # survivable (finchat-lint R3, the _fail_prefix_job bug
                # class): a raising device op here would skip the slot
                # return and the prefix-ref release below, leaking the
                # slot forever — and _release's callers (_evict via
                # watchdog cancel, stop) don't expect a raise. Admission
                # rewrites the page-table row and context length anyway;
                # a wedged device trips the breaker.
                logger.error("slot reset failed releasing %s: %s",
                             handle.seq_id, e)
            self.decoding.pop(handle.slot, None)
            if handle in self.prefilling:
                self.prefilling.remove(handle)
            # restore non-truncating defaults: the sampler's exact full-vocab
            # fast path keys on ALL slots' params, so a freed slot must not
            # keep a dead request's top_p/top_k (sampler.py sample())
            self._temperature[handle.slot] = 0.0
            self._top_p[handle.slot] = 1.0
            self._top_k[handle.slot] = 0
            self.free_slots.append(handle.slot)
            handle.slot = -1
            if handle.prefix_entry is not None:
                handle.prefix_entry.refs -= 1
                handle.prefix_entry = None
                self._reap_prefixes()

    def _session_drop(self, entry) -> None:
        """Session-cache ``on_drop`` hook (LRU eviction, replacement, or
        divergence truncation to nothing): release the entry's reference on
        its shared-prefix head so retirement can finally free those pages."""
        if entry.prefix_entry is not None:
            entry.prefix_entry.refs -= 1
            entry.prefix_entry = None
            self._reap_prefixes()

    def _maybe_offload(self, handle: SequenceHandle) -> None:
        """Snapshot a normally-retiring sequence's KV into the session cache
        (device→host) BEFORE its pages are freed. Whole pages only — the
        matcher is page-granular, so a partial tail page could never be
        resumed. The D2H copy blocks (engine.offload_pages) by design: the
        pages are returned to the allocator the moment this returns, and an
        async copy would race the next sequence's writes into them."""
        cache = self.session_cache
        if cache is None or not handle.conversation_id or handle.slot < 0:
            return
        if handle.prefill_pos < len(handle.prompt_ids) or not handle.generated:
            return  # never reached decode; nothing coherent to keep
        page = self.engine.page_size
        # KV-cached tokens: prompt + generated minus the last delivered
        # token, whose KV append belongs to the step that was never
        # consumed. Bounded rows (ISSUE 15) count in COMPACTED coordinates
        # — the snapshot holds only the SURVIVING sink+window pages, and
        # the entry records the gap so a restore resumes with them intact.
        gap = handle.kv_gap
        context = len(handle.history) - 1 - gap
        n_tok = (context // page) * page  # compacted, page-whole
        if n_tok <= 0:
            return
        shared = min(handle.shared_len, n_tok)
        # a shared head without a refcounted entry would store device page
        # ids nobody protects — use-after-free; admission guarantees the pair
        assert shared == 0 or handle.prefix_entry is not None
        # incremental offload: pages covering [shared, resumed_len) were
        # restored from the previous entry's snapshot at admission and never
        # rewritten (the slot's writes start at resumed_len), so reuse those
        # host bytes — without this every retirement re-copies the WHOLE
        # history D2H and the per-turn cost grows linearly again. Gapped
        # rows skip the splice (the page↔token index math shifts under the
        # gap, and a bounded snapshot is at most sink+window pages — the
        # re-copy is O(budget), not O(history), by construction).
        prev = cache.get(handle.conversation_id)
        reuse_pages = 0
        if (gap == 0 and prev is not None and prev.snap is not None
                and prev.kv_gap == 0
                and prev.prefix_len == shared and handle.resumed_len > shared):
            m = min(handle.resumed_len, n_tok, prev.n_tokens)
            reuse_pages = (m - shared) // page
            if reuse_pages and not np.array_equal(
                prev.token_ids[shared : shared + reuse_pages * page],
                np.asarray(handle.history[shared : shared + reuse_pages * page], np.int32),
            ):
                reuse_pages = 0  # entry replaced by a different stream since
        own_ids = handle.page_list[shared // page + reuse_pages : n_tok // page]
        try:
            inject("session.offload", seq_id=handle.seq_id)
            with Timer(self.metrics, "finchat_session_offload_seconds"):
                snap_new = self.engine.offload_pages(own_ids) if own_ids else None
        except Exception as e:  # cache is an optimization; never fail eviction
            logger.error("session cache offload failed for %s: %s", handle.seq_id, e)
            return
        from finchat_tpu.engine.session_cache import SessionEntry, concat_snaps

        entry = SessionEntry(
            conversation_id=handle.conversation_id,
            # token ids cover the ABSOLUTE span [0, n_tok + gap): the
            # evicted tokens' ids must still match the next turn's prompt
            # for the surviving KV to be valid (match() compares them all)
            token_ids=np.asarray(handle.history[: n_tok + gap], np.int32),
            prefix_entry=handle.prefix_entry if shared else None,
            prefix_pages=list(handle.page_list[: shared // page]),
            prefix_len=shared,
            snap=concat_snaps(prev.snap if reuse_pages else None, reuse_pages, snap_new),
            kv_gap=gap,
            # a gapped handle can retire on an UNBOUNDED engine (a fleet
            # sibling adopted its preempt snapshot): record sink 0 there —
            # nothing is salvageable without the policy's sink geometry
            kv_sink=(self.bounded_kv.sink_tokens
                     if gap and self.bounded_kv is not None else 0),
        )
        # reference the shared head BEFORE put(): put may drop an older
        # entry holding the same (possibly retired) head, and a momentary
        # refs==0 would free pages the new entry is about to point at
        if entry.prefix_entry is not None:
            entry.prefix_entry.refs += 1
        if cache.put(entry):
            self.metrics.inc("finchat_session_cache_offloaded_pages_total", len(own_ids))
        elif entry.prefix_entry is not None:
            entry.prefix_entry.refs -= 1
            self._reap_prefixes()

    def _evict(self, handle: SequenceHandle, reason: str, error: str | None = None) -> None:
        if error is None and reason in ("eos", "length"):
            # normal retirement: the sequence's KV is a coherent prefix of
            # this conversation's next turn — offload before pages free
            self._maybe_offload(handle)
        self._release(handle)
        if error is not None:
            handle.finished = True
            handle.span.finish()
            handle.events.put_nowait({"type": "error", "message": error})
        else:
            self._finish(handle, reason)

    # --- bounded-KV serving (ISSUE 15; kv_cache.BoundedKVPolicy) --------
    def _bounded_pinned_pages(self, handle: SequenceHandle) -> int:
        """Unevictable leading pages of a bounded row: the attention sink,
        widened to the whole shared-prefix head when the head is larger
        (head pages are refcounted read-only references — dropping one
        from this row's list without freeing it would just shrink the
        sink below the policy, so the head pins whole: an effectively
        larger sink for head-sharing rows)."""
        return max(self.bounded_kv.sink_pages,
                   handle.shared_len // self.engine.page_size)

    def _bounded_evict_wave(self) -> None:  # finchat-lint: hot
        """Page-granular eviction for bounded rows: between dispatches,
        any row whose NEXT dispatch would not fit its page list evicts the
        oldest post-sink page(s) — the pages leave the row's logical page
        list (survivors shift down one logical slot; physically nothing
        moves), return to the pool, and fresh pages extend the tail for
        the incoming writes. ``kv_gap`` grows by a page per eviction and
        the engine mirror (``state.kv_gaps``) updates in the same wave, so
        every enqueued dispatch sees a table and gap that agree — device
        stream order keeps in-flight programs reading the table they were
        dispatched against, which is why no drain is needed.

        The wave is host-deterministic: its sole inputs are each row's
        ``kv_ctx`` (the dispatch-time context mirror — exactly the next
        dispatch's write position, whatever the pipeline depth or capture
        state) and fixed per-config reserve constants, so the gap a token
        is computed under is a pure function of its position. That is
        what makes the free-run capture's gap schedule identical to the
        host-stepped one (captures are capped at the next eviction
        boundary — ``_bounded_freerun_cap`` — exactly like budget stops)
        and a preempt-replay's identical to the uninterrupted run's."""
        bp = self.bounded_kv
        if bp is None:
            return
        page = self.engine.page_size
        chunk = self.engine.engine_cfg.prefill_chunk
        pt_rows: dict[int, list[int]] = {}
        gap_rows: dict[int, int] = {}
        evicted_total = 0
        for handle in list(self.prefilling) + list(self.decoding.values()):
            if handle.slot < 0 or handle.finished or self._parked(handle):
                continue
            # the reserve is exactly what the next dispatch WRITES for
            # this row: a prefill chunk, or ONE decode token — fused
            # multi-token spans (decode_loop tails, spec verify blocks)
            # are gated to never cross the eviction boundary
            # (_bounded_span_room), so the only dispatch that ever
            # reaches the boundary writes a single token. Reserving the
            # full fused burst here would evict one dispatch EARLY
            # whenever the gate demotes at the boundary — and a replay,
            # whose residual chunk regroups those positions, would then
            # see a different gap schedule than the uninterrupted run
            # (the byte-identity contracts pin this).
            prefilling = handle.prefill_pos < len(handle.prompt_ids)
            if prefilling:
                remaining = len(handle.prompt_ids) - handle.prefill_pos
                incoming = min(chunk, remaining)
            else:
                incoming = 1
            try:
                e = bp.plan_eviction(
                    handle.kv_ctx - handle.kv_gap, incoming,
                    len(handle.page_list), self._bounded_pinned_pages(handle),
                )
            except PageAllocationError as err:
                # infeasible plan = a policy/config violation for THIS row
                # (e.g. a shared head pinning almost the whole budget);
                # per-sequence isolation, the others keep serving
                logger.error("bounded eviction infeasible for %s: %s",
                             handle.seq_id, err)
                self._evict(handle, "error", error=str(err))
                continue
            if not e:
                continue
            if handle.kv_gap == 0:
                self.metrics.inc("finchat_boundedkv_bounded_sessions_total")
            pin = self._bounded_pinned_pages(handle)
            victims = handle.page_list[pin : pin + e]
            handle.page_list = (
                handle.page_list[:pin] + handle.page_list[pin + e :]
            )
            self.allocator.free(handle.seq_id, victims)
            # keep capacity constant: fresh tail pages for the incoming
            # writes (the LIFO free list usually hands the same physical
            # pages straight back)
            handle.page_list = handle.page_list + self.allocator.allocate(
                handle.seq_id, e
            )
            handle.kv_gap += e * page
            handle.kv_gap_pos = handle.kv_ctx
            pt_rows[handle.slot] = handle.page_list
            gap_rows[handle.slot] = handle.kv_gap
            evicted_total += e
        if pt_rows:
            self.engine.set_page_table_rows(pt_rows)
            self.engine.set_kv_gap_rows(gap_rows)
            self.metrics.inc("finchat_boundedkv_evicted_pages_total",
                             evicted_total)
            if TRACER.enabled:
                TRACER.event("boundedkv_evict", track=self._trace_track,
                             args={"pages": evicted_total,
                                   "slots": sorted(pt_rows)})

    def _bounded_span_room(self, handle: SequenceHandle) -> int:
        """Tokens this row may still write before its next eviction
        boundary (``page-list capacity + kv_gap``). Fused multi-token
        dispatches — decode_loop blocks/tails, spec verify spans — must
        FIT this room: a span crossing the boundary would give its tail
        tokens the pre-eviction gap, and since a preempt-replay (or a
        capture) regroups spans on a shifted grid, the gap a given token
        sees would stop being a pure function of its position — breaking
        the byte-identity contracts. Unbounded rows have unlimited room
        by construction (capacity covers prompt + max_new)."""
        if self.bounded_kv is None:
            return 1 << 30
        boundary = (len(handle.page_list) * self.engine.page_size
                    + handle.kv_gap)
        return max(0, boundary - handle.kv_ctx)

    def _bounded_freerun_cap(self) -> int:
        """Rounds the next capture may free-run before some bounded row
        needs an eviction wave — the capture-boundary staging of eviction
        (like budget stops): within the cap the staged writes fit every
        row's current page list, so the captured rounds see exactly the
        gap schedule the host-stepped loop would."""
        bp = self.bounded_kv
        cap = self.freerun_rounds
        if bp is None:
            return cap
        chunk = self.engine.engine_cfg.prefill_chunk
        decode_burst = 1 + max(self.loop_depth - 1, self.spec_k)
        for handle in list(self.prefilling) + list(self.decoding.values()):
            if handle.slot < 0 or handle.finished or self._parked(handle):
                continue
            room = self._bounded_span_room(handle)
            # a prefill row may flip to decode mid-capture; the larger of
            # a chunk and a decode burst bounds both roles' per-round
            # writes, so it is the conservative deterministic divisor
            prefilling = handle.prefill_pos < len(handle.prompt_ids)
            per_round = max(chunk, decode_burst) if prefilling else decode_burst
            cap = min(cap, max(1, room // max(1, per_round)))
        return cap

    # --- resilience plane (ISSUE 5; ROBUSTNESS.md) ----------------------
    def _preempt(self, handle: SequenceHandle, *, for_rebuild: bool = False) -> None:
        """Recompute preemption: free the victim's slot and KV pages but
        keep its prompt AND already-generated tokens on the handle. The
        replay plan sets ``prompt_ids = history`` (prompt + delivered
        tokens), so re-admission re-prefills exactly the stream so far —
        composing with the shared-prefix and session caches, which makes
        the replay usually cheap — and the commit at replay-prefill
        completion samples precisely the NEXT token: zero duplicate or
        dropped tokens on the stream (greedy replay is byte-identical;
        tests/test_resilience.py pins it). Any token of the victim still
        riding an in-flight dispatch is discarded at consume time
        (``handle.slot`` is -1 by then) and recomputed by the replay.

        Used for page pressure (the latest-deadline victim yields instead
        of the earliest-deadline candidate stalling head-of-line) and as
        the circuit breaker's recovery primitive. ``for_rebuild`` skips
        per-slot device resets — the whole device state is about to be
        replaced and the engine may be wedged."""
        if handle.finished:
            return
        slot = handle.slot
        if slot >= 0:
            if self.bounded_kv is not None and handle.kv_gap:
                # bounded rows preempt by SNAPSHOT, not recompute (the
                # ISSUE 15 satellite bugfix): the surviving window's KV
                # cannot be recomputed byte-identically from the token
                # stream (window keys attended to tokens that are gone),
                # and the old sizing re-prefilled — and re-allocated —
                # pages the policy would immediately evict. Gather the
                # surviving compacted pages to host BEFORE they free; the
                # replay restores them and re-prefills only the tail.
                self._bounded_preempt_snapshot(handle, for_rebuild)
            pages = self.allocator.owned_by(handle.seq_id)
            if pages:
                self.allocator.free(handle.seq_id, pages)
            self.decoding.pop(slot, None)
            if handle in self.prefilling:
                self.prefilling.remove(handle)
            self._temperature[slot] = 0.0
            self._top_p[slot] = 1.0
            self._top_k[slot] = 0
            self.free_slots.append(slot)
            handle.slot = -1
            if handle.prefix_entry is not None:
                handle.prefix_entry.refs -= 1
                handle.prefix_entry = None
                if not for_rebuild:
                    self._reap_prefixes()
            if not for_rebuild:
                try:
                    self.engine.reset_slot(slot)
                except Exception as e:
                    # survivable: admission rewrites the page-table row and
                    # context length; a wedged device trips the breaker
                    logger.error("slot reset failed preempting %s: %s",
                                 handle.seq_id, e)
        elif handle in self.pending:
            return  # already queued; nothing to preempt
        handle.prompt_ids = list(handle.history)
        handle.prefill_pos = 0
        handle.kv_ctx = 0
        handle.page_list = []
        handle.shared_len = 0
        handle.resumed_len = 0
        handle.ring_path = False
        handle.grafted = False
        handle.preempted += 1
        handle.epoch += 1  # invalidate stale dispatch-membership snapshots
        # preempted sequences re-admit ahead of new load: they are live
        # streams mid-answer, and _prepare_pending's EDF ordering applies
        # on top when deadlines are in play
        self.pending.appendleft(handle)
        self.metrics.inc("finchat_preemptions_total")
        if TRACER.enabled and handle.trace_id is not None:
            TRACER.event("preempt", handle.trace_id, track=self._trace_track,
                         args={"preempted": handle.preempted,
                               "for_rebuild": for_rebuild})
        self.metrics.set_gauge("finchat_queue_depth", len(self.pending))
        self._wakeup.set()

    def _bounded_preempt_snapshot(self, handle: SequenceHandle,
                                  for_rebuild: bool) -> None:
        """Snapshot a bounded row's surviving pages for its replay (see
        ``_preempt``). ``for_rebuild`` preempts run against a possibly
        wedged device — no snapshot is attempted; the row demotes to a
        full-history recompute (gap reset; post-window tokens may diverge
        from the uninterrupted stream, counted as a recompute fallback).

        The snapshot covers the EXACT compacted context — the partial
        tail page included, not just whole pages: the tail tokens' KV was
        computed against surviving pages that may since have been evicted,
        so RE-computing them at replay would attend a different set and
        break the byte-identity contract. Only the last history token
        (whose KV belongs to the never-consumed step) re-prefills.

        Identity caveat: the contract assumes the preempt was taken at a
        CONSUMED boundary (``kv_gap_pos <= len(history) - 1``) — true for
        the page-pressure path, which drains the in-flight dispatch
        before executing its plan. A mid-flight preempt that lands inside
        an eviction transition (breaker/whole-round-failure paths, which
        carry no identity contract) recomputes the pending boundary token
        under the newer gap — a valid bounded decode, one token per
        page-crossing wide."""
        page = self.engine.page_size
        snap_tokens = len(handle.history) - 1 - handle.kv_gap
        if not for_rebuild and snap_tokens > 0:
            try:
                n = -(-snap_tokens // page)  # whole pages incl. partial tail
                handle.bounded_snap = self.engine.offload_pages(
                    handle.page_list[:n]
                )
                handle.bounded_snap_tokens = snap_tokens
                return
            except Exception as e:
                logger.error("bounded preempt snapshot failed for %s: %s",
                             handle.seq_id, e)
        handle.bounded_snap = None
        handle.bounded_snap_tokens = 0
        handle.kv_gap = 0
        self.metrics.inc("finchat_boundedkv_recompute_fallbacks_total")

    def _preemption_plan(self) -> list[SequenceHandle]:
        """Page-pressure preemption policy: when the earliest-deadline
        pending request cannot be admitted for lack of KV pages, return
        the latest-deadline decoding victims (deadline-less = lowest
        priority) whose deadlines are STRICTLY later than the candidate's
        and whose pages would make the admission fit. Strict deadline
        order makes the policy livelock-free: a victim can never in turn
        preempt the sequence it yielded to. Returns [] when preemption is
        off, nothing is stalled, no eligible victim exists, or even
        preempting every eligible victim would not free enough pages
        (preempting without admitting would be pure loss). Planning only —
        the loop drains the in-flight dispatch before executing the plan,
        so no freed page can still be a target of queued device writes."""
        if not self.preemption_enabled or not self.pending or not self.decoding:
            return []
        self._prepare_pending()
        if not self.pending:
            return []
        cand = self.pending[0]
        if cand.deadline is None:
            return []  # only deadline urgency justifies evicting live KV
        page = self.engine.page_size
        total = self._admission_pages(cand)
        if total > self.engine.max_pages_per_seq:
            return []
        # prefix-aware need (same plan _admit will compute): an admission
        # a shared head would satisfy must not trigger a preemption
        ring = self.engine._use_ring_prefill(len(cand.prompt_ids))
        if ring and self.engine.ring_segment_tokens() == 0:
            shared_len = 0
        else:
            _, shared_len = self._match_prefix(cand.prompt_ids)
        need = total - shared_len // page
        if self.free_slots and self.allocator.can_allocate(need):
            return []  # admissible as-is; _admit will take it

        def eff(h: SequenceHandle) -> float:
            return h.deadline if h.deadline is not None else float("inf")

        pool = [h for h in self.decoding.values()
                if not h.finished and eff(h) > cand.deadline]
        pool.sort(key=eff, reverse=True)
        victims: list[SequenceHandle] = []
        freeable = self.allocator.free_count
        for v in pool:
            victims.append(v)
            freeable += len(self.allocator.owned_by(v.seq_id))
            if freeable >= need:
                return victims
        return []

    # --- fleet surface (serve/fleet.py; ISSUE 6) ------------------------
    def adopt(self, handle: SequenceHandle) -> bool:
        """Admit a handle drained from a sibling replica. The handle
        arrives device-free — ``_preempt`` normalized it (prompt_ids =
        full history, slot -1, no pages, epoch bumped past every stale
        membership snapshot) — and its ``events`` queue travels WITH it,
        so the original consumer keeps streaming with no seam: the next
        token it sees is exactly the next token of the stream. Live
        streams (already-delivered tokens) jump the queue the same way
        local preemption replays do — they are always adopted, exactly as
        a local preempt-replay never counts against the bound. A
        NEVER-admitted handle is plain queued load wearing a drain coat:
        it honors ``max_queue_depth`` like any fresh submit (refused →
        False), or a victim's give-up would transplant its whole backlog
        past the sibling's backpressure bound and lock out new clients
        with OverloadedError until it drains. Returns whether the handle
        was taken."""
        if handle.finished:
            return True
        live = bool(handle.preempted or handle.generated)
        if (not live and self.max_queue_depth > 0
                and len(self.pending) >= self.max_queue_depth):
            return False
        handle.owner = self  # cleanup (cancel) must target THIS scheduler now
        if TRACER.enabled and handle.trace_id is not None:
            TRACER.event("adopt", handle.trace_id, track=self._trace_track,
                         args={"live": live})
        if live:
            self.pending.appendleft(handle)
        else:
            self.pending.append(handle)
        self.metrics.set_gauge("finchat_queue_depth", len(self.pending))
        self._wakeup.set()
        return True

    def export_session(self, conversation_id: str | None) -> dict | None:
        """Portable image of a conversation's session-cache entry for
        cross-replica handoff (device pages dropped; see
        SessionKVCache.export_entry)."""
        if self.session_cache is None or not conversation_id:
            return None
        return self.session_cache.export_entry(conversation_id)

    def import_session_entry(self, payload: dict | None, *,
                             spill: bool = True) -> bool:
        """Adopt a sibling's exported session-cache entry (drain handoff /
        lazy route-time migration). The export carries no device pages —
        an entry whose KV rode a shared-prefix head re-links against THIS
        scheduler's own live registration of the same head (every fleet
        replica registers the same prompt heads), refcounted exactly like
        a local offload. No matching live head → the entry is refused
        (counted) and the conversation resumes cold: KV positions are
        absolute, so the snapshot's pages are meaningless without the
        head KV below them."""
        if payload is None or self.session_cache is None:
            return False
        from finchat_tpu.engine.session_cache import snap_kv_mode

        if (payload.get("snap") is not None
                and snap_kv_mode(payload["snap"]) != self.engine.kv_quant):
            # cross-MODE snapshot (a handoff or disk record from an engine
            # serving the other page-pool dtype): scattering it would
            # value-cast into garbage KV — refuse, count the dequant
            # fallback, resume cold (kv_cache.scatter_pages_device is the
            # raising last line behind this counted gate)
            logger.warning(
                "session import for %s refused: snapshot kv mode %r vs "
                "engine kv_quant %r — cold start",
                payload.get("conversation_id"),
                snap_kv_mode(payload["snap"]), self.engine.kv_quant,
            )
            self.metrics.inc("finchat_quant_dequant_fallbacks_total")
            return False
        prefix_len = int(payload["prefix_len"])
        entry_ref = None
        pages: list[int] = []
        if prefix_len > 0:
            page = self.engine.page_size
            if prefix_len % page:
                # fleet-LEVEL series: unlabeled like the rest of the
                # finchat_fleet_* family (one reader sees all refusals)
                METRICS.inc("finchat_fleet_session_import_refused_total")
                return False
            head_ids = [int(t) for t in payload["token_ids"][:prefix_len]]
            for cand in self._prefixes:
                if (not cand.retired and cand.shared_len >= prefix_len
                        and cand.ids[:prefix_len] == head_ids):
                    entry_ref = cand
                    pages = cand.pages[: prefix_len // page]
                    break
            if entry_ref is None:
                # fleet-LEVEL series: unlabeled like the rest of the
                # finchat_fleet_* family (one reader sees all refusals)
                METRICS.inc("finchat_fleet_session_import_refused_total")
                return False
            # reference BEFORE put (put may drop an older entry holding the
            # same head — a momentary refs==0 would free it), exactly the
            # _maybe_offload discipline
            entry_ref.refs += 1
        ok = self.session_cache.import_entry(
            payload, prefix_entry=entry_ref, prefix_pages=pages, spill=spill
        )
        if not ok and entry_ref is not None:
            entry_ref.refs -= 1
            self._reap_prefixes()
        return ok

    # --- durability plane (ISSUE 7; ROBUSTNESS.md §5) --------------------
    def _restore_session_from_disk(self, conversation_id: str) -> bool:
        """RAM-miss fall-through to the session disk tier: load the
        conversation's record (checksummed; corruption quarantines and
        returns None) and adopt it through ``import_session_entry`` — the
        exact path a fleet handoff takes, so shared-head re-linking and
        refcounts work identically. Returns True when the entry is now
        resident in RAM."""
        cache = self.session_cache
        if cache is None or cache.disk is None:
            return False
        if conversation_id not in cache.disk:
            if self.fabric is not None:
                # with the shared tier this IS the fleet-wide lookup: a
                # miss means no replica ever retired this conversation
                self.metrics.inc("finchat_fabric_misses_total")
            return False
        t0 = time.perf_counter()
        with Timer(self.metrics, "finchat_durability_restore_seconds"):
            payload = cache.disk.load(conversation_id)
            if payload is None:
                return False  # quarantined (corrupt/truncated): cold start
            # an over-RAM-budget record is trimmed to the prefix that
            # fits (partial warm resume); one that can't fit at all is
            # dropped — put() would refuse it every turn, paying a full
            # record read + rewrite for a guaranteed cold start
            payload = cache.fit_payload(payload)
            if payload is None:
                cache.disk.discard(conversation_id)
                return False
            try:
                # spill=False: these bytes just came OFF this disk tier —
                # rewriting the identical record would double restore I/O
                ok = self.import_session_entry(payload, spill=False)
            except Exception as e:
                logger.error("disk session restore failed for %s: %s",
                             conversation_id, e)
                return False
        if ok:
            self.metrics.inc("finchat_durability_disk_restores_total")
            if self.fabric is not None:
                # the record came off the fleet-shared tier: ANY replica's
                # retirement (or a handoff) could have written it — this
                # replica resumes it warm without ever having seen it
                self.metrics.inc("finchat_fabric_hits_total")
                self.metrics.observe("finchat_fabric_restore_seconds",
                                     time.perf_counter() - t0)
                if TRACER.enabled:
                    TRACER.event("fabric_hit", track="fabric",
                                 args={"kind": "session",
                                       "key": conversation_id})
        elif self.fabric is not None:
            self.metrics.inc("finchat_fabric_misses_total")
        return ok

    def spill_sessions(self) -> int:
        """Write every resident session entry through to the disk tier
        (graceful-shutdown tail; puts already write through, so this is a
        retry/freshness pass)."""
        if self.session_cache is None:
            return 0
        return self.session_cache.spill_all()

    async def shutdown_drain(self) -> None:
        """Graceful-shutdown tail (SIGTERM; serve/app.py drain_and_stop):
        stop the loop, then preempt every straggler to host — its coherent
        KV prefix is offloaded into the session tier (which writes through
        to disk) before its slot and pages are released — and fail it with
        a structured retryable ``shutting_down`` error, so its client
        retries against the restarted process instead of hanging. Pending
        never-admitted work fails the same way. Zero slot/page leaks by
        construction: every live handle goes through ``_release``, and the
        only pages still owned afterwards are the shared-prefix heads'
        (device cache, dropped with the process)."""
        await self.stop()
        shutdown_error = {
            "type": "error",
            "message": "server shutting down; retry with backoff",
            "code": "shutting_down", "retryable": True,
        }
        for handle in list(self.decoding.values()) + list(self.prefilling):
            try:
                # mid-decode stragglers have a coherent prompt+generated
                # KV prefix — the same snapshot a normal retirement takes
                self._maybe_offload(handle)
            except Exception as e:
                logger.error("shutdown offload failed for %s: %s",
                             handle.seq_id, e)
            self._release(handle)
            handle.finished = True
            handle.span.finish()
            handle.events.put_nowait(dict(shutdown_error))
        for handle in list(self.pending):
            self.pending.remove(handle)
            handle.finished = True
            handle.span.finish()
            handle.events.put_nowait(dict(shutdown_error))
        self.metrics.set_gauge("finchat_queue_depth", 0)
        self.spill_sessions()

    def _drain_to_sink(self) -> int:
        """Offer every pending handle — the just-preempted live streams
        AND queued not-yet-admitted work — to the fleet drain sink,
        together with its conversation's exported session-cache bytes.
        Adopted handles leave this scheduler entirely. Runs BEFORE the
        trip purges device-referencing caches (the export must still see
        the entries). Parked/held overlap handles are skipped: their
        extend_prompt seam is bound to this scheduler, and retrieval is
        ms-scale — they replay locally. Returns how many were adopted."""
        sink = self.drain_sink
        if sink is None:
            return 0
        adopted = 0
        for handle in list(self.pending):
            if handle.held:
                continue
            payload = None
            try:
                payload = self.export_session(handle.conversation_id)
            except Exception as e:
                logger.error("session export failed for %s: %s",
                             handle.conversation_id, e)
            try:
                taken = bool(sink(handle, payload))
            except Exception as e:
                logger.error("drain sink failed for %s: %s", handle.seq_id, e)
                taken = False
            if taken:
                self.pending.remove(handle)
                if handle.conversation_id and self.session_cache is not None:
                    # the bytes moved with the stream; keeping the source
                    # entry would let a later divergent turn resume stale
                    self.session_cache.discard(handle.conversation_id)
                adopted += 1
        self.metrics.set_gauge("finchat_queue_depth", len(self.pending))
        return adopted

    def revive(self) -> bool:
        """Supervisor respawn of a given-up replica: the breaker exhausted
        its rebuild budget, the fleet drained this replica's streams to
        siblings and marked it OUT; ``revive`` retries the device-state
        rebuild from a clean slate so the router can bring the replica
        back. Only callable with nothing live here (the drain emptied it).
        Returns True when the engine is serving again."""
        self._revive_prepare()
        if not self._revive_rebuild():
            return False
        self._revive_commit()
        return True

    async def revive_async(self) -> bool:
        """``revive`` with the device rebuild in a worker thread. The
        rebuild reallocates the whole KV pool — seconds of device work at
        real sizes — and the supervisor shares its event loop with every
        SIBLING scheduler, so running it inline would freeze the exact
        streams the drain just saved. Host bookkeeping stays on the loop
        (asyncio futures must resolve there; the OUT replica receives no
        routing, so its idle loop ticks observe only the consistent
        post-prepare state while the thread rebuilds)."""
        self._revive_prepare()
        ok = await asyncio.to_thread(self._revive_rebuild)
        if not ok:
            return False
        self._revive_commit()
        return True

    def _revive_prepare(self) -> None:
        """Clean-slate host bookkeeping ahead of the rebuild. Idempotent —
        the supervisor re-runs it on every backoff retry."""
        if self.decoding or self.prefilling:
            raise RuntimeError("revive() with live sequences; drain first")
        for job in list(self._prefix_jobs):
            # no device ops (a wedged device is why we're here, exactly
            # the trip path's reasoning): the resets below reclaim the
            # slot and pages wholesale, and the future must resolve
            self._prefix_jobs.remove(job)
            if not job.future.done():
                job.future.set_result(0)
        if self.session_cache is not None:
            self.session_cache.discard_if(
                lambda e: e.prefix_len > 0 or e.prefix_entry is not None
            )
        self._prefixes.clear()
        self.allocator.reset()
        self.free_slots = list(range(self.engine.engine_cfg.max_seqs))
        self._temperature[:] = 0.0
        self._top_p[:] = 1.0
        self._top_k[:] = 0

    def _revive_rebuild(self) -> bool:
        """The device-only half (threadable: touches the engine, not
        scheduler state)."""
        try:
            # armable site: a chaos drill wedging this replica's device
            # keeps revive failing too (a broken device fails its rebuild),
            # so the supervisor backs off instead of rejoining a replica
            # that would immediately re-trip (bench --fleet-sweep)
            inject("engine.rebuild", replica=self.replica_id)
            with Timer(self.metrics, "finchat_engine_rebuild_seconds"):
                self.engine.rebuild_device_state()
        except Exception as e:
            logger.error("revive: engine rebuild failed: %s", e)
            return False
        return True

    def _revive_commit(self) -> None:
        self.gave_up = False
        self._rebuilds_without_success = 0
        for bucket in self._fail_streaks:
            self._fail_streaks[bucket] = 0
        self._breaker_bucket = None
        self._breaker_tripped_at = None
        self.metrics.set_gauge("finchat_breaker_state", 0)
        self.metrics.inc("finchat_engine_rebuilds_total")
        for cb in list(self.on_rebuild):
            try:
                cb()
            except Exception as e:
                logger.error("on_rebuild callback failed: %s", e)
        self._wakeup.set()

    async def _round_failed(self, scope: str, error: str) -> None:
        """A whole-round dispatch failure — not attributable to one
        sequence. Breaker off (``breaker_threshold`` 0): legacy behavior,
        the round's population is evicted with an error. Breaker on: the
        failure streak for the plane ('prefill' or 'decode'; mixed and
        spec ride 'decode') advances — below the threshold the round's
        sequences are recompute-preempted and replay through admission (a
        transient blip costs a re-prefill, not the stream); at the
        threshold the breaker trips and the engine device state is
        rebuilt (async: the rebuild itself runs in a worker thread —
        _trip_breaker). Dispatches are never re-consumed after a failure:
        a partially-consumed step cannot be told apart from an unconsumed
        one, and replay recomputes any undelivered token anyway."""
        self.metrics.inc("finchat_dispatch_failures_total")
        if self.breaker_threshold <= 0:
            if scope in ("prefill", "mixed"):
                self._fail_prefill_round(error)
            if scope in ("decode", "mixed", "spec"):
                for handle in list(self.decoding.values()):
                    self._evict(handle, "error", error=error)
            return
        bucket = "prefill" if scope == "prefill" else "decode"
        self._fail_streaks[bucket] += 1
        if self._fail_streaks[bucket] >= self.breaker_threshold:
            await self._trip_breaker(bucket, error)
            return
        if scope in ("prefill", "mixed"):
            for handle in list(self.prefilling):
                if not self._parked(handle):
                    self._preempt(handle)
            for job in list(self._prefix_jobs):
                try:  # registration is best-effort by contract
                    self._fail_prefix_job(job)
                except Exception as e:
                    logger.error("failing prefix job during %s failure: %s",
                                 scope, e)
        if scope in ("decode", "mixed", "spec"):
            for handle in list(self.decoding.values()):
                self._preempt(handle)

    def _note_round_ok(self, bucket: str) -> None:
        """A dispatch round of ``bucket`` completed: its failure streak
        resets; if this is the plane that tripped the breaker, the
        half-open breaker closes (recovery latency observed from trip to
        here) and the consecutive-rebuild give-up counter clears."""
        self._fail_streaks[bucket] = 0
        if self._breaker_bucket in (None, bucket):
            self._rebuilds_without_success = 0
            self._breaker_bucket = None
            if self._breaker_tripped_at is not None:
                self.metrics.observe(
                    "finchat_breaker_recovery_seconds",
                    time.perf_counter() - self._breaker_tripped_at,
                )
                self._breaker_tripped_at = None
                self.metrics.set_gauge("finchat_breaker_state", 0)

    async def _trip_breaker(self, bucket: str, error: str) -> None:
        """Breaker trip: preempt every live sequence to host, tear down
        and rebuild the engine's device state (weights retained, compiled
        variants still valid — shapes are unchanged), reset the page
        allocator and slot bookkeeping, and drop every cache entry that
        referenced device pages (shared-prefix heads, session entries with
        referenced heads). The next loop iteration is the half-open probe:
        admission re-admits via the recompute path, and the first
        successful round closes the breaker. ``breaker_max_rebuilds``
        consecutive trips without a successful round in between give up
        and fail the in-flight streams — a persistently wedged engine
        must not rebuild-loop forever.

        The rebuild itself runs in a worker thread (the same discipline
        as ``revive_async``): reallocating the KV pool is seconds of
        device work at real sizes, and in a fleet every SIBLING replica
        shares this event loop — an inline rebuild would freeze the very
        streams the drain-on-trip just handed them (ISSUE 8 / finchat-lint
        R1; the pre-PR-8 code did exactly that). All host bookkeeping —
        preempts, drain, cache purge, allocator/slot resets — completes
        BEFORE the await, so concurrent coroutines observe a consistent
        emptied scheduler; ``_rebuilding`` gates the one seam that writes
        device state from outside the loop (register_prefix_async)."""
        self._breaker_bucket = bucket
        self._rebuilds_without_success += 1
        if self._rebuilds_without_success > self.breaker_max_rebuilds:
            # black box for the give-up drill (ISSUE 12): the ring holds
            # the tripped rounds' dispatch spans and the failing streams'
            # lifecycle events at the moment this replica goes OUT
            TRACER.anomaly("replica_give_up", args={
                "plane": bucket, "error": str(error)[:200],
                "replica": self.replica_id,
                "rebuilds": self._rebuilds_without_success - 1,
            })
            if self.drain_sink is not None:
                # fleet give-up (ISSUE 6): the streams survive on siblings
                # — preempt every live sequence to host (prompt+generated
                # kept on the handle) and hand it off, instead of failing
                # it; whatever no sibling can adopt fails the legacy way
                logger.error(
                    "breaker: giving up after %d rebuilds; draining %d live "
                    "sequences to sibling replicas (%s)",
                    self._rebuilds_without_success - 1,
                    len(self.decoding) + len(self.prefilling), error,
                )
                for handle in list(self.decoding.values()) + list(self.prefilling):
                    try:
                        self._preempt(handle, for_rebuild=True)
                    except Exception as e:
                        logger.error("preempting %s at breaker give-up: %s",
                                     handle.seq_id, e)
                self._drain_to_sink()
                # whatever no sibling adopted — preempted live streams,
                # parked holds, AND never-admitted queue entries — fails
                # NOW with the retryable error: this scheduler is going
                # OUT, and leaving queued work here would burn another
                # full fail-streak cycle per handle against a known-wedged
                # engine before its client hears anything
                for handle in list(self.pending):
                    self.pending.remove(handle)
                    # the ONLY site counting drain failures — one increment
                    # per stream the drain couldn't save (sink refusals stay
                    # pending and land here; parked holds were never offered
                    # but their streams fail all the same); fleet-LEVEL
                    # series, unlabeled like the rest of finchat_fleet_*
                    METRICS.inc("finchat_fleet_drain_failures_total")
                    handle.finished = True
                    handle.span.finish()
                    handle.events.put_nowait({
                        "type": "error", "message": error,
                        "code": "replica_out", "retryable": True,
                    })
                # the queue is empty now — an OUT replica must not export
                # phantom backlog for its whole OUT/RESPAWNING period
                self.metrics.set_gauge("finchat_queue_depth",
                                       len(self.pending))
            else:
                logger.error(
                    "breaker: %d consecutive rebuilds without a successful "
                    "round; failing in-flight streams (%s)",
                    self._rebuilds_without_success - 1, error,
                )
                for handle in list(self.decoding.values()) + list(self.prefilling):
                    try:
                        self._evict(handle, "error", error=error)
                    except Exception as e:
                        logger.error("evicting %s after breaker give-up: %s",
                                     handle.seq_id, e)
            for job in list(self._prefix_jobs):
                try:  # slot + pages must come back even on give-up
                    self._fail_prefix_job(job)
                except Exception as e:
                    logger.error("failing prefix job at breaker give-up: %s", e)
            for bucket in self._fail_streaks:
                self._fail_streaks[bucket] = 0
            # the scheduler keeps serving new admissions (degraded): close
            # the gauge and drop the trip timestamp so a later recovery
            # doesn't record the whole given-up idle period as latency —
            # _rebuilds_without_success deliberately persists, so another
            # trip without an intervening success gives up immediately
            self._breaker_tripped_at = None
            self.metrics.set_gauge("finchat_breaker_state", 0)
            # the supervisor marks this replica OUT, reassigns its routing
            # share, and respawns it in the background (revive)
            self.gave_up = True
            for cb in list(self.on_give_up):
                try:
                    cb()
                except Exception as e:
                    logger.error("on_give_up callback failed: %s", e)
            return
        logger.error("breaker tripped (%s): preempting %d live sequences and "
                     "rebuilding engine device state", error,
                     len(self.decoding) + len(self.prefilling))
        # flight recorder (ISSUE 12): the anomaly event + ring dump capture
        # the tripped rounds' dispatch spans and every live stream's
        # lifecycle up to this instant — the black box for the breaker
        # drill ROBUSTNESS.md scripts. Host bookkeeping only; the dump
        # itself writes in a worker thread.
        TRACER.anomaly("breaker_trip", args={
            "plane": bucket, "error": str(error)[:200],
            "replica": self.replica_id, "dispatch_tally": self._dispatch_tally,
            "live": len(self.decoding) + len(self.prefilling),
        })
        if self._breaker_tripped_at is None:
            self._breaker_tripped_at = time.perf_counter()
        self.metrics.set_gauge("finchat_breaker_state", 1)
        for handle in list(self.decoding.values()):
            self._preempt(handle, for_rebuild=True)
        for handle in list(self.prefilling):
            # parked overlap holds included: their prefix KV is going away,
            # so they re-prefill and park again awaiting extend_prompt
            self._preempt(handle, for_rebuild=True)
        for job in list(self._prefix_jobs):
            # no device ops here (the engine may be wedged): the slot and
            # pages are reclaimed wholesale by the resets below
            self._prefix_jobs.remove(job)
            if not job.future.done():
                job.future.set_result(0)
        # fleet drain-on-trip (ISSUE 6): hand the preempted streams — and
        # their conversations' session-cache host bytes — to sibling
        # replicas NOW, before the purge below drops the entries, so the
        # streams continue elsewhere while this replica rebuilds instead
        # of stalling behind the rebuild. Whatever no sibling adopts stays
        # pending and replays here after the rebuild (PR 5 behavior).
        if self.drain_sink is not None:
            adopted = self._drain_to_sink()
            if adopted:
                logger.info("breaker drain: %d streams adopted by siblings",
                            adopted)
        # caches referencing device pages reference a pool that no longer
        # exists: session entries with a referenced head are purged (their
        # on_drop releases the head refs), then the head entries drop
        if self.session_cache is not None:
            self.session_cache.discard_if(
                lambda e: e.prefix_len > 0 or e.prefix_entry is not None
            )
        self._prefixes.clear()
        # host bookkeeping resets BEFORE the rebuild attempt: the old
        # device pool is discarded either way (rebuild drops it first), so
        # this also reclaims the prefix jobs' pages/slots wholesale — a
        # rebuild failure must not strand them owned by dead registrants
        # and stall admission forever
        self.allocator.reset()
        self.free_slots = list(range(self.engine.engine_cfg.max_seqs))
        self._temperature[:] = 0.0
        self._top_p[:] = 1.0
        self._top_k[:] = 0
        try:
            self._rebuilding = True
            try:
                with Timer(self.metrics, "finchat_engine_rebuild_seconds"):
                    await asyncio.to_thread(self.engine.rebuild_device_state)
            finally:
                self._rebuilding = False
        except Exception as e:
            # rebuild itself failed (device gone?): fail what we hold and
            # leave the breaker open — the next trip retries the rebuild
            logger.error("engine rebuild failed: %s", e)
            for handle in list(self.pending):
                if handle.preempted:
                    self.pending.remove(handle)
                    handle.finished = True
                    handle.span.finish()
                    handle.events.put_nowait(
                        {"type": "error", "message": f"engine rebuild failed: {e}"}
                    )
            return
        for bucket in self._fail_streaks:
            self._fail_streaks[bucket] = 0
        self.metrics.inc("finchat_engine_rebuilds_total")
        self.metrics.set_gauge("finchat_breaker_state", 2)  # half-open
        for cb in list(self.on_rebuild):
            try:
                cb()
            except Exception as e:
                logger.error("on_rebuild callback failed: %s", e)

    async def _prefill_round(self) -> None:
        """Advance EVERY currently-prefilling sequence one chunk in a single
        batched ``prefill_step`` (one weights-read for the whole round). The
        batch dim is padded to the next power of two (round_up_pow2 — the
        same policy Engine.warmup compiles for) so a burst of admissions
        compiles at most log2(max_seqs) prefill variants, not one per N.

        Long prompts on a ``seq > 1`` mesh take the seq-sharded ring path
        instead (engine.prefill_ring, SURVEY §5.7c) and complete in this
        same round."""
        eng = self.engine
        C = eng.engine_cfg.prefill_chunk
        # one logical serving round (the dispatches-per-ROUND denominator;
        # the decode dispatch riding the same iteration is the same round)
        self._round_tally += 1
        batch: list[SequenceHandle] = []
        # (handle, device logits row, epoch) triples whose prompt completed
        # this round — the epoch tells a preempted-and-replayed incarnation
        # from the one this round prefilled
        completions: list[tuple[SequenceHandle, object, int]] = []
        for handle in list(self.prefilling):
            if self._parked(handle):
                continue  # awaiting extend_prompt
            try:
                inject("scheduler.prefill", seq_id=handle.seq_id, replica=self.replica_id)
                if self._ring_routed(handle):
                    rc = eng.ring_segment_tokens()
                    if rc == 0:
                        assert handle.prefill_pos == 0  # monolithic never
                        # admits with a prefix hit (see _admit)
                        # monolithic one-shot SP prefill (only when
                        # ring_prefill_chunk=0; both sp_modes chunk now):
                        # in-flight decode streams stall for the whole
                        # seq-sharded prefill — the latency trade the
                        # chunked path below exists to avoid
                        with Timer(self.metrics, "finchat_prefill_seconds") as _pt:
                            ring_logits = eng.prefill_ring(handle.slot, handle.prompt_ids)
                        self._tally_dispatch()
                        if TRACER.enabled:
                            self._trace_dispatch(
                                "ring",
                                [[handle.slot, handle.trace_id or handle.seq_id, "ring"]],
                                ts=_pt.started, dur=_pt.elapsed,
                            )
                        handle.prefill_pos = len(handle.prompt_ids)
                        handle.kv_ctx = handle.prefill_pos
                        completions.append((handle, ring_logits, handle.epoch))
                        continue
                    # chunked ring: ONE segment per round — decode steps
                    # interleave between segments, so one long prompt no
                    # longer freezes every other stream (each segment
                    # folds the cached earlier segments into its ring
                    # attention, engine.prefill_ring_segment)
                    handle.ring_path = True
                    seg = handle.prompt_ids[handle.prefill_pos : handle.prefill_pos + rc]
                    with Timer(self.metrics, "finchat_prefill_seconds") as _pt:
                        seg_logits = eng.prefill_ring_segment(
                            handle.slot, seg, handle.prefill_pos
                        )
                    self._tally_dispatch()
                    if TRACER.enabled:
                        self._trace_dispatch(
                            "ring_segment",
                            [[handle.slot, handle.trace_id or handle.seq_id, "ring"]],
                            ts=_pt.started, dur=_pt.elapsed,
                        )
                    handle.prefill_pos += len(seg)
                    handle.kv_ctx = handle.prefill_pos
                    if handle.prefill_pos >= len(handle.prompt_ids):
                        completions.append((handle, seg_logits, handle.epoch))
                    continue
            except Exception as e:  # per-sequence isolation
                logger.error("prefill error for %s: %s", handle.seq_id, e)
                self._evict(handle, "error", error=str(e))
                continue
            batch.append(handle)

        # chunked prefix registrations (register_prefix_async) ride the
        # same batched step: one chunk per round, no logits needed
        jobs = list(self._prefix_jobs)
        if batch or jobs:
            from finchat_tpu.engine.engine import round_up_pow2

            rows = [(h.slot, h.prompt_ids, h.prefill_pos) for h in batch]
            rows += [(j.slot, j.ids, j.pos) for j in jobs]
            N = round_up_pow2(len(rows))
            tokens, slots, starts, n_valids = self._pack_prefill_rows(rows, N, C)
            with Timer(self.metrics, "finchat_prefill_seconds") as _pt:
                # host-side dispatch time for the round (device work is
                # async; steady-state it tracks the round cadence)
                eng.state, logits = prefill_step(
                    eng.params, eng.state,
                    jnp.asarray(tokens), jnp.asarray(slots),
                    jnp.asarray(starts), jnp.asarray(n_valids),
                    config=eng.config, page_size=eng.page_size,
                    attn_backend=eng.attn_backend,
                )
            self._tally_dispatch()
            if TRACER.enabled:
                trows = [[h.slot, h.trace_id or h.seq_id, "prefill"] for h in batch]
                trows += [[j.slot, f"prefix:{j.owner}", "prefix"] for j in jobs]
                self._trace_dispatch("prefill", trows,
                                     ts=_pt.started, dur=_pt.elapsed)
            for i, handle in enumerate(batch):
                handle.prefill_pos += int(n_valids[i])
                handle.kv_ctx = handle.prefill_pos
                if handle.prefill_pos >= len(handle.prompt_ids):
                    if handle.held:
                        continue  # park: the first token commits only
                        # after extend_prompt grafts the real prompt end
                    completions.append((handle, logits[i], handle.epoch))
            for i, job in enumerate(jobs, start=len(batch)):
                job.pos += int(n_valids[i])
                if job.pos >= job.shared_len:
                    self._complete_prefix_job(job, "chunked")

        if not completions:
            return  # dispatch-only round, no host sync needed

        tokens_dev = []
        for h, row_logits, _e in completions:
            h.span.mark("prefill_done")
            s = h.sampling
            eng.state, token = commit_first_token(
                eng.state, jnp.int32(h.slot), row_logits,
                jnp.float32(s.temperature), jnp.float32(s.top_p), jnp.int32(s.top_k),
            )
            tokens_dev.append(token)
        # one host fetch for all completions (worker thread keeps loop live)
        fetched, logits_host = await asyncio.to_thread(
            lambda: (
                [int(np.asarray(t)) for t in tokens_dev],
                [
                    np.asarray(row_logits) if h.constraint is not None else None
                    for h, row_logits, _e in completions
                ],
            )
        )
        for (handle, _lg, epoch), token_id, row_host in zip(completions, fetched, logits_host):
            if handle.finished or handle.epoch != epoch:
                continue  # cancelled/preempted while fetching
            try:
                if handle.constraint is not None:
                    token_id = self._constrained_pick(handle, row_host)
                self.prefilling.remove(handle)
                self.decoding[handle.slot] = handle
                self._deliver(handle, int(token_id))
            except Exception as e:  # per-sequence isolation (host-side pick
                # or delivery error must not fail the other sequences)
                logger.error("prefill completion error for %s: %s", handle.seq_id, e)
                self._evict(handle, "error", error=str(e))

    @staticmethod
    def _pack_prefill_rows(rows, N: int, C: int):
        """Ragged row arrays for a chunked split-path round
        (_prefill_round; the packed ragged round builds its own buffer):
        one chunk per ``(slot, ids, pos)`` row; padding rows carry the
        first row's slot with ``n_valid 0`` → trash writes."""
        tokens = np.zeros((N, C), np.int32)
        slots = np.zeros((N,), np.int32)
        starts = np.zeros((N,), np.int32)
        n_valids = np.zeros((N,), np.int32)
        slots[:] = rows[0][0]
        for i, (slot, ids, pos) in enumerate(rows):
            chunk = ids[pos : pos + C]
            tokens[i, : len(chunk)] = chunk
            slots[i] = slot
            starts[i] = pos
            n_valids[i] = len(chunk)
        return tokens, slots, starts, n_valids

    def _complete_prefix_job(self, job: _PrefixJob, how: str) -> None:
        """A chunked prefix registration finished its last chunk: publish
        the entry, return the engine slot, resolve the caller's future
        (shared by both round paths — they must stay in lock-step)."""
        self._prefix_jobs.remove(job)
        self.engine.reset_slot(job.slot)
        self.free_slots.append(job.slot)
        self._prefixes.append(
            _PrefixEntry(job.ids, job.pages, job.shared_len, job.owner)
        )
        logger.info(
            "prefix cache: registered %d shared tokens (%d pages, %s)",
            job.shared_len, len(job.pages), how,
        )
        if not job.future.done():
            job.future.set_result(job.shared_len)

    def _fail_prefill_round(self, error: str) -> None:
        """A whole-round prefill failure is not attributable to one
        sequence: fail everything that was IN the dispatch. Parked overlap
        holds whose prefix already finished were skipped from the round
        (they are awaiting extend_prompt, not prefilling), so they must
        survive — the pre-fix behavior evicted them too, failing in-flight
        retrieval overlaps that never touched the failed dispatch."""
        for handle in list(self.prefilling):
            if self._parked(handle):
                continue  # not in the failed round
            self._evict(handle, "error", error=error)
        for job in list(self._prefix_jobs):
            self._fail_prefix_job(job)

    # every label the demotion counter can emit — pre-seeded to 0 at
    # construction so the whole family renders even when (by design, the
    # ISSUE 10 point) spec / decode_loop / constrained never fire again
    MIXED_DEMOTION_REASONS = ("spec", "decode_loop", "constrained", "ring", "other")

    # every reason a free-run capture caps to one host-stepped round —
    # pre-seeded at 0 when the free-running loop is enabled (the same
    # discipline as MIXED_DEMOTION_REASONS)
    FREERUN_CAP_REASONS = ("constrained", "spec", "underfill", "boundedkv")

    def _freerun_rounds_cap(self) -> int:
        """How many consecutive rounds the next capture may free-run — the
        ``_use_mixed``-style predicate of ISSUE 13. Rows that need a HOST
        decision every round cap the capture to 1 (exactly today's
        host-stepped behavior): grammar-constrained rows (the host pick
        feeds the next round's input) and live spec-proposal windows
        (drafts are proposed from DELIVERED tokens the device is still
        holding). Bounded-KV rows cap the capture at their next eviction
        boundary (ISSUE 15 — eviction is staged at capture boundaries
        like budget stops, so a capture's gap schedule matches the
        host-stepped loop's exactly)."""
        F = self.freerun_rounds
        if F <= 1:
            return 1
        if (any(h.constraint is not None for h in self.decoding.values())
                or any(h.constraint is not None for h in self.prefilling
                       if not self._parked(h))):
            # parked holds are skipped by the staging anyway (and today's
            # overlap API never parks a constrained prompt) — only rows
            # that would actually ride the capture may cap it
            self.metrics.inc("finchat_freerun_capped_total",
                             labels={"reason": "constrained"})
            return 1
        if (self.spec_k > 0 and self._spec_cooldown == 0
                and self._spec_proposal_live()):
            # a proposal must ACTUALLY fire to cap the capture: eligible
            # slots whose n-gram lookups all miss would run a plain decode
            # round anyway (see _spec_proposal_live), so they free-run
            self.metrics.inc("finchat_freerun_capped_total",
                             labels={"reason": "spec"})
            return 1
        if self.bounded_kv is not None:
            cap = self._bounded_freerun_cap()
            if cap < F:
                self.metrics.inc("finchat_freerun_capped_total",
                                 labels={"reason": "boundedkv"})
                return max(1, cap)
        return F

    def _dispatch_freerun(self, rounds: int,  # finchat-lint: hot
                          ahead: dict[int, int]) -> "_InFlightRing | None":
        """Stage and enqueue ONE captured multi-round program (ISSUE 13;
        engine.ragged_multi over ops/freerun.stage_freerun): every
        prefilling row's next ``rounds`` chunks, every decode slot's next
        ``rounds`` tokens (with fused tails where eligible), and the
        completion→decode flips in between are pre-staged into the
        descriptor queue; the device then free-runs ``rounds`` ragged
        rounds with no host round-trip, emitting into the token ring this
        returns. Returns None — the caller runs the host-stepped single
        round instead — when the staged plan cannot fill every round
        (work runs out mid-capture; empty device rounds would be pure
        waste). ``ahead`` is ``_undelivered()`` for the still-unconsumed
        in-flight dispatch: budgets are staged NET of it, so a capture
        staged before the previous ring drains can never run a stream
        past ``max_new_tokens`` or its page allocation."""
        from finchat_tpu.ops.freerun import RowSpec, stage_freerun

        eng = self.engine
        C = eng.engine_cfg.prefill_chunk
        B = eng.engine_cfg.max_seqs
        specs: list[RowSpec] = []
        members: list[tuple] = []

        def _budget(h: SequenceHandle) -> int:
            return max(
                0, h.sampling.max_new_tokens - h.generated - ahead.get(h.slot, 0)
            )

        for handle in list(self.prefilling):
            if self._parked(handle):
                continue  # awaiting extend_prompt
            try:
                inject("scheduler.prefill", seq_id=handle.seq_id,
                       replica=self.replica_id)
            except Exception as e:  # per-sequence isolation, as in _ragged_round
                logger.error("prefill error for %s: %s", handle.seq_id, e)
                self._evict(handle, "error", error=str(e))
                continue
            s = handle.sampling
            if handle.prefill_pos >= len(handle.prompt_ids):
                # completed inside a still-unconsumed ring: its first
                # token is in flight (counted in ``ahead``) and this
                # capture stages it as a plain decode row
                specs.append(RowSpec(
                    slot=handle.slot, kind="decode", budget=_budget(handle),
                    loop_ok=self.loop_depth > 1,
                    temperature=s.temperature, top_p=s.top_p, top_k=s.top_k,
                ))
                members.append((len(specs) - 1, handle.slot, handle,
                                handle.epoch, "decode"))
                continue
            specs.append(RowSpec(
                slot=handle.slot, kind="prefill", ids=handle.prompt_ids,
                pos=handle.prefill_pos, arm=not handle.held,
                budget=_budget(handle), loop_ok=self.loop_depth > 1,
                temperature=s.temperature, top_p=s.top_p, top_k=s.top_k,
            ))
            members.append((len(specs) - 1, handle.slot, handle,
                            handle.epoch, "prefill"))
        jobs = list(self._prefix_jobs)
        for job in jobs:
            specs.append(RowSpec(slot=job.slot, kind="job",
                                 ids=job.ids[: job.shared_len], pos=job.pos,
                                 arm=False))
            members.append((len(specs) - 1, job.slot, job, 0, "job"))
        for slot, handle in self.decoding.items():
            s = handle.sampling
            specs.append(RowSpec(
                slot=slot, kind="decode", budget=_budget(handle),
                loop_ok=self.loop_depth > 1,
                temperature=s.temperature, top_p=s.top_p, top_k=s.top_k,
            ))
            members.append((len(specs) - 1, slot, handle, handle.epoch,
                            "decode"))
        if not specs:
            return None  # a fault drained everything; split paths resume

        plan = stage_freerun(specs, rounds=rounds, chunk=C,
                             loop_depth=self.loop_depth, max_seqs=B,
                             bucket=eng.ragged_bucket)
        if plan.active_rounds < rounds:
            # the work runs out before the capture would: fall back to the
            # host-stepped round rather than free-running empty rounds
            self.metrics.inc("finchat_freerun_capped_total",
                             labels={"reason": "underfill"})
            return None
        inject("scheduler.decode", replica=self.replica_id)
        inject("scheduler.mixed", replica=self.replica_id)
        with Timer(self.metrics, "finchat_mixed_step_seconds") as _mt:
            ring_tok, ring_n, ring_blk = eng.ragged_multi(
                jnp.asarray(plan.tokens), jnp.asarray(plan.tok_row),
                jnp.asarray(plan.row_slot), jnp.asarray(plan.row_start),
                jnp.asarray(plan.row_len), jnp.asarray(plan.row_from_device),
                jnp.asarray(plan.row_arm),
                jnp.asarray(plan.temperature), jnp.asarray(plan.top_p),
                jnp.asarray(plan.top_k), jnp.asarray(plan.loop_active),
                jnp.asarray(self._temperature), jnp.asarray(self._top_p),
                jnp.asarray(self._top_k), self.eos_id,
            )
        self._tally_dispatch()
        self._round_tally += rounds
        self.metrics.inc("finchat_freerun_dispatches_total")
        # unit is ROUNDS, not seconds: the N-rounds-per-1-dispatch
        # attribution instrument ISSUE 13 names
        self.metrics.observe("finchat_freerun_rounds_per_dispatch", rounds)  # finchat-lint: disable=metrics-discipline -- rounds-per-dispatch histogram: the unit is rounds (ISSUE 13 names this metric); _seconds would be a lie
        if TRACER.enabled:
            trows = []
            for _row, slot, owner, _epoch, kind in members:
                tid = (f"prefix:{owner.owner}" if kind == "job"
                       else (owner.trace_id or owner.seq_id))
                trows.append([slot, tid, "freerun"])
            self._trace_dispatch("freerun", trows,
                                 ts=_mt.started, dur=_mt.elapsed)
        # prompt-cursor bookkeeping at dispatch, exactly _ragged_round's
        # discipline: the staged chunks ARE dispatched
        for row, slot, owner, _epoch, kind in members:
            adv = plan.advanced.get(row, 0)
            if kind == "job":
                if adv:
                    owner.pos += adv
                    if owner.pos >= owner.shared_len:
                        self._complete_prefix_job(owner, "freerun")
                continue
            if adv:
                owner.prefill_pos += adv
                owner.kv_ctx = owner.prefill_pos
            # staged decode rounds advance device context by 1 per armed
            # round (+ the fused tails) — plan.ahead counts exactly those
            # emissions, except a completion flip's first token (sampled,
            # its KV not yet written)
            extra = plan.ahead.get(slot, 0)
            if row in plan.completes_at:
                extra -= 1
            owner.kv_ctx += max(0, extra)
        return _InFlightRing(
            tokens=ring_tok, n_emitted=ring_n, blocks=ring_blk,
            rounds=rounds, members=members, armed=plan.row_arm,
            loop_rounds=plan.loop_active, completes_at=plan.completes_at,
            ahead=plan.ahead,
        )

    async def _consume_ring(self, ring: _InFlightRing) -> None:  # finchat-lint: hot
        """Drain a captured run's token ring: ONE device→host fetch (in a
        worker thread — never ``block_until_ready`` on the consume path,
        the finchat-lint R2 seam) for up to ``rounds`` tokens per row plus
        the fused tails, delivered round-by-round in device order. Runs
        while the device is already mid-flight on the NEXT capture
        (depth-2). Stale rows — evicted / preempted / replayed since
        dispatch, detected by the (slot, handle, epoch) snapshot — have
        their residual ring tokens discarded exactly once and recomputed
        by the replay (the PR 5 discipline); such a drain is the epoch
        boundary and is recorded as a ``freerun_epoch_break`` trace
        event. A round emitting where the staged plan never armed is a
        free-run divergence: flight-recorder dump, tokens not
        delivered."""
        tok_host, n_host, blk_host = await asyncio.to_thread(
            lambda: (np.asarray(ring.tokens), np.asarray(ring.n_emitted),
                     np.asarray(ring.blocks)),
        )
        armed = ring.armed
        if bool(((n_host > 0) & ~armed).any()):
            # ring replay mismatch: the device emitted outside the staged
            # schedule — dump the black box and deliver nothing from the
            # unarmed cells (they were never part of any stream)
            self.metrics.inc("finchat_freerun_divergences_total")
            TRACER.anomaly("freerun_divergence", args={
                "replica": self.replica_id, "rounds": ring.rounds,
                "cells": int(((n_host > 0) & ~armed).sum()),
            })
        K1 = int(blk_host.shape[1])
        wasted = 0
        epoch_break = False
        for r in range(ring.rounds):
            for row, slot, owner, epoch, kind in ring.members:
                if kind == "job":
                    continue
                handle: SequenceHandle = owner
                stale = (handle.finished or handle.slot != slot
                         or handle.epoch != epoch)
                n = int(n_host[r, row])
                if n > 0 and armed[r, row]:
                    if stale:
                        # evicted/cancelled/preempted since dispatch: the
                        # replay recomputes this token — discarding it
                        # here is what keeps delivery exactly-once
                        epoch_break = True
                        wasted += n
                    else:
                        if ring.completes_at.get(row) == r:
                            handle.span.mark("prefill_done")
                            self.prefilling.remove(handle)
                            self.decoding[handle.slot] = handle
                        self._deliver(handle, int(tok_host[r, row]))
                        stale = (handle.finished or handle.slot != slot
                                 or handle.epoch != epoch)
                if K1 and ring.loop_rounds[r, slot]:
                    # fused tail rows: -1 marks where the device stop
                    # mask kicked in (exactly _consume_block's drain)
                    if stale:
                        wasted += K1
                        continue
                    for j in range(K1):
                        token = int(blk_host[r, j, slot])
                        if token < 0:
                            wasted += K1 - j
                            break
                        self._deliver(handle, token)
                        if handle.finished:
                            wasted += K1 - j - 1
                            break
        if wasted:
            self.metrics.inc("finchat_decode_loop_wasted_tail_tokens_total",
                             wasted)
        if epoch_break:
            # the membership epoch invalidated this capture mid-flight:
            # visible on the Perfetto timeline as the capture/replay
            # boundary (ISSUE 13)
            self.metrics.inc("finchat_freerun_epoch_breaks_total")
            TRACER.event("freerun_epoch_break", track=self._trace_track,
                         args={"replica": self.replica_id,
                               "rounds": ring.rounds})
        self.metrics.set_gauge("finchat_batch_occupancy", len(self.decoding))

    def _use_mixed(self) -> bool:
        """Can this iteration run ONE packed ragged dispatch instead of a
        prefill round plus a decode-side dispatch? Both populations must
        exist — and that is now the ONLY condition. The ragged rebuild
        (ISSUE 10) folded spec verify blocks, decode_loop fused tails, and
        grammar-constrained picks into rows of the packed buffer; ring/
        seq-sharded prefill — the last demotion reason — is promoted too
        (ISSUE 15): a ring-routed prompt rides the packed round as
        ordinary bounded-size chunk rows, where the ragged kernel's
        per-page online-softmax accumulation IS the ring fold's carry
        (ops/ring_attention.py ``ring_attention_with_prefix`` — each chunk
        folds the cached earlier segments page by page), and a
        prefill_chunk-sized row bounds activation memory the way the
        segmented ring schedule did. ``finchat_mixed_demotions_total``
        stays pre-seeded per reason — INCLUDING reason="ring" — so the
        complete erasure is observable (bench --ragged-sweep /
        --longctx-smoke gate it at zero). The split path — where
        ring-routed rows still run their seq-sharded collective schedule
        when no decode coexists — stays the golden-identical fallback."""
        if not self.mixed_enabled or not self.decoding:
            return False
        rows = [h for h in self.prefilling if not self._parked(h)]
        if not rows and not self._prefix_jobs:
            return False
        return True

    async def _ragged_round(self) -> None:  # finchat-lint: hot
        """Advance EVERY serving population in a single packed ragged
        dispatch (ISSUE 10; engine.ragged_mixed_step over
        ops/ragged_paged_attention.py): prefilling sequences a chunk each,
        plain decode slots a token, grammar-constrained slots a token with
        their logits row returned for the host pick, spec-eligible slots a
        (1+Kd)-token verify block, and loop-eligible slots a further fused
        ``loop_depth - 1``-token tail — one model dispatch, one host
        fetch. PR 4's padded mixed step demoted the whole iteration to the
        serialized split path whenever any of those features was live —
        exactly the mix a loaded engine runs; now only ring/seq-sharded
        prefill demotes (_use_mixed). Prefill rows whose prompt completes
        sample their first token on-device in the same dispatch
        (greedy-identical to commit_first_token)."""
        eng = self.engine
        C = eng.engine_cfg.prefill_chunk
        B = eng.engine_cfg.max_seqs
        self._round_tally += 1  # one host-stepped serving round
        Kd = self.spec_k
        spec_on = Kd > 0 and self._spec_cooldown == 0
        batch: list[SequenceHandle] = []
        for handle in list(self.prefilling):
            if self._parked(handle):
                continue  # awaiting extend_prompt
            try:
                inject("scheduler.prefill", seq_id=handle.seq_id, replica=self.replica_id)
            except Exception as e:  # per-sequence isolation, as in the split path
                logger.error("prefill error for %s: %s", handle.seq_id, e)
                self._evict(handle, "error", error=str(e))
                continue
            batch.append(handle)
        jobs = list(self._prefix_jobs)
        decode_members = [
            (slot, h, h.epoch) for slot, h in self.decoding.items()
        ]
        if (not batch and not jobs) or not decode_members:
            return  # a fault above drained one side; split paths resume next tick
        inject("scheduler.decode", replica=self.replica_id)
        # mixed-specific armable site (ISSUE 5 satellite): targets ONLY the
        # unified dispatch, so tests can fail the fused round while the
        # split fallback paths stay healthy
        inject("scheduler.mixed", replica=self.replica_id)
        from finchat_tpu.engine.spec import NgramIndex

        # one row per live slot (prefill handles, prefix jobs, decode
        # slots all hold distinct engine slots, so rows <= max_seqs); the
        # descriptor arrays are fixed [max_seqs] — only the packed-token
        # bucket varies the compiled shape
        R = B
        row_slot = np.zeros((R,), np.int32)
        row_start = np.zeros((R,), np.int32)
        row_len = np.zeros((R,), np.int32)
        row_from_device = np.zeros((R,), bool)
        row_arm = np.zeros((R,), bool)
        row_n_drafts = np.zeros((R,), np.int32)
        temp = np.zeros((R,), np.float32)
        top_p = np.ones((R,), np.float32)
        top_k = np.zeros((R,), np.int32)
        loop_active = np.zeros((B,), bool)
        packed: list[int] = []
        tok_row: list[int] = []

        completions: list[tuple[int, SequenceHandle, int]] = []  # (row, h, epoch)
        prefill_rows: list[tuple[int, SequenceHandle]] = []
        job_rows: list[tuple[int, _PrefixJob]] = []
        plain_rows: list[tuple[int, int, SequenceHandle, int]] = []
        spec_rows: list[tuple[int, int, SequenceHandle, int]] = []
        constrained_decode: list[tuple[int, int, SequenceHandle, int]] = []
        constrained_rows: list[int] = []  # row indices whose logits the host needs
        loop_members: list[tuple[int, SequenceHandle, int]] = []
        spec_consulted = False

        i = 0
        for h in batch:
            chunk = h.prompt_ids[h.prefill_pos : h.prefill_pos + C]
            row_slot[i] = h.slot
            row_start[i] = h.prefill_pos
            row_len[i] = len(chunk)
            packed += chunk
            tok_row += [i] * len(chunk)
            if not h.held and h.prefill_pos + len(chunk) >= len(h.prompt_ids):
                # prompt completes this chunk: arm the row so its first
                # token samples on-device with the sequence's own params
                # (constrained completions keep the non-truncating
                # defaults — the host pick replaces the sample, and a
                # truncating top_p/top_k would knock the whole packed
                # batch off the sampler's exact full-vocab fast path)
                row_arm[i] = True
                completions.append((i, h, h.epoch))
                if h.constraint is not None:
                    constrained_rows.append(i)
                else:
                    s = h.sampling
                    temp[i], top_p[i], top_k[i] = s.temperature, s.top_p, s.top_k
            prefill_rows.append((i, h))
            i += 1
        for job in jobs:
            chunk = job.ids[job.pos : job.pos + C]
            row_slot[i] = job.slot
            row_start[i] = job.pos
            row_len[i] = len(chunk)
            packed += chunk
            tok_row += [i] * len(chunk)
            job_rows.append((i, job))
            i += 1
        for slot, h, epoch in decode_members:
            row_slot[i] = slot
            row_from_device[i] = True
            row_arm[i] = True
            if h.constraint is not None:
                # host-side grammar pick from this row's returned logits
                # (the depth-1 round consumes within the iteration, so the
                # pick lands before the slot's next dispatch); sampling
                # params stay the non-truncating defaults
                row_len[i] = 1
                packed.append(0)
                tok_row.append(i)
                constrained_rows.append(i)
                constrained_decode.append((i, slot, h, epoch))
                h.kv_ctx += 1
                i += 1
                continue
            prop: list[int] = []
            if spec_on and self._spec_eligible(h):
                spec_consulted = True
                if h.ngram_index is None:  # one-time build; _deliver
                    h.ngram_index = NgramIndex(h.history)  # keeps it in sync
                remaining = h.sampling.max_new_tokens - h.generated
                # bounded rows: the (1 + drafts) verify span must fit the
                # eviction-boundary room (see _bounded_span_room)
                cap = min(Kd, remaining - 1, self._bounded_span_room(h) - 1)
                prop = h.ngram_index.propose(cap) if cap > 0 else []
            s = h.sampling
            temp[i], top_p[i], top_k[i] = s.temperature, s.top_p, s.top_k
            if prop:
                # spec verify row: [device last_token, d1..dKd'] — the
                # drafts ride the packed buffer; acceptance on device
                row_len[i] = 1 + len(prop)
                row_n_drafts[i] = len(prop)
                packed.append(0)
                tok_row.append(i)
                packed += [int(t) for t in prop]
                tok_row += [i] * len(prop)
                spec_rows.append((i, slot, h, epoch))
                # context advances by n_emitted (>= 1) — the extra
                # accepted tokens land on kv_ctx at consume (depth-1)
                h.kv_ctx += 1
            else:
                row_len[i] = 1
                packed.append(0)
                tok_row.append(i)
                plain_rows.append((i, slot, h, epoch))
                if self.loop_depth > 1 and self._loop_eligible(h, 0):
                    # fused K-token tail inside the SAME dispatch: the
                    # row's phase-1 token plus loop_depth-1 tail tokens
                    # stay within the budget (and eviction-boundary room)
                    # _loop_eligible checks — the span starts at the
                    # phase-1 write, so eligibility runs pre-bump
                    loop_active[slot] = True
                    loop_members.append((slot, h, epoch))
                    h.kv_ctx += self.loop_depth
                else:
                    h.kv_ctx += 1
            i += 1

        T = eng.ragged_bucket(len(packed))
        packed += [0] * (T - len(packed))
        tok_row += [R] * (T - len(tok_row))
        with Timer(self.metrics, "finchat_mixed_step_seconds") as _mt:
            emitted_dev, n_em_dev, row_logits_dev, block_dev = eng.ragged_mixed(
                jnp.asarray(np.asarray(packed, np.int32)),
                jnp.asarray(np.asarray(tok_row, np.int32)),
                jnp.asarray(row_slot), jnp.asarray(row_start),
                jnp.asarray(row_len), jnp.asarray(row_from_device),
                jnp.asarray(row_arm), jnp.asarray(row_n_drafts),
                jnp.asarray(temp), jnp.asarray(top_p), jnp.asarray(top_k),
                jnp.asarray(loop_active), jnp.asarray(self._temperature),
                jnp.asarray(self._top_p), jnp.asarray(self._top_k),
                self.eos_id,
            )
        self._tally_dispatch()
        if TRACER.enabled:
            # dispatch span piggybacking on the round's own row
            # bookkeeping (ISSUE 12): every (slot, trace, mode) row that
            # rode this one ragged dispatch, from host data only
            trows = [[h.slot, h.trace_id or h.seq_id, "prefill"]
                     for _i, h in prefill_rows]
            trows += [[j.slot, f"prefix:{j.owner}", "prefix"]
                      for _i, j in job_rows]
            trows += [[slot, h.trace_id or h.seq_id, "constrained"]
                      for _i, slot, h, _e in constrained_decode]
            trows += [[slot, h.trace_id or h.seq_id,
                       "decode_loop" if loop_active[slot] else "decode"]
                      for _i, slot, h, _e in plain_rows]
            trows += [[slot, h.trace_id or h.seq_id, "spec"]
                      for _i, slot, h, _e in spec_rows]
            self._trace_dispatch("ragged", trows,
                                 ts=_mt.started, dur=_mt.elapsed)
        # prefill bookkeeping happens at dispatch: row_len is host data
        for idx, h in prefill_rows:
            h.prefill_pos += int(row_len[idx])
            h.kv_ctx = h.prefill_pos
        for idx, job in job_rows:
            job.pos += int(row_len[idx])
            if job.pos >= job.shared_len:
                self._complete_prefix_job(job, "ragged")
        logits_sel = None
        if constrained_rows:
            # only the constrained rows' logits cross to host — a device
            # slice [n, vocab], exactly the _dispatch_decode discipline
            logits_sel = row_logits_dev[jnp.asarray(constrained_rows, jnp.int32)]
        # ONE host fetch serves decode tokens, spec acceptances, first
        # tokens, the fused tail block, and the constrained rows' logits
        # (worker thread keeps the event loop live)
        emitted, n_emitted, block, logits_host = await asyncio.to_thread(
            lambda: (
                np.asarray(emitted_dev), np.asarray(n_em_dev),
                np.asarray(block_dev),
                np.asarray(logits_sel) if logits_sel is not None else None,
            )
        )
        for idx, handle, epoch in completions:
            if handle.finished or handle.epoch != epoch:
                continue  # cancelled/preempted while fetching
            handle.span.mark("prefill_done")
            try:
                if handle.constraint is not None:
                    token = self._constrained_pick(
                        handle, logits_host[constrained_rows.index(idx)]
                    )
                else:
                    token = int(emitted[idx, 0])
                self.prefilling.remove(handle)
                self.decoding[handle.slot] = handle
                self._deliver(handle, int(token))
            except Exception as e:  # per-sequence isolation
                logger.error("prefill completion error for %s: %s", handle.seq_id, e)
                self._evict(handle, "error", error=str(e))
        for idx, slot, handle, epoch in constrained_decode:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                continue  # evicted/cancelled/preempted since dispatch
            token = self._constrained_pick(
                handle, logits_host[constrained_rows.index(idx)]
            )
            self._deliver(handle, token)
        for idx, slot, handle, epoch in plain_rows:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                continue
            self._deliver(handle, int(emitted[idx, 0]))
        accepted_total = 0
        for idx, slot, handle, epoch in spec_rows:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                continue
            n = int(n_emitted[idx])
            handle.kv_ctx += max(0, n - 1)  # accepted drafts' context advance
            accepted_total += max(0, n - 1)
            for token in emitted[idx, :n]:
                self._deliver(handle, int(token))
                if handle.finished:  # EOS / length inside the prefix
                    break
        if accepted_total:
            self.metrics.inc("finchat_spec_tokens_accepted_total", accepted_total)
        if spec_consulted:
            # the all-miss demotion bookkeeping keeps its split-path
            # cadence: a ragged round where every proposal missed (or
            # nothing was accepted) advances the streak
            self._spec_note_step(accepted=accepted_total)
        # fused tail: drain each loop slot's [loop_depth-1] row — -1 marks
        # where the device stop mask kicked in after a phase-1/tail EOS
        wasted = 0
        K1 = int(block.shape[0])
        for slot, handle, epoch in loop_members:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                wasted += K1  # phase-1 EOS/length/cancel: device free-ran
                continue
            for j in range(K1):
                token = int(block[j, slot])
                if token < 0:  # device stop mask
                    wasted += K1 - j
                    break
                self._deliver(handle, token)
                if handle.finished:  # EOS (host view) / length / cancel
                    wasted += K1 - j - 1
                    break
        if wasted:
            self.metrics.inc("finchat_decode_loop_wasted_tail_tokens_total", wasted)
        self.metrics.set_gauge("finchat_batch_occupancy", len(self.decoding))

    def _deliver(self, handle: SequenceHandle, token_id: int) -> None:
        now = time.perf_counter()
        if handle.last_token_at is not None:
            # the instrument behind the mixed step's admission-stall win
            # (ISSUE 4): inter-token gaps split by whether this iteration
            # also ran prefill work (admission) or not (steady decode).
            # Deliberately stamped at CONSUME time, not dispatch time: the
            # loop awaits the prefill round BEFORE consuming the in-flight
            # step, so a gap ending at this delivery spans the consuming
            # iteration's prefill work — a step dispatched in steady
            # decode but delivered behind an admission's prefill round WAS
            # stretched by it, and must land in the "yes" series
            self.metrics.observe(
                "finchat_inter_token_seconds", now - handle.last_token_at,
                labels={"prefill_concurrent": "yes" if self._iter_ran_prefill else "no"},
                trace_id=handle.trace_id,
            )
        handle.last_token_at = now
        handle._emit_first_token_metrics()
        handle.generated += 1
        handle.history.append(token_id)
        if handle.ngram_index is not None:
            handle.ngram_index.push(token_id)
        self.metrics.inc("finchat_tokens_generated_total")
        if token_id == self.eos_id:
            self._evict(handle, "eos")
        elif handle.generated >= handle.sampling.max_new_tokens:
            handle.events.put_nowait({"type": "token", "token_id": token_id})
            self._evict(handle, "length")
        else:
            handle.events.put_nowait({"type": "token", "token_id": token_id})

    def _dispatch_decode(
        self, exclude: set[int] = frozenset(),
        membership: list[tuple[int, SequenceHandle, int]] | None = None,
    ) -> _InFlightStep:
        """Enqueue one decode step on the device; returns without syncing.

        ``exclude`` slots ride the step INACTIVE (KV writes trash-redirected,
        ``context_lens`` frozen, no token delivered) — used for
        grammar-constrained slots whose host-side pick from the previous
        step has not landed yet, so unconstrained streams keep the depth-2
        pipeline cadence while a tool decision is in flight.

        ``membership`` pins the step to an EXPLICIT (slot, handle, epoch)
        snapshot instead of re-reading ``self.decoding`` — the PR 5 epoch
        discipline applied to dispatch BUILDING: _dispatch_decode_loop
        passes its demoted subset so both of the iteration's dispatches
        derive from the same snapshot (see the regression note there)."""
        inject("scheduler.decode", replica=self.replica_id)
        eng = self.engine
        B = eng.engine_cfg.max_seqs
        if membership is None:
            membership = [
                (slot, h, h.epoch) for slot, h in self.decoding.items()
            ]
        active = np.zeros((B,), bool)
        members = []
        for slot, handle, epoch in membership:
            if slot in exclude:
                continue
            active[slot] = True
            members.append((slot, handle, epoch))
            handle.kv_ctx += 1
        # step logits come back to host only while a grammar-constrained
        # sequence is IN this step (a second compiled decode variant), and
        # only the constrained rows are transferred — a [n, vocab] device
        # slice, not the whole batch's [B, vocab].
        constrained_slots = sorted(
            slot for slot, h, _e in members if h.constraint is not None
        )
        need_logits = bool(constrained_slots)
        result = eng.decode(
            jnp.asarray(active),
            jnp.asarray(self._temperature),
            jnp.asarray(self._top_p),
            jnp.asarray(self._top_k),
            return_logits=need_logits,
        )
        self._tally_dispatch()
        if TRACER.enabled:
            self._trace_dispatch(
                "decode",
                [[slot, h.trace_id or h.seq_id, "decode"]
                 for slot, h, _e in members],
            )
        next_tokens, logits = result if need_logits else (result, None)
        if logits is not None:
            logits = logits[jnp.asarray(constrained_slots, jnp.int32)]
        return _InFlightStep(
            tokens=next_tokens, logits=logits,
            members=members,
            constrained_slots=constrained_slots,
        )

    def _undelivered(self, inflight) -> dict[int, int]:
        """Per-slot token count already dispatched in the still-unconsumed
        in-flight step/block. ``handle.generated`` lags by exactly this
        amount at the next dispatch (depth-2 dispatches N+1 BEFORE
        consuming N), so budget eligibility must subtract it — otherwise a
        slot with K tokens left would ride TWO consecutive blocks and the
        second one's K in-place appends would run past its page
        allocation."""
        if inflight is None:
            return {}
        if isinstance(inflight, _InFlightRing):
            # the staged plan's max emissions per slot (budget already
            # consumed deterministically at staging time)
            return dict(inflight.ahead)
        if isinstance(inflight, _InFlightBlock):
            ahead = {slot: self.loop_depth for slot, _h, _e in inflight.block_members}
            if inflight.step is not None:
                for slot, _h, _e in inflight.step.members:
                    ahead[slot] = 1
            return ahead
        return {slot: 1 for slot, _h, _e in inflight.members}

    def _loop_eligible(self, handle: SequenceHandle, ahead: int = 0) -> bool:
        """Can this slot ride a fused K-token block? It must need NO
        per-token host control for the next ``loop_depth`` tokens: no
        grammar constraint (host-side picks land between steps) and at
        least K tokens of ``max_new_tokens`` budget left beyond the
        ``ahead`` tokens still undelivered in the in-flight dispatch (its
        page allocation covers prompt + max_new, so the budget check also
        bounds the block's in-place KV appends). Slots that fail are
        DEMOTED to the single-step decode riding the same iteration and
        rejoin blocks when eligibility returns — the same
        demote-and-reprobe shape as SPEC_MISS_DEMOTE."""
        return (
            handle.constraint is None
            and handle.sampling.max_new_tokens - handle.generated - ahead
            >= self.loop_depth
            # bounded rows: the fused span must not cross the next
            # eviction boundary (see _bounded_span_room) — the row rides
            # single-step for that iteration and rejoins after the wave
            and self._bounded_span_room(handle) >= self.loop_depth
        )

    def _dispatch_decode_loop(
        self, exclude: set[int] = frozenset(),
        ahead: dict[int, int] | None = None,
    ) -> _InFlightBlock:
        """Enqueue one fused K-token decode block (plus a single decode
        step for any demoted slots) on the device; returns without
        syncing. The caller guarantees at least one non-excluded
        loop-eligible slot. ``exclude`` slots (constrained picks still in
        flight) ride fully inactive, exactly as in _dispatch_decode;
        ``ahead`` is _undelivered() for the in-flight dispatch.

        ONE membership snapshot drives BOTH dispatches (regression,
        ISSUE 10 satellite): the demoted-slot step used to be rebuilt
        from ``self.decoding`` AFTER the block dispatch
        (``exclude=set(self.decoding) - demoted``), so a slot vacated by
        a mid-iteration fault handler and re-populated before the second
        dispatch would be swept into the demoted step under a handle that
        was never in this iteration's membership — stepped once by the
        stale exclusion math and again by its own next iteration
        (double-step). The snapshot pins both dispatches to the same
        (slot, handle, epoch) view, the PR 5 discipline membership
        CONSUMPTION already used."""
        inject("scheduler.decode", replica=self.replica_id)
        eng = self.engine
        ahead = ahead or {}
        B = eng.engine_cfg.max_seqs
        membership = [
            (slot, h, h.epoch) for slot, h in self.decoding.items()
        ]
        active = np.zeros((B,), bool)
        block_members = []
        demoted: list[tuple[int, SequenceHandle, int]] = []
        for slot, handle, epoch in membership:
            if slot in exclude:
                continue
            if self._loop_eligible(handle, ahead.get(slot, 0)):
                active[slot] = True
                block_members.append((slot, handle, epoch))
                handle.kv_ctx += self.loop_depth
            else:
                demoted.append((slot, handle, epoch))
        token_block = eng.decode_loop(
            jnp.asarray(active),
            jnp.asarray(self._temperature),
            jnp.asarray(self._top_p),
            jnp.asarray(self._top_k),
            eos_id=self.eos_id,
        )
        self._tally_dispatch()
        if TRACER.enabled:
            self._trace_dispatch(
                "decode_loop",
                [[slot, h.trace_id or h.seq_id, "decode_loop"]
                 for slot, h, _e in block_members],
            )
        self.metrics.inc("finchat_decode_loop_blocks_total")
        self.metrics.set_gauge("finchat_decode_loop_demoted_slots", len(demoted))
        step = None
        if demoted:
            # demoted slots advance one token via the plain step, built
            # from the SAME snapshot as the block (never re-read from
            # self.decoding — see the docstring's double-step regression)
            step = self._dispatch_decode(membership=demoted)
        return _InFlightBlock(
            block_tokens=token_block, block_members=block_members, step=step
        )

    async def _consume_block(self, blk: _InFlightBlock) -> None:
        """Fetch a dispatched block's ``[K, max_seqs]`` tokens (one
        device→host round-trip for K steps' worth of output) and drain each
        member slot's row: deliver until EOS/length finishes the sequence
        or a -1 sentinel marks where the device's stop mask kicked in.
        Device iterations spent free-running past a finished slot are the
        price of the fixed-shape block — counted as wasted tail tokens."""
        tokens_host = await asyncio.to_thread(
            lambda: np.asarray(blk.block_tokens)
        )
        K = tokens_host.shape[0]
        wasted = 0
        for slot, handle, epoch in blk.block_members:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                wasted += K  # evicted/cancelled/preempted since dispatch
                continue
            for i in range(K):
                token = int(tokens_host[i, slot])
                if token < 0:  # device stop mask: EOS'd at i-1, free-ran
                    wasted += K - i
                    break
                self._deliver(handle, token)
                if handle.finished:  # EOS (host view) / length / cancel
                    wasted += K - i - 1
                    break
        if wasted:
            self.metrics.inc("finchat_decode_loop_wasted_tail_tokens_total", wasted)
        if blk.step is not None:
            await self._consume_step(blk.step)
        self.metrics.set_gauge("finchat_batch_occupancy", len(self.decoding))

    @staticmethod
    def _spec_eligible(handle: SequenceHandle) -> bool:
        """Can this slot benefit from drafts? Greedy, unconstrained, and at
        least 2 tokens to go (a draft needs room for itself + the bonus)."""
        return (
            handle.constraint is None
            and handle.sampling.temperature <= 0.0
            and handle.sampling.max_new_tokens - handle.generated >= 2
        )

    def _spec_candidates(self) -> bool:
        """True when at least one decoding slot can benefit from a verify
        step — otherwise the pipelined depth-2 decode path is strictly
        better."""
        return any(self._spec_eligible(h) for h in self.decoding.values())

    def _spec_proposal_live(self) -> bool:
        """Would the spec path actually PROPOSE drafts this round? The
        probe mirrors ``_run_spec_step``'s proposal loop exactly — lazy
        one-time ``NgramIndex`` build included (``_deliver`` keeps the
        index in sync afterwards, so building here is the same build the
        spec step would do), same span cap, same ``propose`` lookup
        (read-only). Eligibility alone (``_spec_candidates``) is NOT a
        live proposal window: an eligible slot whose n-gram lookup misses
        would make ``_run_spec_step`` fall back to the plain decode round
        anyway, so capping a free-run capture for it threw away F-1
        captured rounds for nothing — the streams are byte-identical
        either way (spec verify is greedy-exact)."""
        from finchat_tpu.engine.spec import NgramIndex

        Kd = self.spec_k
        for handle in self.decoding.values():
            if not self._spec_eligible(handle):
                continue
            if handle.ngram_index is None:
                handle.ngram_index = NgramIndex(handle.history)
            remaining = handle.sampling.max_new_tokens - handle.generated
            cap = min(Kd, remaining - 1, self._bounded_span_room(handle) - 1)
            if cap > 0 and handle.ngram_index.propose(cap):
                return True
        return False

    def _constrained_pick(self, handle: SequenceHandle, row_logits) -> int:
        """Host-side grammar pick for one constrained slot: choose the
        token, write it back as the slot's next decode input, and return
        it for delivery. The ONE place the pick's sampling arguments are
        threaded (called from prefill completion, pipelined consume, and
        the spec path)."""
        s = handle.sampling
        token = handle.constraint.pick(
            row_logits, s.temperature, self._rng,
            remaining=s.max_new_tokens - handle.generated,
            top_p=s.top_p, top_k=s.top_k,
        )
        self.engine.set_last_token(handle.slot, token)
        return token

    def _spec_note_step(self, *, accepted: int) -> None:
        """Track the zero-accept streak behind the spec path's demotion:
        SPEC_MISS_DEMOTE consecutive steps with no accepted draft tokens
        put the loop back on the pipelined depth-2 path for
        SPEC_RETRY_EVERY steps (the depth-1 verify cadence only pays for
        itself when drafts land — see class constants)."""
        if accepted > 0:
            self._spec_miss_streak = 0
            return
        self._spec_miss_streak += 1
        if self._spec_miss_streak >= self.SPEC_MISS_DEMOTE:
            self._spec_miss_streak = 0
            self._spec_cooldown = self.SPEC_RETRY_EVERY
            self.metrics.inc("finchat_spec_demotions_total")

    async def _run_spec_step(self) -> None:
        """One speculative verify step: propose drafts from each greedy
        slot's n-gram index, score them all in one forward, deliver the
        accepted prefix + bonus token per slot. Depth-1 by necessity (the
        drafts extend the LAST delivered token); acceptance makes up for
        the lost overlap by committing up to Kd+1 tokens per weights-read.
        """
        from finchat_tpu.engine.spec import NgramIndex

        if not self.decoding:
            return  # consuming the drained pipeline step may have evicted all
        inject("scheduler.decode", replica=self.replica_id)
        eng = self.engine
        B = eng.engine_cfg.max_seqs
        Kd = self.spec_k
        active = np.zeros((B,), bool)
        drafts = np.zeros((B, Kd), np.int32)
        n_drafts = np.zeros((B,), np.int32)
        members = []
        for slot, handle in self.decoding.items():
            active[slot] = True
            members.append((slot, handle, handle.epoch))
            if self._spec_eligible(handle):
                if handle.ngram_index is None:  # one-time build; _deliver
                    handle.ngram_index = NgramIndex(handle.history)  # keeps it in sync
                remaining = handle.sampling.max_new_tokens - handle.generated
                # bounded rows: the verify span must fit the
                # eviction-boundary room (see _bounded_span_room) —
                # computed BEFORE the kv_ctx bump, at the span's start
                cap = min(Kd, remaining - 1,
                          self._bounded_span_room(handle) - 1)
                prop = handle.ngram_index.propose(cap) if cap > 0 else []
                drafts[slot, : len(prop)] = prop
                n_drafts[slot] = len(prop)
        if not n_drafts.any():
            # every candidate missed its n-gram lookup this step: a
            # Kd+1-wide verify forward would cost K× the query compute for
            # an unconditional n_emitted == 1 — run the plain (cheaper,
            # already-warmed) decode step instead (which does its own
            # kv_ctx accounting — bumping here too would double-count
            # this step and skew the eviction schedule off its positions)
            self._spec_note_step(accepted=0)
            await self._consume_step(self._dispatch_decode())
            return
        for _slot, handle, _epoch in members:
            handle.kv_ctx += 1  # the verify's position-0 write

        constrained_slots = sorted(
            slot for slot, h, _e in members if h.constraint is not None
        )
        need_logits = bool(constrained_slots)
        result = eng.decode_spec(
            jnp.asarray(active), jnp.asarray(drafts), jnp.asarray(n_drafts),
            jnp.asarray(self._temperature),
            jnp.asarray(self._top_p),
            jnp.asarray(self._top_k),
            return_logits=need_logits,
        )
        self._tally_dispatch()
        if TRACER.enabled:
            self._trace_dispatch(
                "spec",
                [[slot, h.trace_id or h.seq_id, "spec"]
                 for slot, h, _e in members],
            )
        emitted, n_emitted, logits = result if need_logits else (*result, None)
        if logits is not None:
            logits = logits[jnp.asarray(constrained_slots, jnp.int32)]

        emitted_host, n_emitted_host, logits_host = await asyncio.to_thread(
            lambda: (
                np.asarray(emitted),
                np.asarray(n_emitted),
                np.asarray(logits) if logits is not None else None,
            )
        )
        accepted_total = 0
        for slot, handle, epoch in members:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                continue  # evicted/cancelled/preempted since dispatch
            if handle.constraint is not None and logits_host is not None:
                token = self._constrained_pick(
                    handle, logits_host[constrained_slots.index(slot)]
                )
                self._deliver(handle, token)
                continue
            n = int(n_emitted_host[slot])
            handle.kv_ctx += max(0, n - 1)  # accepted drafts' context advance
            accepted_total += max(0, n - 1)
            for token in emitted_host[slot, :n]:
                self._deliver(handle, int(token))
                if handle.finished:  # EOS / length inside the prefix
                    break
        if accepted_total:
            self.metrics.inc("finchat_spec_tokens_accepted_total", accepted_total)
        self._spec_note_step(accepted=accepted_total)
        self.metrics.set_gauge("finchat_batch_occupancy", len(self.decoding))

    async def _consume_step(self, step: _InFlightStep) -> None:
        """Fetch a dispatched step's tokens (in a worker thread, so the event
        loop keeps serving) and deliver them to the sequences that were in
        the batch when it was dispatched."""
        tokens_host, logits_host = await asyncio.to_thread(
            lambda: (
                np.asarray(step.tokens),
                np.asarray(step.logits) if step.logits is not None else None,
            )
        )
        eng = self.engine
        for slot, handle, epoch in step.members:
            if handle.finished or handle.slot != slot or handle.epoch != epoch:
                continue  # evicted/cancelled/preempted since dispatch
            if handle.constraint is not None and logits_host is not None:
                token = self._constrained_pick(
                    handle, logits_host[step.constrained_slots.index(slot)]
                )
                self._deliver(handle, token)
            else:
                self._deliver(handle, int(tokens_host[slot]))
        self.metrics.set_gauge("finchat_batch_occupancy", len(self.decoding))

    def _pending_constrained(self, inflight) -> set[int]:
        """Constrained slots whose host-side pick lands only when
        ``inflight`` is consumed — they must sit out the next dispatch.
        In a block, constrained slots only ever ride the demoted step; a
        free-run capture never carries constrained rows (the cap)."""
        if isinstance(inflight, _InFlightRing):
            return set()
        if isinstance(inflight, _InFlightBlock):
            return set(inflight.step.constrained_slots) if inflight.step else set()
        return set(inflight.constrained_slots)

    async def _consume_inflight(self, inflight) -> None:
        if isinstance(inflight, _InFlightRing):
            await self._consume_ring(inflight)
        elif isinstance(inflight, _InFlightBlock):
            await self._consume_block(inflight)
        else:
            await self._consume_step(inflight)

    async def _drain_inflight(self, inflight) -> None:
        """Consume an in-flight dispatch OUTSIDE the decode try-block
        (idle drain, pre-mixed drain, pre-preemption drain), converting a
        failure into the whole-round recovery path instead of letting it
        kill the scheduler task. A failed consume is never retried — a
        partially-consumed step cannot be told apart from an unconsumed
        one, and preempt/replay recomputes the undelivered tokens.
        Always returns None (the caller's new ``inflight``)."""
        try:
            await self._consume_inflight(inflight)
            self._note_round_ok("decode")
            if isinstance(inflight, _InFlightRing):
                # a captured run carried the prefill rows too: its drain
                # is a successful round of BOTH planes
                self._note_round_ok("prefill")
        except Exception as e:
            logger.error("in-flight step consume error: %s", e)
            scope = "mixed" if isinstance(inflight, _InFlightRing) else "decode"
            await self._round_failed(scope, str(e))
        return None

    async def _loop(self) -> None:
        logger.info("scheduler loop started (max_seqs=%d)", self.engine.engine_cfg.max_seqs)
        inflight: _InFlightStep | _InFlightBlock | None = None
        while self._running:
            self._reap_stale_holds()
            # attribute the previous coexist iteration's dispatches at the
            # top of EVERY iteration (idle ones included), so the last
            # coexist iteration before a quiet period is still booked
            if self._coexist_mark is not None:
                self.metrics.inc("finchat_coexist_dispatches_total",
                                 self._dispatch_tally - self._coexist_mark)
                # ...and the logical ROUNDS those dispatches advanced (a
                # captured free-run books F rounds for its 1 dispatch) —
                # together the exact dispatches-per-round ratio (ISSUE 13)
                self.metrics.inc("finchat_coexist_rounds_total",
                                 self._round_tally - self._coexist_round_mark)
                self._coexist_mark = None
            # parked holds (prefix prefilled, waiting for extend_prompt)
            # are not work: without the _prefill_work() refinement the
            # loop would busy-spin for the whole retrieval latency
            if not (self.pending or self.decoding or self._prefix_jobs
                    or self._prefill_work()):
                if inflight is not None:  # drain the pipeline before idling
                    self._iter_ran_prefill = False
                    inflight = await self._drain_inflight(inflight)
                    continue
                self._wakeup.clear()
                try:
                    await asyncio.wait_for(self._wakeup.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    pass
                continue

            try:
                # page-pressure preemption (ISSUE 5): planned BEFORE any
                # dispatch and executed only after the in-flight step is
                # drained, so a freed page can never still be the target of
                # queued device writes
                victims = self._preemption_plan()
                if victims:
                    if inflight is not None:
                        inflight = await self._drain_inflight(inflight)
                        # consuming may have retired slots / freed pages
                        # (or, on a drain failure, preempted the victims
                        # already) — recompute the plan either way
                        victims = self._preemption_plan()
                    cand = self.pending[0].seq_id if self.pending else "?"
                    for victim in victims:
                        logger.info(
                            "page pressure: preempting %s (deadline %.3f) for %s",
                            victim.seq_id, victim.deadline or float("inf"), cand,
                        )
                        self._preempt(victim)
                self._admit()
                # bounded-KV eviction wave (ISSUE 15): runs BETWEEN
                # dispatches — the page-table/gap updates enqueue after
                # every in-flight program and before this iteration's
                # dispatch, so device stream order keeps each program
                # reading the table it was staged against; the freed
                # pages' next writers are ordered after it too
                self._bounded_evict_wave()
            except Exception as e:
                # admission must never kill the loop (e.g. device state
                # mid-rebuild-failure): log, back off, keep serving what
                # still runs
                logger.error("admission error: %s", e)
                await asyncio.sleep(0.05)

            prefill_active = bool(self._prefix_jobs) or self._prefill_work()
            # label for the inter-token histogram, and the denominator for
            # the dispatches-per-iteration figure bench --ragged-sweep
            # reports: iterations where prefill work and in-flight decodes
            # coexist are exactly where the ragged step's >=2→1 fusion
            # applies. The mark/attribute pair books every dispatch from a
            # coexist iteration's start to the next accounting point into
            # finchat_coexist_dispatches_total — an exact numerator for
            # dispatches-per-coexist-iteration.
            self._iter_ran_prefill = prefill_active
            if prefill_active and self.decoding:
                self.metrics.inc("finchat_coexist_iterations_total")
                self._coexist_mark = self._dispatch_tally
                self._coexist_round_mark = self._round_tally

            if self._spec_cooldown > 0:
                # demoted after sustained all-miss steps: count pipelined
                # steps down to the next spec re-probe
                self._spec_cooldown -= 1

            if self._use_mixed():
                rounds = self._freerun_rounds_cap()
                if rounds > 1:
                    # free-running loop (ISSUE 13), depth-2: stage and
                    # dispatch the next captured multi-round program FIRST,
                    # then drain the previous in-flight dispatch's tokens —
                    # the host delivers to streams while the device is
                    # mid-flight on the later rounds. Membership events in
                    # between (admit/evict/preempt/breaker) end re-entry at
                    # this round boundary: the next iteration re-stages
                    # from the new snapshot, and stale residual ring
                    # tokens replay exactly once via the epoch discipline.
                    ring = None
                    try:
                        ring = self._dispatch_freerun(
                            rounds, self._undelivered(inflight))
                    except Exception as e:
                        logger.error("freerun dispatch error: %s", e)
                        if inflight is not None:
                            inflight = await self._drain_inflight(inflight)
                        await self._round_failed("mixed", str(e))
                        await asyncio.sleep(0)
                        continue
                    if ring is not None:
                        prev, inflight = inflight, ring
                        if prev is not None:
                            await self._drain_inflight(prev)
                        await asyncio.sleep(0)  # let producers/consumers run
                        continue
                    # staging underfilled: fall through to the host-stepped
                    # single round below
                # the host-stepped mixed path is depth-1 (dispatch + consume
                # within the iteration — the prefill side was synchronous in
                # the split path too): drain any pipelined leftover first
                if inflight is not None:
                    inflight = await self._drain_inflight(inflight)
                if self._use_mixed():  # consuming may have evicted slots
                    try:
                        await self._ragged_round()
                        self._note_round_ok("decode")
                        self._note_round_ok("prefill")
                    except Exception as e:
                        # not attributable to one sequence: the round's
                        # prefill rows AND decode members rode the same
                        # dispatch — recover them together (preempt/replay
                        # under the breaker, legacy eviction without it)
                        logger.error("mixed step error: %s", e)
                        await self._round_failed("mixed", str(e))
                    await asyncio.sleep(0)  # let producers/consumers run
                    continue

            if isinstance(inflight, _InFlightRing):
                # leaving the mixed path with a captured run still in
                # flight (the decode side was cancelled/evicted, or a
                # ring-routed admission demoted the iteration): the ring
                # must drain BEFORE any split-path round. A prompt that
                # completed INSIDE the capture is still in `prefilling`
                # until the drain flips it to decoding — a split prefill
                # round running first would re-complete it on an empty
                # chunk (a garbage duplicate first token off an all-padding
                # logits row, then the drain's flip raises). Regression:
                # tests/test_freerun.py
                # test_freerun_cancel_mid_capture_spares_completions.
                inflight = await self._drain_inflight(inflight)

            # one batched prefill round (all prefilling sequences advance a
            # chunk together), interleaved with decode so TTFT work cannot
            # starve in-flight streams
            if self.prefilling or self._prefix_jobs:
                try:
                    await self._prefill_round()
                    self._note_round_ok("prefill")
                except Exception as e:
                    logger.error("prefill round error: %s", e)
                    await self._round_failed("prefill", str(e))
                try:
                    # a completion flips straight into THIS iteration's
                    # decode dispatch below: its first decode write needs
                    # the wave's capacity guarantee at the advanced
                    # kv_ctx — without it, a completion landing exactly on
                    # a full page list would trash-write its first decode
                    # KV. Idempotent; no-op when no boundary was crossed.
                    self._bounded_evict_wave()
                except Exception as e:
                    logger.error("bounded eviction wave error: %s", e)

            if (
                self.decoding and self.spec_k > 0
                and self._spec_cooldown == 0 and self._spec_candidates()
            ):
                try:
                    # speculative decode is depth-1: constrained picks land
                    # before the next dispatch, so no slot ever sits a step
                    # out. Drain any pipelined step left over from the
                    # depth-2 path before switching modes.
                    if inflight is not None:
                        await self._consume_inflight(inflight)
                        inflight = None
                    await self._run_spec_step()
                    self._note_round_ok("decode")
                except Exception as e:
                    logger.error("spec decode step error: %s", e)
                    inflight = None
                    await self._round_failed("spec", str(e))
            elif self.decoding:
                try:
                    # a grammar-constrained slot's next input comes from a
                    # host-side pick that lands when its step is CONSUMED —
                    # so such a slot sits out the speculative step dispatched
                    # before that consume (it rejoins the following one,
                    # advancing every other step). Unconstrained slots keep
                    # the full depth-2 cadence throughout (verdict r3 #6).
                    # a decode round counts OK only when a consume actually
                    # succeeded: dispatch-only iterations (inflight was None
                    # right after a failure) must not reset the streak, or a
                    # device whose errors surface at the host FETCH would
                    # oscillate the streak 0↔1 and never trip the breaker
                    consumed = False
                    pending = self._pending_constrained(inflight) if inflight is not None else set()
                    ahead = self._undelivered(inflight)
                    use_loop = self.loop_depth > 1 and any(
                        slot not in pending
                        and self._loop_eligible(h, ahead.get(slot, 0))
                        for slot, h in self.decoding.items()
                    )
                    if use_loop:
                        # decode_loop mode, same depth-2 shape: dispatch
                        # block N+1 (loop-eligible slots fused K steps,
                        # demoted slots one plain step, pending constrained
                        # slots out entirely), then consume block N — the
                        # device runs K decode iterations while the host
                        # delivers the previous K tokens per slot
                        blk = self._dispatch_decode_loop(exclude=pending, ahead=ahead)
                        if inflight is not None:
                            await self._consume_inflight(inflight)
                            consumed = True
                        inflight = blk
                    elif any(slot not in pending for slot in self.decoding):
                        # depth-2 pipeline: dispatch N+1 (sans pending
                        # constrained slots), then consume N — the device
                        # computes while the host delivers tokens
                        step = self._dispatch_decode(exclude=pending)
                        if inflight is not None:
                            await self._consume_inflight(inflight)
                            consumed = True
                        inflight = step
                    else:
                        # every decoding slot is waiting on a host pick:
                        # drain, then run depth-1
                        if inflight is not None:
                            await self._consume_inflight(inflight)
                            inflight = None
                            consumed = True
                        if self.decoding:
                            await self._consume_step(self._dispatch_decode())
                            consumed = True
                    if consumed:
                        self._note_round_ok("decode")
                except Exception as e:
                    # a whole-batch failure is not attributable to one
                    # sequence: recover all in-flight decodes together
                    # (preempt/replay under the breaker, legacy eviction
                    # without it), keep serving. The dropped in-flight
                    # dispatch is never re-consumed — it may be partially
                    # delivered, and replay recomputes the rest anyway.
                    logger.error("decode step error: %s", e)
                    inflight = None
                    await self._round_failed("decode", str(e))
            elif inflight is not None:
                inflight = await self._drain_inflight(inflight)

            await asyncio.sleep(0)  # let producers/consumers run
        logger.info("scheduler loop stopped")
