"""Inference engine: compiled prefill/decode step functions over the paged
KV cache.

TPU-first shape discipline (SURVEY §7.3 hard part #2): every jitted entry
point has ONE static shape per (batch-bucket) —

- ``prefill_step``: ``N × prefill_chunk`` tokens — N sequences advance one
  chunk together (batched prefill; a 64-session burst is a handful of
  steps, not 64 serial weight-reads — the round-3 bench measured 8.6 s for
  64×128-token prompts through the old one-sequence-at-a-time path).
  Arbitrary prompt lengths become rounds of fixed-size chunks (chunked
  prefill, SURVEY §5.7a) so there is no bucketing recompile storm;
  exhausted prompts ride later rounds with ``n_valid = 0``.
- ``decode_step``: the full ``max_seqs`` slot batch, every step. Inactive
  slots ride along writing their KV to the trash page.
- ``decode_loop_step``: the same slot batch, ``decode_loop_depth`` fused
  decode iterations per dispatch (on-device sampling + per-slot EOS mask
  inside a ``fori_loop``) — the host pays one dispatch and one
  ``[K, max_seqs]`` token fetch per K tokens instead of per token.
- ``ragged_mixed_step``: ONE packed ragged dispatch advancing every
  prefilling sequence a chunk, every decoding slot a token, every
  spec-decode slot a (1+Kd)-token verify block, and every loop-eligible
  slot a fused K-token tail — rows of a PACKED token buffer
  (ops/ragged_paged_attention.py), each carrying its own length, page
  list, and sampling params, with on-device sampling preserved
  throughout. The scheduler's mixed path (engine.mixed_step config,
  default on) cuts a coexisting iteration from two-or-more serialized
  model dispatches to one, with no per-mode demotions (ISSUE 10; PR 4's
  padded ``[rows, chunk]`` buffer demoted on spec/loop/constrained work
  and paid dense decode-row compute per padded column).
- ``ragged_multi_round``: the free-running loop (ISSUE 13) — up to
  ``freerun_rounds`` consecutive ragged rounds captured as ONE device
  program (``lax.scan`` over the same round body), with a staged
  descriptor queue the rounds drain in order, on-device EOS stop masks
  generalized to every row, and a per-round output token ring the host
  drains asynchronously; host control returns only at membership epochs.

State is donated on every call and the KV cache is updated IN PLACE by the
Pallas append kernel (ops/kv_append.py) on the decode path — XLA's scatter
would copy the multi-GB cache every token (measured ~22 ms/step, round 4).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import Array

from finchat_tpu.engine.kv_cache import (
    PagedKVCache,
    scatter_kv_chunk,
)
from finchat_tpu.engine.sampler import sample
from finchat_tpu.models.llama import LlamaConfig, forward, lm_head
from finchat_tpu.ops.dispatch import paged_attention
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def round_up_pow2(n: int) -> int:
    """The batch/shape padding policy shared by the scheduler's prefill
    rounds, warmup's variant enumeration, and ring-prefill length buckets —
    ONE rule so startup warmup always covers what serving dispatches."""
    p = 1
    while p < n:
        p *= 2
    return p


@jax.tree_util.register_dataclass
@dataclass
class DecodeState:
    """Device-resident engine state (a pytree; all leaves are arrays).

    ``k_scales``/``v_scales`` are the int8-KV-cache scale arrays
    (kv_cache.py); (1,1,1,1) placeholders when kv_quant is off so the
    pytree structure is mode-independent.

    ``kv_gaps`` is the bounded-KV compaction offset per slot (ISSUE 15;
    kv_cache.BoundedKVPolicy): tokens the eviction policy has dropped from
    the slot's page list, always a whole-page multiple, 0 for unbounded
    rows. ``context_lens`` stays ABSOLUTE (it feeds rotary positions);
    every KV write offset and attention mask runs at the COMPACTED
    position ``absolute - kv_gaps[slot]``, so the surviving sink+window
    pages pack the front of the page list and an evicted page simply
    stops being referenced. All zeros reduces every compacted expression
    to the legacy absolute one bit-for-bit."""

    k_pages: Array  # [L, P, page_size, Hkv*hd] (model dtype, or int8)
    v_pages: Array
    k_scales: Array  # [L, P, scale_rows, page_size] fp32 (or (1,1,1,1))
    v_scales: Array
    page_table: Array  # [max_seqs, max_pages_per_seq] int32 (0 = trash)
    context_lens: Array  # [max_seqs] int32 — ABSOLUTE tokens seen (rotary)
    last_tokens: Array  # [max_seqs] int32 — next decode input per slot
    kv_gaps: Array  # [max_seqs] int32 — evicted tokens (bounded KV; 0 = none)
    rng: Array


def create_state(
    config: LlamaConfig, engine_cfg: EngineConfig, max_pages_per_seq: int,
    kv_quant: str = "",
) -> DecodeState:
    cache = PagedKVCache.create(
        config, engine_cfg.num_pages, engine_cfg.page_size, kv_quant=kv_quant
    )
    return DecodeState(
        k_pages=cache.k_pages,
        v_pages=cache.v_pages,
        k_scales=cache.k_scales,
        v_scales=cache.v_scales,
        page_table=jnp.zeros((engine_cfg.max_seqs, max_pages_per_seq), jnp.int32),
        context_lens=jnp.zeros((engine_cfg.max_seqs,), jnp.int32),
        last_tokens=jnp.zeros((engine_cfg.max_seqs,), jnp.int32),
        kv_gaps=jnp.zeros((engine_cfg.max_seqs,), jnp.int32),
        rng=jax.random.key(engine_cfg.max_seqs),
    )


def _paged_attention_fn(
    page_table: Array, start_pos: Array, n_valid: Array,
    page_size: int, n_kv: int, attn_backend: str,
    inplace_append: bool = False,
):
    """Build the model's attention callback for paged prefill/decode.

    ``page_table`` [B, max_pages], ``start_pos`` [B] (absolute position of
    the first query token), ``n_valid`` [B] (real tokens in this chunk; 0
    for inactive decode slots). The callback receives the FULL-depth cache
    (carried through the layer scan) plus the layer index.

    ``inplace_append`` forces the in-place page-RMW write path for C > 1
    (one single-token append per chunk position) — used by the speculative
    verify step, whose few-token chunks would otherwise pay the scatter's
    full-cache copy every step, exactly what the append kernel exists to
    avoid.
    """
    interpret = True if attn_backend == "pallas-interpret" else None

    def attention(q: Array, k: Array, v: Array, cache: Any, layer_idx: Array):
        from finchat_tpu.utils.tracing import named_scope

        k_pages, v_pages, k_scales, v_scales = cache
        quantized = k_pages.dtype == jnp.int8  # static under trace
        B, C = k.shape[:2]
        layer = layer_idx.reshape(1)
        if (C == 1 or inplace_append) and attn_backend != "ref":
            # decode / spec verify: in-place single-page RMW appends (no
            # cache copy); token i of the chunk is valid iff i < n_valid
            with named_scope("kv_append"):
                for i in range(C):
                    kv_new = jnp.concatenate(
                        [k[:, i].reshape(B, 1, -1), v[:, i].reshape(B, 1, -1)],
                        axis=-1,
                    )
                    i_valid = (i < n_valid).astype(jnp.int32)
                    if quantized:
                        from finchat_tpu.ops.kv_append import paged_kv_append_q8

                        k_pages, v_pages, k_scales, v_scales = paged_kv_append_q8(
                            kv_new, k_pages, v_pages, k_scales, v_scales,
                            page_table, start_pos + i, i_valid, layer,
                            page_size=page_size, n_kv=n_kv, interpret=interpret,
                        )
                    else:
                        from finchat_tpu.ops.kv_append import paged_kv_append

                        k_pages, v_pages = paged_kv_append(
                            kv_new, k_pages, v_pages, page_table, start_pos + i,
                            i_valid, layer, page_size=page_size, interpret=interpret,
                        )
        else:
            # prefill chunk (or jnp reference path): XLA scatter — one
            # cache copy amortized over the whole batched chunk
            with named_scope("kv_scatter"):
                k_pages, v_pages, k_scales, v_scales = _scatter_kv(
                    (k_pages, v_pages, k_scales, v_scales), k, v,
                    page_table, start_pos, n_valid, page_size, layer_idx, n_kv,
                )
        with named_scope("paged_attention"):
            out = paged_attention(
                q, k_pages, v_pages, page_table, start_pos, start_pos + n_valid,
                layer, page_size=page_size, n_kv=n_kv, backend=attn_backend,
                k_scales=k_scales if quantized else None,
                v_scales=v_scales if quantized else None,
            )
        return out, (k_pages, v_pages, k_scales, v_scales)

    return attention


@partial(jax.jit, static_argnames=("config", "page_size", "attn_backend", "qm_backend"), donate_argnums=(1,))
def prefill_step(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [N, C] — one chunk of N sequences' prompts
    slots: Array,  # [N] int32
    start_pos: Array,  # [N] int32 — absolute position of tokens[i, 0]
    n_valid: Array,  # [N] int32 — real tokens in this chunk per sequence
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
) -> tuple[DecodeState, Array]:
    """Run one prefill chunk for N sequences; returns (state,
    last-valid-token logits [N, vocab])."""
    N, C = tokens.shape
    positions = start_pos[:, None] + jnp.arange(C)[None, :]  # [N, C] — rotary
    page_rows = state.page_table[slots]  # [N, max_pages]

    # KV writes and masking run COMPACTED (bounded KV, ISSUE 15): a row
    # whose policy evicted kv_gaps[slot] tokens writes this chunk
    # kv_gaps[slot] positions earlier in its (compacted) page list, while
    # the rotary positions above stay absolute. Zero gaps = identity.
    attention = _paged_attention_fn(
        page_rows, start_pos - state.kv_gaps[slots], n_valid,
        page_size, config.n_kv_heads, attn_backend
    )
    # hidden states only, then project just each sequence's last valid row:
    # full-chunk fp32 logits would be [N, C, vocab] — 4.2 GB for the 8B
    # bench shape (64 x 128 x 128256) — vs 33 MB for [N, vocab]
    hidden, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        return_hidden=True, qm_backend=qm_backend,
    )
    last_hidden = jnp.take_along_axis(
        hidden, jnp.maximum(n_valid - 1, 0)[:, None, None], axis=1
    )[:, 0]  # [N, D]
    last_logits = lm_head(params, last_hidden, config=config,
                          qm_backend=qm_backend)  # [N, vocab]

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens.at[slots].add(n_valid),
    )
    return new_state, last_logits


def _scatter_kv(cache, k, v, page_table, start_pos, n_valid, page_size,
                layer_idx, n_kv):
    """Write one chunk's K/V into the paged cache (XLA scatter),
    dispatching on the cache dtype — the ONE place the int8-vs-native
    write choice lives for the scatter paths (chunked prefill, ring
    prefill, ring segments)."""
    k_pages, v_pages, k_scales, v_scales = cache
    if k_pages.dtype == jnp.int8:
        from finchat_tpu.engine.kv_cache import scatter_kv_chunk_q8

        return scatter_kv_chunk_q8(
            k_pages, v_pages, k_scales, v_scales, k, v,
            page_table, start_pos, n_valid, page_size, layer_idx, n_kv,
        )
    k_pages, v_pages = scatter_kv_chunk(
        k_pages, v_pages, k, v, page_table, start_pos, n_valid,
        page_size, layer_idx,
    )
    return k_pages, v_pages, k_scales, v_scales


def _ring_prefill_attention_fn(mesh, page_table: Array, start_pos: Array, n_valid: Array,
                               page_size: int, n_kv: int, sp_mode: str = "ring"):
    """Attention callback for the seq-sharded long-prompt prefill: SP
    attention over the ``seq`` mesh axis for the compute — ring (K/V blocks
    rotate the ICI ring) or Ulysses (all-to-all head scatter, SURVEY
    §5.7d) per ``sp_mode`` — and an XLA scatter for the cache write (one
    cache copy amortized over the WHOLE prompt)."""

    def attention(q: Array, k: Array, v: Array, cache: Any, layer_idx: Array):
        k_pages, v_pages, k_scales, v_scales = cache
        if sp_mode == "ulysses":
            from finchat_tpu.ops.ulysses import ulysses_attention

            out = ulysses_attention(
                q, k, v, mesh=mesh, axis="seq", head_axis="model", causal=True
            )
        else:
            from finchat_tpu.ops.ring_attention import ring_attention

            out = ring_attention(
                q, k, v, mesh=mesh, axis="seq", head_axis="model", causal=True
            )
        cache = _scatter_kv(
            (k_pages, v_pages, k_scales, v_scales), k, v,
            page_table, start_pos, n_valid, page_size, layer_idx, n_kv,
        )
        return out, cache

    return attention


def _ring_segment_attention_fn(mesh, page_table: Array, prefix_pages: int,
                               start_pos: Array, n_valid: Array,
                               page_size: int, n_kv: int,
                               sp_mode: str = "ring"):
    """Attention callback for ONE SEGMENT of a chunked seq-sharded
    prefill: the segment's Q/K/V SP-attend over the ``seq`` axis — ring
    or Ulysses per ``sp_mode`` — while the ALREADY-CACHED earlier
    segments are gathered from their pages and folded into the
    online-softmax carry (ops/ring_attention.py
    ``ring_attention_with_prefix`` / ops/ulysses.py
    ``ulysses_attention_with_prefix``). This is what lets the scheduler
    run a long SP prefill in rounds interleaved with decode steps —
    killing the every-stream stall of the monolithic path — without
    losing cross-segment attention."""

    def attention(q: Array, k: Array, v: Array, cache: Any, layer_idx: Array):
        from finchat_tpu.engine.kv_cache import gather_kv_any
        if sp_mode == "ulysses":
            from finchat_tpu.ops.ulysses import (
                ulysses_attention_with_prefix as attn_with_prefix,
            )
        else:
            from finchat_tpu.ops.ring_attention import (
                ring_attention_with_prefix as attn_with_prefix,
            )

        k_pages, v_pages, k_scales, v_scales = cache
        lay = jnp.asarray(layer_idx, jnp.int32).reshape(())
        # the GATHER is bounded to the static prefix-page bucket (folding
        # max_pages every segment would cost O(segments x max_seq_len));
        # the SCATTER below keeps the full row — the segment's own pages
        # lie past the prefix
        kp, vp = gather_kv_any(
            k_pages, v_pages, k_scales, v_scales,
            page_table[:, :prefix_pages], page_size, lay, n_kv, dtype=q.dtype,
        )
        out = attn_with_prefix(
            q, k, v, kp, vp, start_pos[0],
            mesh=mesh, axis="seq", head_axis="model", causal=True,
        )
        # cache write AFTER the gather: the prefix fold must see only
        # earlier segments (positions < start_pos); this segment's own
        # tokens enter attention through the ring, not the cache
        cache = _scatter_kv(
            (k_pages, v_pages, k_scales, v_scales), k, v,
            page_table, start_pos, n_valid, page_size, layer_idx, n_kv,
        )
        return out, cache

    return attention


@partial(jax.jit, static_argnames=("config", "page_size", "mesh", "prefix_pages", "sp_mode", "qm_backend"), donate_argnums=(1,))
def ring_prefill_segment_step(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [1, S] — ONE segment, padded to a seq-axis multiple
    slot: Array,  # scalar int32
    start_pos: Array,  # scalar int32 — absolute position of tokens[0, 0]
    n_valid: Array,  # scalar int32 — real tokens in this segment
    *,
    config: LlamaConfig,
    page_size: int,
    mesh,
    prefix_pages: int,
    sp_mode: str = "ring",
    qm_backend: str = "ref",
) -> tuple[DecodeState, Array]:
    """One segment of a chunked seq-sharded prefill (SURVEY §5.7c +
    VERDICT r4 weak #8): segments attend to the cached earlier segments
    via the prefix fold and to themselves via the ring, so the scheduler
    can interleave decode steps between segments. Returns (state,
    last-valid-token logits [vocab]) — callers use the logits of the
    FINAL segment only.

    ``prefix_pages`` (static, power-of-two-bucketed by the caller) bounds
    the gather+fold to the pages that can actually hold the prefix —
    without it every segment would dequantize and fold max_seq_len
    positions per layer, costing O(segments x max_seq_len) attention
    instead of the monolithic path's O(S^2/2)."""
    S = tokens.shape[1]
    positions = start_pos + jnp.arange(S)[None, :]  # RoPE is absolute
    page_row = jax.lax.dynamic_slice_in_dim(state.page_table, slot, 1, axis=0)

    attention = _ring_segment_attention_fn(
        mesh, page_row, prefix_pages, start_pos[None], n_valid[None],
        page_size, config.n_kv_heads, sp_mode,
    )
    hidden, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        return_hidden=True, qm_backend=qm_backend,
    )
    last_hidden = jax.lax.dynamic_index_in_dim(
        hidden[0], jnp.maximum(n_valid - 1, 0), axis=0, keepdims=False
    )  # [D]
    last_logits = lm_head(params, last_hidden, config=config,
                          qm_backend=qm_backend)  # [vocab]

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens.at[slot].add(n_valid),
    )
    return new_state, last_logits


@partial(jax.jit, static_argnames=("config", "page_size", "mesh", "sp_mode", "qm_backend"), donate_argnums=(1,))
def ring_prefill_step(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [1, S] — the WHOLE prompt, padded to a seq-axis multiple
    slot: Array,  # scalar int32
    n_valid: Array,  # scalar int32 — real prompt tokens
    *,
    config: LlamaConfig,
    page_size: int,
    mesh,
    sp_mode: str = "ring",
    qm_backend: str = "ref",
) -> tuple[DecodeState, Array]:
    """Seq-sharded single-shot prefill for long RAG prompts (SURVEY §5.7c).

    The sequence dim is sharded over the mesh's ``seq`` axis: activations
    and attention state are O(S / seq) per device, with the cross-device
    exchange done per ``sp_mode`` — K/V blocks rotating the ICI ring
    (ops/ring_attention.py) or Ulysses all-to-all head scatter
    (ops/ulysses.py) — so prompts beyond one chip's HBM become servable.
    Composes with TP (``model`` axis) via the head axis.
    Returns (state, last-valid-token logits [vocab])."""
    S = tokens.shape[1]
    positions = jnp.arange(S)[None, :]  # [1, S]
    page_row = jax.lax.dynamic_slice_in_dim(state.page_table, slot, 1, axis=0)

    attention = _ring_prefill_attention_fn(
        mesh, page_row, jnp.zeros((1,), jnp.int32), n_valid[None], page_size,
        config.n_kv_heads, sp_mode,
    )
    # hidden states only — a full [S, vocab] fp32 logits tensor at long-S
    # would cost GBs in exactly the regime this path exists for; project
    # the single last-valid row instead
    hidden, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        return_hidden=True, qm_backend=qm_backend,
    )
    last_hidden = jax.lax.dynamic_index_in_dim(
        hidden[0], jnp.maximum(n_valid - 1, 0), axis=0, keepdims=False
    )  # [D]
    last_logits = lm_head(params, last_hidden, config=config,
                          qm_backend=qm_backend)  # [vocab]

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens.at[slot].add(n_valid),
    )
    return new_state, last_logits


@partial(jax.jit, donate_argnums=(0,))
def commit_first_token(
    state: DecodeState, slot: Array, logits: Array, temperature: Array, top_p: Array, top_k: Array
) -> tuple[DecodeState, Array]:
    """Sample the first generated token from prefill logits and arm the slot
    for decode. (temperature/top_p/top_k are scalars for this one sequence.)"""
    rng, sub = jax.random.split(state.rng)
    token = sample(logits[None], sub, temperature[None], top_p[None], top_k[None])[0]
    new_state = dataclasses.replace(
        state,
        last_tokens=state.last_tokens.at[slot].set(token),
        rng=rng,
    )
    return new_state, token


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "qm_backend",
                     "return_logits"),
    donate_argnums=(1,),
)
def decode_step(
    params: dict[str, Any],
    state: DecodeState,
    active: Array,  # [max_seqs] bool
    temperature: Array,  # [max_seqs]
    top_p: Array,  # [max_seqs]
    top_k: Array,  # [max_seqs] int32
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    return_logits: bool = False,
) -> tuple[DecodeState, Array, Array | None]:
    """One decode step for ALL slots; returns (state, next_tokens [max_seqs]).

    Each active slot's ``last_token`` KV is appended at ``context_lens`` and
    the next token sampled from its logits. Inactive slots write to the
    trash page and their sampled tokens are ignored by the host.

    ``return_logits=True`` additionally returns the step logits [B, vocab]
    (fp32) — the host-side path for grammar-constrained sampling
    (agent/constrained.py), which overrides ``last_tokens`` afterwards.
    """
    tokens = state.last_tokens[:, None]  # [B, 1]
    positions = state.context_lens[:, None]  # [B, 1] — absolute (rotary)
    n_valid = active.astype(jnp.int32)  # [B]

    # write + mask at the compacted position (bounded KV; zero-gap rows
    # reduce to the legacy absolute math bit-for-bit)
    attention = _paged_attention_fn(
        state.page_table, state.context_lens - state.kv_gaps, n_valid,
        page_size, config.n_kv_heads, attn_backend,
    )
    logits, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        qm_backend=qm_backend,
    )
    step_logits = logits[:, 0, :]  # [B, vocab]

    rng, sub = jax.random.split(state.rng)
    next_tokens = sample(step_logits, sub, temperature, top_p, top_k)

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens + n_valid,
        last_tokens=jnp.where(active, next_tokens, state.last_tokens),
        rng=rng,
    )
    return new_state, next_tokens, (step_logits if return_logits else None)


def _ragged_attention_fn(
    page_rows: Array,  # [R, max_pages] per-ROW page lists (host-gathered)
    tok_row: Array,  # [T] int32 — owning row per packed token (R = padding)
    tok_pos: Array,  # [T] int32 — absolute position per packed token
    row_kv_len: Array,  # [R] int32 — valid KV per row incl. this dispatch
    tok_valid: Array,  # [T] bool — real token (False = buffer padding)
    page_size: int,
    n_kv: int,
    attn_backend: str,
    row_gap: Array | None = None,  # [R] int32 — bounded-KV eviction gap
):
    """Attention callback for the packed ragged step (``ragged_mixed_step``):
    per-token KV writes through the chunk scatter (one full-cache copy per
    round, amortized over every row — the mixed-step trade), then the ragged
    paged kernel (ops/ragged_paged_attention.py) reads each row's pages in
    place. The ``jax.lax`` reference backend computes each packed token as
    its own batch element of the SAME ``gather_kv`` + ``mha_reference`` math
    the split path uses — the fp32 byte-identity contract's foundation.

    ``row_gap`` (bounded KV, ISSUE 15) shifts each row's KV WRITE to its
    compacted position and rides into the kernel as the per-row
    ``kv_gap`` offset, so the gather walks the surviving pages while
    ``tok_pos`` — and the rotary positions upstream — stay absolute."""
    from finchat_tpu.ops.dispatch import ragged_paged_attention

    R = page_rows.shape[0]
    safe_row = jnp.minimum(tok_row, R - 1)
    # per-token page rows for the scatter; padding tokens write the trash
    # page (n_valid 0 redirects them inside the scatter)
    pt_tok = page_rows[safe_row]  # [T, max_pages]
    n_valid_tok = tok_valid.astype(jnp.int32)
    if row_gap is None:
        tok_wpos = tok_pos
    else:
        # valid tokens of a gapped row always sit past the evicted region
        # (the scheduler's eviction/restore invariant), so the uniform
        # subtraction is exact; the clamp only guards padding tokens
        tok_wpos = jnp.maximum(tok_pos - row_gap[safe_row], 0)

    def attention(q: Array, k: Array, v: Array, cache: Any, layer_idx: Array):
        from finchat_tpu.utils.tracing import named_scope

        k_pages, v_pages, k_scales, v_scales = cache
        quantized = k_pages.dtype == jnp.int8  # static under trace
        T = k.shape[1]
        layer = layer_idx.reshape(1)
        with named_scope("kv_scatter_ragged"):
            # each packed token is one (B=T, C=1) scatter row at its own
            # COMPACTED position through its own page list
            k_pages, v_pages, k_scales, v_scales = _scatter_kv(
                (k_pages, v_pages, k_scales, v_scales),
                k.reshape(T, 1, n_kv, -1), v.reshape(T, 1, n_kv, -1),
                pt_tok, tok_wpos, n_valid_tok, page_size, layer_idx, n_kv,
            )
        with named_scope("ragged_paged_attention"):
            out = ragged_paged_attention(
                q[0], k_pages, v_pages, page_rows, tok_row, tok_pos,
                row_kv_len, layer, page_size=page_size, n_kv=n_kv,
                backend=attn_backend,
                k_scales=k_scales if quantized else None,
                v_scales=v_scales if quantized else None,
                kv_gap=row_gap,
            )
        return out[None], (k_pages, v_pages, k_scales, v_scales)

    return attention


def _ragged_round_math(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [T] int32 PACKED token buffer (0 at device-read positions)
    tok_row: Array,  # [T] int32 — owning row, ascending contiguous (R = padding)
    row_slot: Array,  # [R] int32 — engine slot per row
    row_start: Array,  # [R] int32 — abs pos of the row's first token (prefill)
    row_len: Array,  # [R] int32 — tokens in the row (0 = padding row)
    row_from_device: Array,  # [R] bool — token 0 reads last_tokens[slot] and the
    #   row starts at context_lens[slot] (decode rows, spec verify rows)
    row_arm: Array,  # [R] bool — commit this row's sampled token to last_tokens
    row_n_drafts: Array,  # [R] int32 — spec rows: row_len == 1 + n_drafts
    temperature: Array,  # [R] — PER-ROW sampling params
    top_p: Array,  # [R]
    top_k: Array,  # [R] int32
    loop_active: Array,  # [max_seqs] bool — slots riding the fused K-token tail
    loop_temperature: Array,  # [max_seqs] — per-SLOT params for the tail
    loop_top_p: Array,  # [max_seqs]
    loop_top_k: Array,  # [max_seqs] int32
    eos_id: Array,  # scalar int32 (< 0 disables the tail's stop mask)
    row_live: Array,  # [R] bool — free-run stop mask (see docstring)
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    spec_width: int = 0,
    loop_depth: int = 1,
) -> tuple[DecodeState, Array, Array, Array, Array]:
    """The packed ragged round body, shared VERBATIM by the single-round
    ``ragged_mixed_step`` and the multi-round free-run capture
    (``ragged_multi_round``) so a captured round is bit-identical math to
    a host-stepped one by construction.

    ``row_live`` is the free-run generalization of ``decode_loop_step``'s
    per-slot stop mask to the full ragged row set: a dead row rides the
    round fully inert — its KV writes trash-redirect (the scatter sees
    ``n_valid 0``), nothing arms, ``context_lens``/``last_tokens`` stay
    frozen, and its emitted count is 0 (the host drain sentinel). The
    single-round path passes all-True, which reduces every gate below to
    the identity — the mixed-vs-split byte-identity tests pin that the
    extraction changed nothing. (See ``ragged_mixed_step`` for the full
    row/descriptor contract.)"""
    T = tokens.shape[0]
    R = row_slot.shape[0]
    B = state.context_lens.shape[0]
    W = spec_width + 1
    tok_row = jnp.asarray(tok_row, jnp.int32)
    safe_row = jnp.minimum(tok_row, R - 1)
    # dead rows' tokens are demoted to padding: KV writes trash-redirect
    # and attention treats them as buffer padding (all-True live mask →
    # exactly the original tok_row < R predicate)
    tok_valid = (tok_row < R) & row_live[safe_row]
    # nothing arms on a dead row: n_emitted 0, last_tokens delta 0
    row_arm = row_arm & row_live
    q_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(row_len, dtype=jnp.int32)[:-1]]
    )  # [R] exclusive — rows packed in ascending contiguous order
    tok_off = jnp.arange(T, dtype=jnp.int32) - q_start[safe_row]
    eff_start = jnp.where(
        row_from_device, state.context_lens[row_slot], row_start
    )  # [R]
    tok_pos = jnp.where(tok_valid, eff_start[safe_row] + tok_off, 0)
    row_last = state.last_tokens[row_slot]  # [R]
    tok_in = jnp.where(
        tok_valid & row_from_device[safe_row] & (tok_off == 0),
        row_last[safe_row], tokens,
    )
    page_rows = state.page_table[row_slot]  # [R, max_pages]
    row_kv_len = jnp.where(row_len > 0, eff_start + row_len, 0)  # [R]
    row_gap = state.kv_gaps[row_slot]  # [R] — bounded-KV compaction offset

    attention = _ragged_attention_fn(
        page_rows, tok_row, tok_pos, row_kv_len, tok_valid,
        page_size, config.n_kv_heads, attn_backend, row_gap=row_gap,
    )
    # hidden states only, then project only each row's sampling positions —
    # the [T, vocab] fp32 logits tensor would cost GBs at production shapes
    hidden, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tok_in[None], tok_pos[None],
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        return_hidden=True, qm_backend=qm_backend,
    )
    h = hidden[0]  # [T, D]

    # sampling positions: spec rows need logits at EVERY row position
    # (ascending, for draft acceptance); every other row only at its last
    # valid token — all W columns point there, so column 0 is always the
    # row's sampling position
    col = jnp.arange(W, dtype=jnp.int32)[None, :]  # [1, W]
    last_off = jnp.maximum(row_len - 1, 0)[:, None]  # [R, 1]
    sel_off = jnp.where(
        (row_n_drafts > 0)[:, None], jnp.minimum(col, last_off), last_off
    )
    sel_idx = jnp.clip(q_start[:, None] + sel_off, 0, T - 1)  # [R, W]
    logits = lm_head(params, h[sel_idx], config=config,
                     qm_backend=qm_backend)  # [R, W, vocab] fp32
    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [R, W]

    # spec acceptance — verify_step's math over the packed drafts: draft
    # column i (1..W-1) is accepted while every earlier draft matched and
    # it equals the model's prediction for its position
    cols_d = jnp.arange(1, W, dtype=jnp.int32)[None, :]  # [1, W-1]
    draft_tok = tok_in[jnp.clip(q_start[:, None] + cols_d, 0, T - 1)]
    match = (cols_d <= row_n_drafts[:, None]) & (draft_tok == preds[:, :-1])
    accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)  # [R]

    rng, sub = jax.random.split(state.rng)
    row_logits = logits[:, 0, :]  # [R, vocab] — each row's sampling position
    sampled0 = sample(row_logits, sub, temperature, top_p, top_k)  # [R]
    emitted = jnp.concatenate([sampled0[:, None], preds[:, 1:]], axis=1)
    n_emitted = jnp.where(
        row_arm, jnp.where(row_n_drafts > 0, accepted + 1, 1), 0
    )
    last_tok = jnp.take_along_axis(emitted, accepted[:, None], axis=1)[:, 0]

    # context advance: spec rows move by what they EMITTED (rejected
    # drafts' KV stays beyond the new length); every other row by its
    # packed length (chunk for prefill, 1 for decode, 0 for padding);
    # dead free-run rows stay frozen
    advance = jnp.where(
        row_live, jnp.where(row_n_drafts > 0, n_emitted, row_len), 0
    )
    delta = jnp.where(row_arm, last_tok - row_last, 0)
    state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens.at[row_slot].add(advance),
        last_tokens=state.last_tokens.at[row_slot].add(delta),
        rng=rng,
    )

    # fused K-token tail: loop-eligible decode slots free-run loop_depth-1
    # further iterations in the SAME dispatch — the decode_loop_step body
    # verbatim (same forward, appends, sampling, EOS mask, rng discipline),
    # so the tail is byte-identical to a split-path block
    token_block = jnp.full((max(loop_depth - 1, 0), B), -1, jnp.int32)
    if loop_depth > 1:
        live0 = loop_active & (state.last_tokens != eos_id)

        def body(i, carry):
            state, live, token_block = carry
            toks = state.last_tokens[:, None]  # [B, 1]
            positions = state.context_lens[:, None]
            n_valid = live.astype(jnp.int32)

            attn = _paged_attention_fn(
                state.page_table, state.context_lens - state.kv_gaps, n_valid,
                page_size, config.n_kv_heads, attn_backend,
            )
            step_logits, (kp, vp, ks, vs) = forward(
                params, toks, positions,
                config=config, attention=attn,
                cache=(state.k_pages, state.v_pages,
                       state.k_scales, state.v_scales),
                qm_backend=qm_backend,
            )
            step_logits = step_logits[:, 0, :]
            rng, sub = jax.random.split(state.rng)
            next_tokens = sample(
                step_logits, sub, loop_temperature, loop_top_p, loop_top_k
            )
            state = dataclasses.replace(
                state,
                k_pages=kp, v_pages=vp, k_scales=ks, v_scales=vs,
                context_lens=state.context_lens + n_valid,
                last_tokens=jnp.where(live, next_tokens, state.last_tokens),
                rng=rng,
            )
            token_block = token_block.at[i].set(
                jnp.where(live, next_tokens, -1)
            )
            live = live & (next_tokens != eos_id)
            return state, live, token_block

        state, _, token_block = jax.lax.fori_loop(
            0, loop_depth - 1, body, (state, live0, token_block)
        )
    return state, emitted, n_emitted, row_logits, token_block


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "qm_backend",
                     "spec_width", "loop_depth"),
    donate_argnums=(1,),
)
def ragged_mixed_step(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [T] int32 PACKED token buffer (0 at device-read positions)
    tok_row: Array,  # [T] int32 — owning row, ascending contiguous (R = padding)
    row_slot: Array,  # [R] int32 — engine slot per row
    row_start: Array,  # [R] int32 — abs pos of the row's first token (prefill)
    row_len: Array,  # [R] int32 — tokens in the row (0 = padding row)
    row_from_device: Array,  # [R] bool — token 0 reads last_tokens[slot] and the
    #   row starts at context_lens[slot] (decode rows, spec verify rows)
    row_arm: Array,  # [R] bool — commit this row's sampled token to last_tokens
    row_n_drafts: Array,  # [R] int32 — spec rows: row_len == 1 + n_drafts
    temperature: Array,  # [R] — PER-ROW sampling params
    top_p: Array,  # [R]
    top_k: Array,  # [R] int32
    loop_active: Array,  # [max_seqs] bool — slots riding the fused K-token tail
    loop_temperature: Array,  # [max_seqs] — per-SLOT params for the tail
    loop_top_p: Array,  # [max_seqs]
    loop_top_k: Array,  # [max_seqs] int32
    eos_id: Array,  # scalar int32 (< 0 disables the tail's stop mask)
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    spec_width: int = 0,
    loop_depth: int = 1,
) -> tuple[DecodeState, Array, Array, Array, Array]:
    """ONE packed ragged dispatch advancing every serving population at once
    (the scheduler's mixed path, ISSUE 10 — built on
    ops/ragged_paged_attention.py): prefill chunks of any length, 1-token
    decode rows, grammar-constrained rows (host overrides via the returned
    logits), and (1+Kd)-token spec verify rows are rows of ONE packed
    buffer; loop-eligible decode slots then free-run ``loop_depth - 1``
    additional fused iterations INSIDE the same dispatch (the
    ``decode_loop_step`` body verbatim). Returns
    ``(state, emitted [R, W], n_emitted [R], row_logits [R, vocab],
    loop_block [loop_depth-1, max_seqs])`` with ``W = spec_width + 1``.

    - Device-read rows (``row_from_device``) take their first token from
      ``state.last_tokens[slot]`` and start at ``context_lens[slot]`` ON
      DEVICE; spec rows' drafts ride the packed buffer at offsets 1..Kd.
    - Spec acceptance is the ``verify_step`` math verbatim: draft i commits
      iff it equals THIS forward's argmax at its position;
      ``emitted[r, :n_emitted[r]]`` are the row's tokens (1..Kd+1 for spec
      rows, 1 for armed plain rows, 0 for mid-prompt prefill rows), and
      rejected drafts' KV lands beyond the new ``context_lens``.
    - ``row_logits`` is each row's sampling-position logits (position 0
      for device rows, the last valid chunk token for prefill rows) — the
      host-side grammar-pick path, exactly ``decode_step return_logits``.
    - One rng split for the packed round plus one per tail iteration —
      the same per-iteration discipline as ``decode_step`` /
      ``decode_loop_step``; greedy streams are rng-independent.
    - ``last_tokens`` commits as a DELTA scatter-add so duplicate-slot
      padding rows (delta 0) cannot race the real row's write; the tail
      reads the committed tokens, so a loop slot's phase-1 token chains
      into its fused tail exactly like K single steps.

    Numerics contract (tests/test_mixed_step.py, bench --ragged-sweep):
    same MATH as the split path per token; greedy streams byte-identical
    at fp32 (CI-gated). The documented bf16 near-tie caveat of
    ``verify_step``/PR 4 applies unchanged: a token computed at the packed
    shape can differ in the last ulp from the ``[max_seqs, 1]`` shape and
    flip a later near-tie argmax — either stream is a valid greedy decode.
    """
    R = row_slot.shape[0]
    return _ragged_round_math(
        params, state, tokens, tok_row, row_slot, row_start, row_len,
        row_from_device, row_arm, row_n_drafts, temperature, top_p, top_k,
        loop_active, loop_temperature, loop_top_p, loop_top_k, eos_id,
        jnp.ones((R,), bool),  # every row live: the host stepped this round
        config=config, page_size=page_size, attn_backend=attn_backend,
        qm_backend=qm_backend, spec_width=spec_width, loop_depth=loop_depth,
    )


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "qm_backend",
                     "loop_depth"),
    donate_argnums=(1,),
)
def ragged_multi_round(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [F, T] int32 — staged packed token buffer PER ROUND
    tok_row: Array,  # [F, T] int32
    row_slot: Array,  # [R] int32 — row↔slot binding is FIXED across the run
    row_start: Array,  # [F, R] int32
    row_len: Array,  # [F, R] int32
    row_from_device: Array,  # [F, R] bool
    row_arm: Array,  # [F, R] bool
    temperature: Array,  # [R] — per-row sampling params (fixed across rounds)
    top_p: Array,  # [R]
    top_k: Array,  # [R] int32
    loop_active: Array,  # [F, max_seqs] bool — staged fused-tail schedule
    loop_temperature: Array,  # [max_seqs]
    loop_top_p: Array,  # [max_seqs]
    loop_top_k: Array,  # [max_seqs] int32
    eos_id: Array,  # scalar int32
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    loop_depth: int = 1,
) -> tuple[DecodeState, Array, Array, Array]:
    """The free-running serving loop (ISSUE 13): ``F = freerun_rounds``
    consecutive ragged rounds captured as ONE replayable device program —
    a ``lax.scan`` over the exact ``_ragged_round_math`` body the
    host-stepped path runs, erasing F-1 of every F host round-trips.

    - **Staged-descriptor queue**: the leading ``[F, ...]`` axis of the
      descriptor arrays is a queue in device memory that rounds drain in
      order. The host pre-stages each round at dispatch time from data it
      already owns — prompt chunks advance deterministically, so a
      prefill row's completion round is known ahead and later rounds
      stage it as an on-device-sampled decode row (on-device admission of
      the pre-staged prompt: the completing round arms the row and its
      first token commits to ``last_tokens`` with no host involvement,
      exactly ``commit_first_token``'s math).
    - **On-device stop masks**: budget exhaustion is staged away by the
      host (a row past its remaining ``max_new_tokens`` simply stops
      appearing in later rounds' descriptors); EOS — the one
      data-dependent stop — is the device's: a round recomputes
      ``row_live`` from ``last_tokens[row_slot] == eos_id`` for
      device-read rows, so a row that commits EOS (in its own round OR
      its fused tail) rides every later round inert, emitting 0. This is
      ``decode_loop_step``'s per-slot mask generalized to the ragged row
      set, and it is also what makes a stale capture safe: rows whose
      stream the host has since retired stay dead because their EOS is
      still in ``last_tokens`` until the post-run slot reset applies.
    - **Output ring**: per-round emissions land in the scan's stacked
      output buffers — ``ring_tokens [F, R]`` (each armed row's token),
      ``ring_n [F, R]`` (0 = mid-prompt chunk / dead row — the drain
      sentinel), ``ring_blocks [F, loop_depth-1, max_seqs]`` (the fused
      tails). The scheduler drains the ring off-loop while the device is
      mid-flight on the NEXT capture (depth-2, engine/scheduler.py
      ``_consume_ring``).

    No spec verify rows inside a capture (drafts are host data proposed
    from DELIVERED tokens; live proposal windows cap the capture to one
    round — scheduler ``_freerun_rounds_cap``), so ``spec_width`` is
    pinned to 0 and each ring round emits at most one token per row plus
    its tail. Returns ``(state, ring_tokens, ring_n, ring_blocks)``.

    Byte-identity contract: round r of a capture is bit-identical math to
    the r'th host-stepped ``ragged_mixed_step`` over the same descriptors
    (same body, same rng split discipline — tests/test_freerun.py and
    bench --freerun-sweep pin the stream-level identity at fp32)."""
    R = row_slot.shape[0]
    no_drafts = jnp.zeros((R,), jnp.int32)

    def one_round(state, staged):
        toks, trow, rstart, rlen, rdev, rarm, lact = staged
        # the EOS stop mask: device-read rows whose slot already committed
        # EOS ride this round dead (eos_id < 0 disables, as in the tail)
        row_live = jnp.logical_not(
            rdev & (state.last_tokens[row_slot] == eos_id)
        )
        state, emitted, n_emitted, _row_logits, blk = _ragged_round_math(
            params, state, toks, trow, row_slot, rstart, rlen, rdev, rarm,
            no_drafts, temperature, top_p, top_k, lact,
            loop_temperature, loop_top_p, loop_top_k, eos_id, row_live,
            config=config, page_size=page_size, attn_backend=attn_backend,
            qm_backend=qm_backend, spec_width=0, loop_depth=loop_depth,
        )
        # W = 1 (no spec rows): column 0 is every armed row's token
        return state, (emitted[:, 0], n_emitted, blk)

    state, (ring_tokens, ring_n, ring_blocks) = jax.lax.scan(
        one_round, state,
        (tokens, tok_row, row_start, row_len, row_from_device, row_arm,
         loop_active),
    )
    return state, ring_tokens, ring_n, ring_blocks


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "qm_backend",
                     "loop_depth"),
    donate_argnums=(1,),
)
def decode_loop_step(
    params: dict[str, Any],
    state: DecodeState,
    active: Array,  # [max_seqs] bool
    temperature: Array,  # [max_seqs]
    top_p: Array,  # [max_seqs]
    top_k: Array,  # [max_seqs] int32
    eos_id: Array,  # scalar int32 (< 0 disables the on-device stop mask)
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    loop_depth: int = 4,
) -> tuple[DecodeState, Array]:
    """K fused decode iterations in ONE dispatch (``jax.lax.fori_loop``):
    the multi-step path that amortizes the per-token synchronization
    boundary (one ``decode_step`` dispatch + one device→host token fetch +
    one Python dispatch per generated token) across ``loop_depth`` tokens —
    the dominant remaining tax once the kernels themselves are tuned
    (arxiv 2410.23668 "kernel looping").

    Each iteration is EXACTLY the ``decode_step`` body — same forward, same
    in-place Pallas KV appends, same on-device ``sample`` call with the same
    per-iteration ``jax.random.split`` rng discipline — so a K-block greedy
    stream is token-for-token identical to K single steps
    (tests/test_decode_loop.py pins this).

    On-device stop mask: a slot that samples ``eos_id`` has the EOS token
    recorded, then free-runs the remaining iterations INACTIVE — KV writes
    trash-redirected, ``context_lens`` frozen, output rows -1 — instead of
    forcing an early host exit (a data-dependent loop bound would defeat
    the single fixed-shape dispatch). Slots inactive at entry stay -1
    throughout. The host fetches the whole ``[loop_depth, max_seqs]`` block
    once per dispatch and delivers per-slot rows until EOS/-1.

    Host contract (scheduler ``decode_loop`` mode): slots needing per-token
    host control — grammar-constrained picks, spec-decode drafts, slots
    within ``loop_depth`` tokens of their ``max_new_tokens``/page budget —
    must NOT ride a block; the scheduler demotes them to single-step.

    PRNG: the carried ``state.rng`` splits ONCE per iteration for the whole
    batch — deliberately the same per-iteration discipline as
    ``decode_step`` (not a per-slot key tree), so an iteration of the block
    is bit-identical math to a single step given the same carried state.
    Non-greedy streams still depend on batch-global rng consumption order
    (as they always have); greedy streams are rng-independent, which is
    the block/single-step parity contract the tests pin.
    """
    B = active.shape[0]

    def body(i, carry):
        state, live, token_block = carry
        tokens = state.last_tokens[:, None]  # [B, 1]
        positions = state.context_lens[:, None]  # [B, 1] — absolute (rotary)
        n_valid = live.astype(jnp.int32)  # [B]

        # compacted write/mask coordinates (bounded KV; see decode_step)
        attention = _paged_attention_fn(
            state.page_table, state.context_lens - state.kv_gaps, n_valid,
            page_size, config.n_kv_heads, attn_backend,
        )
        logits, (k_pages, v_pages, k_scales, v_scales) = forward(
            params, tokens, positions,
            config=config, attention=attention,
            cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
            qm_backend=qm_backend,
        )
        step_logits = logits[:, 0, :]  # [B, vocab]

        rng, sub = jax.random.split(state.rng)
        next_tokens = sample(step_logits, sub, temperature, top_p, top_k)

        state = dataclasses.replace(
            state,
            k_pages=k_pages,
            v_pages=v_pages,
            k_scales=k_scales,
            v_scales=v_scales,
            context_lens=state.context_lens + n_valid,
            last_tokens=jnp.where(live, next_tokens, state.last_tokens),
            rng=rng,
        )
        token_block = token_block.at[i].set(jnp.where(live, next_tokens, -1))
        # EOS is recorded above, THEN the slot goes inactive: later
        # iterations trash-write and emit -1 (the host's drain sentinel)
        live = live & (next_tokens != eos_id)
        return state, live, token_block

    token_block = jnp.full((loop_depth, B), -1, jnp.int32)
    state, _, token_block = jax.lax.fori_loop(
        0, loop_depth, body, (state, active, token_block)
    )
    return state, token_block


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "qm_backend",
                     "return_logits"),
    donate_argnums=(1,),
)
def verify_step(
    params: dict[str, Any],
    state: DecodeState,
    active: Array,  # [max_seqs] bool
    drafts: Array,  # [max_seqs, Kd] int32 — host-proposed draft tokens
    n_drafts: Array,  # [max_seqs] int32 — live drafts per slot (0 = plain decode)
    temperature: Array,  # [max_seqs]
    top_p: Array,  # [max_seqs]
    top_k: Array,  # [max_seqs] int32
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    qm_backend: str = "ref",
    return_logits: bool = False,
) -> tuple[DecodeState, Array, Array, Array | None]:
    """Speculative-decoding verify step (prompt-lookup style): one forward
    over ``[last_token, draft_1..draft_Kd]`` per slot scores every draft in
    a single weights-read; the accepted prefix plus one model token commit
    together. Returns ``(state, emitted [B, K], n_emitted [B], logits?)``
    where ``K = Kd + 1`` and ``emitted[b, :n_emitted[b]]`` are the tokens
    produced this step (1..K per slot).

    Greedy-exactness contract (tests/test_spec_decode.py): draft i is
    accepted iff it equals THIS forward's argmax at its position, and
    position i's scores attend only to positions <= i (the paged kernel's
    causal mask) — so acceptance never changes a token, only how many
    commit per step, and the emitted stream is always a self-consistent
    greedy continuation. Bit-equality with token-by-token ``decode_step``
    additionally requires the C=K forward to round like the C=1 forward;
    that holds on the small test configs (asserted) but a bf16 near-tie
    can flip under a different chunk width at scale — either stream is a
    valid greedy decode of the same weights. Rejected drafts' KV lands
    beyond the new ``context_lens`` — masked by every future step and
    overwritten when those positions are reached for real.

    Non-greedy and grammar-constrained slots ride with ``n_drafts = 0``:
    their single token is sampled from position-0 logits with the full
    sampler (bit-identical math to ``decode_step``), and
    ``return_logits=True`` hands position-0 logits to the host for
    constrained picks, as in ``decode_step``.
    """
    B, Kd = drafts.shape
    tokens = jnp.concatenate([state.last_tokens[:, None], drafts], axis=1)  # [B, K]
    K = Kd + 1
    positions = state.context_lens[:, None] + jnp.arange(K)[None, :]  # rotary
    n_valid = jnp.where(active, 1 + n_drafts, 0)  # [B] tokens whose KV is written

    # compacted write/mask coordinates (bounded KV; see decode_step) —
    # rejected drafts' KV still lands beyond the new compacted length and
    # is overwritten when those positions are reached for real
    attention = _paged_attention_fn(
        state.page_table, state.context_lens - state.kv_gaps, n_valid,
        page_size, config.n_kv_heads, attn_backend, inplace_append=True,
    )
    logits, (k_pages, v_pages, k_scales, v_scales) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages, state.k_scales, state.v_scales),
        qm_backend=qm_backend,
    )  # [B, K, vocab]

    preds = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, K]
    # draft column i (1..Kd) is accepted while every earlier draft matched
    # and it equals the model's prediction for its position
    col = jnp.arange(1, K)[None, :]  # [1, Kd]
    match = (col <= n_drafts[:, None]) & (tokens[:, 1:] == preds[:, :-1])
    accepted = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)  # [B]
    n_emitted = jnp.where(active, accepted + 1, 0)

    # non-greedy slots (always draft-free) sample position 0 with the full
    # sampler — same math and rng discipline as decode_step
    rng, sub = jax.random.split(state.rng)
    step_logits = logits[:, 0, :]  # [B, vocab] fp32
    sampled0 = sample(step_logits, sub, temperature, top_p, top_k)
    emitted = jnp.concatenate([sampled0[:, None], preds[:, 1:]], axis=1)  # [B, K]
    last = jnp.take_along_axis(emitted, accepted[:, None], axis=1)[:, 0]

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        k_scales=k_scales,
        v_scales=v_scales,
        context_lens=state.context_lens + n_emitted,
        last_tokens=jnp.where(active, last, state.last_tokens),
        rng=rng,
    )
    return new_state, emitted, n_emitted, (step_logits if return_logits else None)


class InferenceEngine:
    """Host-side wrapper owning the device state and compiled steps.

    Synchronous single-sequence generation lives here (the minimum
    end-to-end slice, BASELINE config 1); the continuous-batching scheduler
    (engine/scheduler.py) drives the same step functions for many sequences.
    """

    def __init__(self, config: LlamaConfig, params: dict[str, Any], engine_cfg: EngineConfig,
                 mesh=None, attn_backend: str | None = None, quant: str = "",
                 quant_group: int = 0, qm_backend: str | None = None):
        from finchat_tpu.models.quant import validate_quant_mode
        from finchat_tpu.ops.dispatch import attention_backend, quant_matmul_backend

        validate_quant_mode(quant)
        if engine_cfg.compilation_cache_dir:
            # persistent XLA compilation cache: warmup's compiles land on
            # disk so a restarted process reloads them instead of
            # recompiling — warmup() logs its wall time either way, so the
            # saving is visible on the second boot. Thresholds dropped to
            # zero: the serving variants are exactly what we want cached,
            # however small or fast-compiling.
            try:
                jax.config.update(
                    "jax_compilation_cache_dir", engine_cfg.compilation_cache_dir
                )
                jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
                jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
                logger.info("persistent compilation cache: %s",
                            engine_cfg.compilation_cache_dir)
            except Exception as e:  # older jaxlib without the knobs
                logger.warning("compilation cache unavailable: %s", e)
        self.config = config
        self.attn_backend = attn_backend or attention_backend()
        # fused dequant-matmul backend (ops/quant_matmul.py): resolved ONCE
        # here — dispatch discipline, same as attn_backend — and passed
        # STATIC through every compiled step. Unquantized engines pin "ref"
        # so the knob adds zero compiled variants for them (bf16 weights
        # never reach the dispatcher anyway).
        self.qm_backend = (qm_backend or quant_matmul_backend()) if quant else "ref"
        # TP collective-overlap knob (ops/tp_overlap.py): surfaced on the
        # engine for the manual-TP stage path and the metrics plane;
        # default off — on CPU the serial psum IS the reference schedule
        self.tp_overlap = engine_cfg.tp_overlap
        self.engine_cfg = engine_cfg
        self.page_size = engine_cfg.page_size
        # fused multi-step decode (decode_loop_step): tokens per dispatch;
        # 1 = per-token decode_step only (today's behavior)
        self.decode_loop_depth = max(1, engine_cfg.decode_loop_depth)
        # free-running loop (ragged_multi_round): consecutive ragged
        # rounds captured per dispatch; 1 = host-stepped rounds only
        self.freerun_rounds = max(1, engine_cfg.freerun_rounds)
        # serving-variant count of the last warmup() (0 = not warmed yet);
        # the scheduler emits it as the finchat_warmup_compiled_variants
        # gauge — the ISSUE 10 warmup-matrix-collapse instrument
        self.compiled_variants = 0
        self.max_pages_per_seq = min(
            engine_cfg.num_pages - 1,
            -(-engine_cfg.max_seq_len // engine_cfg.page_size),
        )
        self.mesh = mesh
        # bounded-KV long-context serving (ISSUE 15): attention-sink +
        # sliding-window page eviction. The policy is pure host math; the
        # device side is the kv_gaps state leaf + compacted write/mask
        # coordinates in every step function. None = unbounded (legacy).
        from finchat_tpu.engine.kv_cache import BoundedKVPolicy

        _bp = BoundedKVPolicy(
            max(0, engine_cfg.kv_sink_pages),
            max(0, engine_cfg.kv_window_pages),
            engine_cfg.page_size,
        )
        if _bp.enabled:
            _bp.validate(
                prefill_chunk=engine_cfg.prefill_chunk,
                max_pages_per_seq=self.max_pages_per_seq,
                decode_loop_depth=self.decode_loop_depth,
                spec_tokens=engine_cfg.spec_tokens,
            )
        self.bounded_kv = _bp if _bp.enabled else None
        # int8 KV composes with a mesh: pages shard over the fused KV-head
        # minor dim, scales over their head row dim (decode_state_shardings;
        # aligned blocks when Hkv % 8 == 0, replicated — they're ~6% of the
        # pages — otherwise), and the SP-prefill write path quantizes too
        self.kv_quant = kv_quant = engine_cfg.kv_quant
        state = create_state(config, engine_cfg, self.max_pages_per_seq, kv_quant=kv_quant)
        if mesh is not None:
            # TP placement: params sharded Megatron-style, KV pages sharded
            # over the fused KV-head dim on the model axis; XLA propagates
            # the rest.
            from finchat_tpu.parallel.sharding import (
                llama_param_shardings,
                shard_decode_state,
                shard_params,
            )

            params = shard_params(params, llama_param_shardings(mesh))
            state = shard_decode_state(state, mesh, config.n_kv_heads)
        if quant:
            # after sharding on purpose: quantize is plain jnp, so q/scale
            # inherit each weight's GSPMD placement (models/quant.py);
            # idempotent on trees the checkpoint loader already quantized
            from finchat_tpu.models.quant import quantize_llama_params

            params = quantize_llama_params(params, mode=quant,
                                           group_size=quant_group)
        self.quant = quant
        self.quant_group = quant_group
        self.params = params
        self.state = state
        self.sp_mode = self._resolve_sp_mode(engine_cfg.sp_mode)

    @property
    def quant_label(self) -> str:
        """The serving quant mode as ONE label ("bf16", "int8", "int4",
        with "+kv8" when the page pool is int8) — stamped on dispatch
        trace events and the finchat_quant_* gauges so traced timelines
        and dashboards distinguish quantized dispatches (ISSUE 14). Must
        stay within tracing.QUANT_MODES (pinned by tests)."""
        base = self.quant or "bf16"
        return base + ("+kv8" if self.kv_quant else "")

    def _resolve_sp_mode(self, sp_mode: str) -> str:
        """Validate the configured SP mode against this model/mesh; Ulysses
        needs per-TP-shard head counts divisible by the seq axis
        (ops/ulysses.py) — fall back to ring (always valid) otherwise."""
        if sp_mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown sp_mode {sp_mode!r} (supported: 'ring', 'ulysses')")
        if sp_mode == "ulysses" and self.mesh is not None:
            from finchat_tpu.ops.ulysses import ulysses_supported

            c = self.config
            if not ulysses_supported(c.n_heads, c.n_kv_heads, self.mesh,
                                     axis="seq", head_axis="model"):
                logger.warning(
                    "sp_mode=ulysses needs per-shard heads divisible by the seq "
                    "axis (H=%d, Hkv=%d, mesh=%s); falling back to ring",
                    c.n_heads, c.n_kv_heads, dict(self.mesh.shape),
                )
                return "ring"
        return sp_mode

    # --- low-level ops used by the scheduler ----------------------------
    def set_page_table_row(self, slot: int, pages: list[int]) -> None:
        self.set_page_table_rows({slot: pages})

    def set_page_table_rows(self, rows: dict[int, list[int]]) -> None:
        """Assign several slots' page lists in ONE device update. Eager
        ``.at[].set`` ops cost ~15 ms each through a remote-tunnel backend
        (measured, round 4) — per-slot loops at batch 64 turn into seconds."""
        import numpy as np

        idx = np.asarray(list(rows), np.int32)
        mat = np.zeros((len(rows), self.max_pages_per_seq), np.int32)
        for i, pages in enumerate(rows.values()):
            mat[i, : len(pages)] = pages
        self.state = dataclasses.replace(
            self.state,
            page_table=self.state.page_table.at[jnp.asarray(idx)].set(jnp.asarray(mat)),
        )

    def set_context_lens_rows(self, rows: dict[int, int]) -> None:
        """Set several slots' context lengths in ONE device update — used by
        prefix-cache admission to start a slot at the shared prefix length
        (see set_page_table_rows for why batching matters)."""
        import numpy as np

        idx = jnp.asarray(np.asarray(list(rows), np.int32))
        vals = jnp.asarray(np.asarray(list(rows.values()), np.int32))
        self.state = dataclasses.replace(
            self.state, context_lens=self.state.context_lens.at[idx].set(vals)
        )

    def set_kv_gap_rows(self, rows: dict[int, int]) -> None:
        """Set several slots' bounded-KV compaction gaps in ONE device
        update (eviction waves / bounded session restores — see
        set_page_table_rows for why batching matters). The gap is host-
        deterministic metadata: the scheduler mirrors it on the handle and
        updates both sides together between dispatches, so every enqueued
        step sees a page table and gap that agree."""
        import numpy as np

        idx = jnp.asarray(np.asarray(list(rows), np.int32))
        vals = jnp.asarray(np.asarray(list(rows.values()), np.int32))
        self.state = dataclasses.replace(
            self.state, kv_gaps=self.state.kv_gaps.at[idx].set(vals)
        )

    def set_last_token(self, slot: int, token: int) -> None:
        """Override a slot's next decode input — used by grammar-constrained
        sampling after a host-side pick replaces the device-sampled token."""
        self.state = dataclasses.replace(
            self.state, last_tokens=self.state.last_tokens.at[slot].set(token)
        )

    def reset_slot(self, slot: int) -> None:
        self.reset_slots([slot])

    def reset_slots(self, slots: list[int]) -> None:
        """Clear several slots in one device update (see set_page_table_rows
        for why batching matters)."""
        idx = jnp.asarray(slots, jnp.int32)
        self.state = dataclasses.replace(
            self.state,
            page_table=self.state.page_table.at[idx].set(0),
            context_lens=self.state.context_lens.at[idx].set(0),
            last_tokens=self.state.last_tokens.at[idx].set(0),
            kv_gaps=self.state.kv_gaps.at[idx].set(0),
        )

    def offload_pages(self, page_ids: list[int]):
        """Snapshot physical pages device→host (all layers, K+V+scales) for
        the session KV cache. Blocks until the D2H copy lands — the caller
        is about to free these pages (see kv_cache.gather_pages_host)."""
        from finchat_tpu.engine.kv_cache import gather_pages_host

        s = self.state
        return gather_pages_host(
            s.k_pages, s.v_pages, s.k_scales, s.v_scales, page_ids
        )

    def restore_pages(self, page_ids: list[int], host: tuple) -> None:
        """Write a host snapshot back into freshly allocated pages (session
        cache resume). One XLA scatter per turn — off the jitted hot path."""
        from finchat_tpu.engine.kv_cache import scatter_pages_device

        s = self.state
        k_pages, v_pages, k_scales, v_scales = scatter_pages_device(
            s.k_pages, s.v_pages, s.k_scales, s.v_scales, page_ids, host
        )
        self.state = dataclasses.replace(
            self.state, k_pages=k_pages, v_pages=v_pages,
            k_scales=k_scales, v_scales=v_scales,
        )

    def rebuild_device_state(self) -> None:
        """Tear down and recreate ALL device-resident engine state — KV
        pool, page table, context lens, last tokens, rng — with the weights
        retained (scheduler circuit-breaker recovery: a wedged or poisoned
        device state is replaced wholesale; in-flight sequences were
        recompute-preempted to host and replay through admission). The old
        state is dropped BEFORE the new allocation so peak HBM stays one
        pool, and the new arrays have identical shapes/dtypes/shardings, so
        every compiled step variant (warmup's work) remains valid — no
        recompilation on the recovery path."""
        self.state = None  # free the old pool before allocating the new one
        state = create_state(
            self.config, self.engine_cfg, self.max_pages_per_seq,
            kv_quant=self.kv_quant,
        )
        if self.mesh is not None:
            from finchat_tpu.parallel.sharding import shard_decode_state

            state = shard_decode_state(state, self.mesh, self.config.n_kv_heads)
        self.state = state

    def _use_ring_prefill(self, prompt_len: int) -> bool:
        return (
            self.mesh is not None
            and self.mesh.shape.get("seq", 1) > 1
            and prompt_len >= self.engine_cfg.ring_prefill_min_tokens
        )

    def _ring_bucket(self, n: int) -> int:
        """Pad a ring-prefill length to a power-of-two bucket (rounded up to
        a seq-axis multiple) so the jit variant count is log2-bounded and
        warmable — per-length shapes would compile fresh per request."""
        n_seq = self.mesh.shape["seq"]
        return -(-round_up_pow2(n) // n_seq) * n_seq

    def prefill_ring(self, slot: int, prompt_ids: list[int]) -> Array:
        """Seq-sharded one-shot prefill of a long prompt (ring attention
        over the mesh's ``seq`` axis); returns last-token logits."""
        assert self.mesh is not None and self.mesh.shape.get("seq", 1) > 1
        n = len(prompt_ids)
        S = self._ring_bucket(n)
        tokens = jnp.asarray(prompt_ids + [0] * (S - n), jnp.int32)[None, :]
        self.state, last_logits = ring_prefill_step(
            self.params, self.state, tokens, jnp.int32(slot), jnp.int32(n),
            config=self.config, page_size=self.page_size, mesh=self.mesh,
            sp_mode=self.sp_mode, qm_backend=self.qm_backend,
        )
        return last_logits

    def ring_segment_tokens(self) -> int:
        """Segment size for the CHUNKED SP prefill (0 = monolithic): the
        configured ``ring_prefill_chunk`` rounded up to a seq-axis
        multiple. Applies to both sp_modes — ring and Ulysses each have a
        prefix-fold segment variant."""
        rc = self.engine_cfg.ring_prefill_chunk
        if rc <= 0 or self.mesh is None:
            return 0
        n_seq = self.mesh.shape.get("seq", 1)
        return -(-rc // n_seq) * n_seq

    def _prefix_page_bucket(self, start_pos: int) -> int:
        """Static page count for a segment's prefix gather: pow-2 bucket
        of the pages holding positions [0, start_pos), capped at the row
        width. Floored at the pages one segment spans so prefixes shorter
        than a segment (a shared-prefix-cache hit on the FIRST segment)
        reuse the smallest warmed bucket instead of compiling a fresh
        sub-rc variant on the request path — the extra gathered pages are
        masked, and their cost is bounded by one segment's own size."""
        floor = -(-self.ring_segment_tokens() // self.page_size)
        need = max(-(-start_pos // self.page_size), 1)
        return min(max(round_up_pow2(need), round_up_pow2(floor)),
                   self.max_pages_per_seq)

    def prefill_ring_segment(self, slot: int, seg_ids: list[int], start_pos: int) -> Array:
        """One segment of a chunked seq-sharded prefill. A segment with
        no cached prefix (``start_pos == 0``) runs the plain ring step
        (bucketed shape shared with the monolithic path); segments with a
        prefix — later segments, or a FIRST segment starting past a
        shared-prefix-cache hit — run the prefix-fold step at the fixed
        segment shape. Returns last-valid-token logits — meaningful for
        the FINAL segment."""
        rc = self.ring_segment_tokens()
        assert rc > 0, "segmented ring prefill requires ring_prefill_chunk > 0"
        n = len(seg_ids)
        assert 0 < n <= rc
        if start_pos == 0:
            return self.prefill_ring(slot, seg_ids)
        tokens = jnp.asarray(seg_ids + [0] * (rc - n), jnp.int32)[None, :]
        self.state, last_logits = ring_prefill_segment_step(
            self.params, self.state, tokens, jnp.int32(slot),
            jnp.int32(start_pos), jnp.int32(n),
            config=self.config, page_size=self.page_size, mesh=self.mesh,
            prefix_pages=self._prefix_page_bucket(start_pos),
            sp_mode=self.sp_mode, qm_backend=self.qm_backend,
        )
        return last_logits

    def prefill_batch(self, items: list[tuple[int, list[int]]]) -> list[Array]:
        """Chunked prefill of N whole prompts together; returns each
        sequence's final-chunk last-token logits (one [vocab] array per
        item, in input order).

        All N sequences advance one ``prefill_chunk`` per round; prompts
        that are exhausted ride the remaining rounds with ``n_valid = 0``
        (their KV writes go to the trash page). One weights-read serves the
        whole batch per round instead of per sequence.

        Prompts past ``ring_prefill_min_tokens`` on a ``seq > 1`` mesh take
        the seq-sharded ring path instead (one shot, O(S/seq) activation
        memory per device).
        """
        assert items, "empty prefill batch"
        ring = [(i, slot, ids) for i, (slot, ids) in enumerate(items)
                if self._use_ring_prefill(len(ids))]
        if ring:
            results: list[Array | None] = [None] * len(items)
            for i, slot, ids in ring:
                results[i] = self.prefill_ring(slot, ids)
            rest = [(i, it) for i, it in enumerate(items)
                    if results[i] is None]
            if rest:
                rest_logits = self.prefill_batch([it for _, it in rest])
                for (i, _), lg in zip(rest, rest_logits):
                    results[i] = lg
            assert all(r is not None for r in results)
            return results  # type: ignore[return-value]

        C = self.engine_cfg.prefill_chunk
        N = len(items)
        slots = jnp.asarray([slot for slot, _ in items], jnp.int32)
        prompts = [ids for _, ids in items]
        assert all(prompts), "empty prompt in prefill batch"
        rounds = max(-(-len(p) // C) for p in prompts)
        last_logits: list[Array | None] = [None] * N
        for r in range(rounds):
            chunk_tokens = []
            n_valid = []
            start = []
            for p in prompts:
                chunk = p[r * C:(r + 1) * C]
                n_valid.append(len(chunk))
                start.append(min(r * C, len(p)))
                chunk_tokens.append(chunk + [0] * (C - len(chunk)))
            self.state, logits = prefill_step(
                self.params, self.state,
                jnp.asarray(chunk_tokens, jnp.int32), slots,
                jnp.asarray(start, jnp.int32), jnp.asarray(n_valid, jnp.int32),
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend, qm_backend=self.qm_backend,
            )
            for i, p in enumerate(prompts):
                if n_valid[i] and r * C + n_valid[i] == len(p):
                    last_logits[i] = logits[i]
        assert all(l is not None for l in last_logits)
        return last_logits  # type: ignore[return-value]

    def prefill(self, slot: int, prompt_ids: list[int]) -> Array:
        """Chunked prefill of a whole prompt into a slot; returns the final
        chunk's last-token logits."""
        return self.prefill_batch([(slot, prompt_ids)])[0]

    def warmup(self, prefill_batch_sizes: list[int] | None = None) -> float:
        """Compile every serving step variant with state-neutral executions
        (verdict r3 weak #4/#5: the first request used to pay full XLA
        compilation inside the 100 s watchdog, and the first tool decision
        triggered a fresh compile of the return_logits decode variant).

        - ``prefill_step`` for every power-of-two batch the scheduler can
          dispatch (it pads rounds to powers of two) — run with
          ``n_valid = 0`` so writes land in the trash page and
          ``context_lens`` gains zero;
        - ``decode_step`` with ``return_logits`` False AND True, all slots
          inactive;
        - ``commit_first_token`` (slot 0's last_token is overwritten by the
          slot's real first prefill completion).

        Returns the wall-clock seconds spent (mostly XLA compilation).
        """
        import time

        import numpy as np

        t0 = time.perf_counter()
        cfg = self.engine_cfg
        B = cfg.max_seqs
        n_variants = 0  # compiled-variant tally → finchat_warmup_compiled_variants
        if prefill_batch_sizes is None:
            # every power of two up to AND INCLUDING the scheduler's largest
            # round padding (round_up_pow2 — the shared policy; for a
            # non-power-of-two max_seqs the padding exceeds it)
            top = round_up_pow2(B)
            prefill_batch_sizes = [1]
            while prefill_batch_sizes[-1] < top:
                prefill_batch_sizes.append(prefill_batch_sizes[-1] * 2)
        C = cfg.prefill_chunk
        for n in prefill_batch_sizes:
            zeros = jnp.zeros((n,), jnp.int32)
            self.state, _ = prefill_step(
                self.params, self.state, jnp.zeros((n, C), jnp.int32),
                zeros, zeros, zeros,
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend, qm_backend=self.qm_backend,
            )
            n_variants += 1
        if cfg.mixed_step:
            # the packed ragged variants the scheduler's mixed path
            # dispatches (ragged_mixed_step) — ONE pow-2 packed-token
            # bucket axis, descriptors fixed at [max_seqs]; all-padding
            # rows (row_len 0, nothing armed, no loop slots) keep it
            # state-neutral. Replaces PR 4's row-bucket × chunk-bucket
            # matrix AND its per-mode demotions — the collapsed warmup
            # matrix is the point (ISSUE 10; the gauge below records it).
            R = B
            rz = jnp.zeros((R,), jnp.int32)
            rflags = jnp.zeros((R,), bool)
            bflags = jnp.zeros((B,), bool)
            bz = jnp.zeros((B,), jnp.float32)
            bo = jnp.ones((B,), jnp.float32)
            bk = jnp.zeros((B,), jnp.int32)
            for t in self.ragged_token_buckets():
                self.state, _, _, _, _ = ragged_mixed_step(
                    self.params, self.state,
                    jnp.zeros((t,), jnp.int32), jnp.full((t,), R, jnp.int32),
                    rz, rz, rz, rflags, rflags, rz,
                    jnp.zeros((R,), jnp.float32), jnp.ones((R,), jnp.float32),
                    jnp.zeros((R,), jnp.int32),
                    bflags, bz, bo, bk, jnp.int32(-1),
                    config=self.config, page_size=self.page_size,
                    attn_backend=self.attn_backend, qm_backend=self.qm_backend,
                    spec_width=cfg.spec_tokens,
                    loop_depth=self.decode_loop_depth,
                )
                n_variants += 1
            if self.freerun_rounds > 1:
                # the captured multi-round program (ragged_multi_round) —
                # one extra variant per packed-token bucket at the fixed
                # freerun_rounds depth, all-padding rounds keeping it
                # state-neutral exactly like the single-round warmup
                F = self.freerun_rounds
                for t in self.ragged_token_buckets():
                    self.state, _, _, _ = ragged_multi_round(
                        self.params, self.state,
                        jnp.zeros((F, t), jnp.int32),
                        jnp.full((F, t), R, jnp.int32),
                        rz, jnp.zeros((F, R), jnp.int32),
                        jnp.zeros((F, R), jnp.int32),
                        jnp.zeros((F, R), bool), jnp.zeros((F, R), bool),
                        jnp.zeros((R,), jnp.float32),
                        jnp.ones((R,), jnp.float32),
                        jnp.zeros((R,), jnp.int32),
                        jnp.zeros((F, B), bool), bz, bo, bk, jnp.int32(-1),
                        config=self.config, page_size=self.page_size,
                        attn_backend=self.attn_backend, qm_backend=self.qm_backend,
                        loop_depth=self.decode_loop_depth,
                    )
                    n_variants += 1
        inactive = jnp.zeros((B,), bool)
        temp = jnp.full((B,), 1.0, jnp.float32)
        top_p = jnp.ones((B,), jnp.float32)
        top_k = jnp.zeros((B,), jnp.int32)
        for return_logits in (False, True):
            self.state, _, _ = decode_step(
                self.params, self.state, inactive, temp, top_p, top_k,
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend, qm_backend=self.qm_backend, return_logits=return_logits,
            )
            n_variants += 1
        if self.decode_loop_depth > 1:
            # the fused multi-step block the scheduler's decode_loop mode
            # dispatches — all slots inactive, so writes trash-redirect and
            # context_lens gains zero (eos_id is a runtime scalar, not part
            # of the jit cache key)
            self.state, _ = decode_loop_step(
                self.params, self.state, inactive, temp, top_p, top_k,
                jnp.int32(-1),
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend, qm_backend=self.qm_backend,
                loop_depth=self.decode_loop_depth,
            )
            n_variants += 1
        if cfg.spec_tokens > 0:
            # both verify-step variants (the scheduler's spec decode path)
            zero_drafts = jnp.zeros((B, cfg.spec_tokens), jnp.int32)
            zero_n = jnp.zeros((B,), jnp.int32)
            for return_logits in (False, True):
                self.state, _, _, _ = verify_step(
                    self.params, self.state, inactive, zero_drafts, zero_n,
                    temp, top_p, top_k,
                    config=self.config, page_size=self.page_size,
                    attn_backend=self.attn_backend, qm_backend=self.qm_backend, return_logits=return_logits,
                )
                n_variants += 1
        self.state, _ = commit_first_token(
            self.state, jnp.int32(0),
            jnp.zeros((self.config.vocab_size,), jnp.float32),
            jnp.float32(0.0), jnp.float32(1.0), jnp.int32(0),
        )
        n_variants += 1
        # ring-prefill length buckets (seq > 1 meshes): every bucket the
        # router can produce, INCLUDING the top one covering max_seq_len
        # (stopping at max_seq_len itself would miss e.g. the 8192 bucket a
        # 5000-token prompt maps to under a 6000 max)
        if self.mesh is not None and self.mesh.shape.get("seq", 1) > 1:
            rc = self.ring_segment_tokens()
            # segmented: a no-prefix first segment is min(prompt, rc)
            # tokens, so the plain-ring buckets that can actually occur
            # are bucket(min(ring_min, rc))..bucket(rc) — when ring_min >
            # rc every first segment is exactly rc (warming only
            # bucket(ring_min) would leave the always-used bucket(rc)
            # cold). Monolithic keeps the full enumeration.
            ring_min = self.engine_cfg.ring_prefill_min_tokens
            if rc > 0:
                S = self._ring_bucket(min(ring_min, rc))
                top = self._ring_bucket(rc)
            else:
                S = self._ring_bucket(ring_min)
                top = self._ring_bucket(self.engine_cfg.max_seq_len)
            while True:
                self.state, _ = ring_prefill_step(
                    self.params, self.state, jnp.zeros((1, S), jnp.int32),
                    jnp.int32(0), jnp.int32(0),
                    config=self.config, page_size=self.page_size,
                    mesh=self.mesh, sp_mode=self.sp_mode, qm_backend=self.qm_backend,
                )
                n_variants += 1
                if S >= top:
                    break
                S = self._ring_bucket(S + 1)
            if rc > 0:
                # later segments: fixed rc shape x each prefix-page
                # bucket a start position can map to (pow-2 enumeration,
                # same policy as the ring buckets)
                pb = self._prefix_page_bucket(rc)
                top_pb = self._prefix_page_bucket(self.engine_cfg.max_seq_len)
                while True:
                    self.state, _ = ring_prefill_segment_step(
                        self.params, self.state, jnp.zeros((1, rc), jnp.int32),
                        jnp.int32(0), jnp.int32(rc), jnp.int32(0),
                        config=self.config, page_size=self.page_size,
                        mesh=self.mesh, prefix_pages=pb,
                        sp_mode=self.sp_mode, qm_backend=self.qm_backend,
                    )
                    n_variants += 1
                    if pb >= top_pb:
                        break
                    pb = min(pb * 2, top_pb)
        np.asarray(self.state.context_lens)  # barrier: compilation done
        elapsed = time.perf_counter() - t0
        cache_note = (
            f" (compilation cache: {cfg.compilation_cache_dir})"
            if cfg.compilation_cache_dir else ""
        )
        # recorded for the warmup-matrix-collapse observability (ISSUE 10):
        # the scheduler re-emits it as the finchat_warmup_compiled_variants
        # gauge through its (possibly replica-labeled) metrics view
        self.compiled_variants = n_variants
        # the variant COUNT is quant-independent by construction (weight
        # dtype never keys a jit cache entry — the quantized tree swaps in
        # under the same traced shapes), and qm_backend-independent too
        # (resolved once at construction, one static value per engine —
        # bench --quantmatmul-smoke gates ref/fused counts equal), so the
        # collapsed-matrix gauge stays comparable across modes; the
        # labels make mode and matmul backend visible
        logger.info(
            "engine warmup [%s, qm=%s]: prefill batches %s + %d serving "
            "variants compiled in %.1fs%s",
            self.quant_label, self.qm_backend, prefill_batch_sizes,
            n_variants, elapsed, cache_note,
        )
        return elapsed

    def decode(self, active, temperature, top_p, top_k, return_logits: bool = False):
        from finchat_tpu.utils.metrics import METRICS

        METRICS.inc("finchat_decode_dispatches_total")
        self.state, next_tokens, logits = decode_step(
            self.params, self.state, active, temperature, top_p, top_k,
            config=self.config, page_size=self.page_size,
            attn_backend=self.attn_backend, qm_backend=self.qm_backend, return_logits=return_logits,
        )
        return (next_tokens, logits) if return_logits else next_tokens

    def ragged_token_buckets(self) -> list[int]:
        """Packed-token buckets for the ragged mixed step (ascending
        pow-2). ONE dimension replaces PR 4's row-bucket × chunk-bucket
        matrix: the dispatch shape varies only in the packed buffer length
        (descriptors are fixed at ``[max_seqs]``), so the compiled-variant
        count is log2 in max_seqs × chunk instead of their product — and
        spec/loop/constrained rows reuse the SAME variants instead of
        demoting to per-mode dispatch schedules. Floored at 64 tokens:
        small rounds pad into the smallest warmed bucket (padding rows are
        fully masked), trading a little dead compute at light load for
        fewer startup compiles."""
        cfg = self.engine_cfg
        top = round_up_pow2(
            cfg.max_seqs * max(cfg.prefill_chunk, cfg.spec_tokens + 1)
        )
        buckets = [min(64, top)]
        while buckets[-1] < top:
            buckets.append(buckets[-1] * 2)
        return buckets

    def ragged_bucket(self, n_tokens: int) -> int:
        """Smallest warmed packed-token bucket holding ``n_tokens``."""
        return next(b for b in self.ragged_token_buckets() if b >= n_tokens)

    def ragged_mixed(self, tokens, tok_row, row_slot, row_start, row_len,  # finchat-lint: hot
                     row_from_device, row_arm, row_n_drafts,
                     temperature, top_p, top_k,
                     loop_active, loop_temperature, loop_top_p, loop_top_k,
                     eos_id: int):
        """One packed ragged dispatch (see ragged_mixed_step); returns
        ``(emitted, n_emitted, row_logits, loop_block)`` device arrays —
        the scheduler fetches once per round. Counted at the dispatch seam
        like decode()/decode_loop(), so bench.py's dispatches-per-iteration
        figure reads real enqueued device programs."""
        from finchat_tpu.utils.metrics import METRICS

        METRICS.inc("finchat_mixed_dispatches_total")
        self.state, emitted, n_emitted, row_logits, loop_block = (
            ragged_mixed_step(
                self.params, self.state, tokens, tok_row, row_slot,
                row_start, row_len, row_from_device, row_arm, row_n_drafts,
                temperature, top_p, top_k,
                loop_active, loop_temperature, loop_top_p, loop_top_k,
                jnp.int32(eos_id),
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend, qm_backend=self.qm_backend,
                spec_width=self.engine_cfg.spec_tokens,
                loop_depth=self.decode_loop_depth,
            )
        )
        return emitted, n_emitted, row_logits, loop_block

    def ragged_multi(self, tokens, tok_row, row_slot, row_start, row_len,  # finchat-lint: hot
                     row_from_device, row_arm, temperature, top_p, top_k,
                     loop_active, loop_temperature, loop_top_p, loop_top_k,
                     eos_id: int):
        """One captured multi-round dispatch (see ragged_multi_round):
        ``tokens.shape[0]`` consecutive ragged rounds in ONE enqueued
        device program, returning the per-round token ring
        ``(ring_tokens, ring_n, ring_blocks)`` as device arrays — the
        scheduler drains them off-loop while the device free-runs the
        next capture. Counted ONCE at the dispatch seam (one program),
        exactly why bench --freerun-sweep's dispatches-per-round figure
        drops below 1."""
        from finchat_tpu.utils.metrics import METRICS

        METRICS.inc("finchat_mixed_dispatches_total")
        self.state, ring_tokens, ring_n, ring_blocks = ragged_multi_round(
            self.params, self.state, tokens, tok_row, row_slot, row_start,
            row_len, row_from_device, row_arm, temperature, top_p, top_k,
            loop_active, loop_temperature, loop_top_p, loop_top_k,
            jnp.int32(eos_id),
            config=self.config, page_size=self.page_size,
            attn_backend=self.attn_backend, qm_backend=self.qm_backend,
            loop_depth=self.decode_loop_depth,
        )
        return ring_tokens, ring_n, ring_blocks

    def decode_loop(self, active, temperature, top_p, top_k, eos_id: int,
                    depth: int | None = None):
        """Fused multi-step decode (see decode_loop_step): K iterations in
        one dispatch, on-device sampling + EOS mask. Returns the
        ``[K, max_seqs]`` token block (device array — callers fetch once).
        ``depth`` overrides the configured ``decode_loop_depth`` (bench
        sweeps); each distinct depth is its own compiled variant."""
        from finchat_tpu.utils.metrics import METRICS

        K = depth if depth is not None else self.decode_loop_depth
        assert K >= 1
        # counted at the DISPATCH seam (one jitted program enqueued), the
        # same counter decode() bumps once per step — what bench.py's
        # dispatches-per-token figure reads, so a host-side fallback that
        # looped K single steps here would be visible, not assumed away
        METRICS.inc("finchat_decode_dispatches_total")
        self.state, token_block = decode_loop_step(
            self.params, self.state, active, temperature, top_p, top_k,
            jnp.int32(eos_id),
            config=self.config, page_size=self.page_size,
            attn_backend=self.attn_backend, qm_backend=self.qm_backend, loop_depth=K,
        )
        return token_block

    def decode_spec(self, active, drafts, n_drafts, temperature, top_p, top_k,
                    return_logits: bool = False):
        """Speculative verify step (see verify_step). ``drafts`` [B, Kd]
        keys the compiled shape — callers pad to a fixed Kd."""
        from finchat_tpu.utils.metrics import METRICS

        # counted at the DISPATCH seam like decode()/decode_loop()/mixed:
        # a verify step is one enqueued device program, and bench.py's
        # dispatches-per-iteration figures must see the spec plane too
        # (the split-path baseline of --ragged-sweep under-counted by the
        # whole verify cadence before this)
        METRICS.inc("finchat_decode_dispatches_total")
        self.state, emitted, n_emitted, logits = verify_step(
            self.params, self.state, active, drafts, n_drafts,
            temperature, top_p, top_k,
            config=self.config, page_size=self.page_size,
            attn_backend=self.attn_backend, qm_backend=self.qm_backend, return_logits=return_logits,
        )
        return (emitted, n_emitted, logits) if return_logits else (emitted, n_emitted)
