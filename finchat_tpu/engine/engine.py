"""Inference engine: compiled prefill/decode step functions over the paged
KV cache.

TPU-first shape discipline (SURVEY §7.3 hard part #2): every jitted entry
point has ONE static shape —

- ``prefill_step``: batch 1 × ``prefill_chunk`` tokens. Arbitrary prompt
  lengths become a loop of fixed-size chunks (chunked prefill, SURVEY §5.7a)
  so there is no bucketing recompile storm.
- ``decode_step``: the full ``max_seqs`` slot batch, every step. Inactive
  slots ride along writing their KV to the trash page.

State is donated on every call, so XLA aliases the cache buffers in place
instead of copying the multi-GB pages each token.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
from jax import Array

from finchat_tpu.engine.kv_cache import (
    PagedKVCache,
    scatter_kv_chunk,
)
from finchat_tpu.engine.sampler import sample
from finchat_tpu.models.llama import LlamaConfig, forward
from finchat_tpu.ops.dispatch import paged_attention
from finchat_tpu.utils.config import EngineConfig
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@jax.tree_util.register_dataclass
@dataclass
class DecodeState:
    """Device-resident engine state (a pytree; all leaves are arrays)."""

    k_pages: Array  # [L, P, Hkv, page_size, hd]
    v_pages: Array
    page_table: Array  # [max_seqs, max_pages_per_seq] int32 (0 = trash)
    context_lens: Array  # [max_seqs] int32 — tokens whose KV is cached
    last_tokens: Array  # [max_seqs] int32 — next decode input per slot
    rng: Array


def create_state(
    config: LlamaConfig, engine_cfg: EngineConfig, max_pages_per_seq: int
) -> DecodeState:
    cache = PagedKVCache.create(config, engine_cfg.num_pages, engine_cfg.page_size)
    return DecodeState(
        k_pages=cache.k_pages,
        v_pages=cache.v_pages,
        page_table=jnp.zeros((engine_cfg.max_seqs, max_pages_per_seq), jnp.int32),
        context_lens=jnp.zeros((engine_cfg.max_seqs,), jnp.int32),
        last_tokens=jnp.zeros((engine_cfg.max_seqs,), jnp.int32),
        rng=jax.random.key(engine_cfg.max_seqs),
    )


def _paged_attention_fn(page_table: Array, start_pos: Array, n_valid: Array, page_size: int, attn_backend: str):
    """Build the model's attention callback for paged prefill/decode.

    ``page_table`` [B, max_pages], ``start_pos`` [B] (absolute position of
    the first query token), ``n_valid`` [B] (real tokens in this chunk; 0
    for inactive decode slots).
    """

    def attention(q: Array, k: Array, v: Array, layer_cache: Any, layer_idx: Array):
        k_l, v_l = layer_cache
        k_l, v_l = scatter_kv_chunk(k_l, v_l, k, v, page_table, start_pos, n_valid, page_size)
        out = paged_attention(
            q, k_l, v_l, page_table, start_pos, start_pos + n_valid,
            page_size=page_size, backend=attn_backend,
        )
        return out, (k_l, v_l)

    return attention


@partial(jax.jit, static_argnames=("config", "page_size", "attn_backend"), donate_argnums=(1,))
def prefill_step(
    params: dict[str, Any],
    state: DecodeState,
    tokens: Array,  # [1, C] — one chunk of one sequence's prompt
    slot: Array,  # scalar int32
    start_pos: Array,  # scalar int32 — absolute position of tokens[0]
    n_valid: Array,  # scalar int32 — real tokens in this chunk
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
) -> tuple[DecodeState, Array]:
    """Run one prefill chunk; returns (state, last-valid-token logits [vocab])."""
    C = tokens.shape[1]
    positions = (start_pos + jnp.arange(C))[None, :]  # [1, C]
    page_row = jax.lax.dynamic_slice_in_dim(state.page_table, slot, 1, axis=0)  # [1, max_pages]

    attention = _paged_attention_fn(page_row, start_pos[None], n_valid[None], page_size, attn_backend)
    logits, (k_pages, v_pages) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages),
    )
    last_logits = jnp.take_along_axis(
        logits[0], jnp.maximum(n_valid - 1, 0)[None, None], axis=0
    )[0]  # [vocab]

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        context_lens=state.context_lens.at[slot].add(n_valid),
    )
    return new_state, last_logits


@partial(jax.jit, donate_argnums=(0,))
def commit_first_token(
    state: DecodeState, slot: Array, logits: Array, temperature: Array, top_p: Array, top_k: Array
) -> tuple[DecodeState, Array]:
    """Sample the first generated token from prefill logits and arm the slot
    for decode. (temperature/top_p/top_k are scalars for this one sequence.)"""
    rng, sub = jax.random.split(state.rng)
    token = sample(logits[None], sub, temperature[None], top_p[None], top_k[None])[0]
    new_state = dataclasses.replace(
        state,
        last_tokens=state.last_tokens.at[slot].set(token),
        rng=rng,
    )
    return new_state, token


@partial(
    jax.jit,
    static_argnames=("config", "page_size", "attn_backend", "return_logits"),
    donate_argnums=(1,),
)
def decode_step(
    params: dict[str, Any],
    state: DecodeState,
    active: Array,  # [max_seqs] bool
    temperature: Array,  # [max_seqs]
    top_p: Array,  # [max_seqs]
    top_k: Array,  # [max_seqs] int32
    *,
    config: LlamaConfig,
    page_size: int,
    attn_backend: str = "ref",
    return_logits: bool = False,
) -> tuple[DecodeState, Array, Array | None]:
    """One decode step for ALL slots; returns (state, next_tokens [max_seqs]).

    Each active slot's ``last_token`` KV is appended at ``context_lens`` and
    the next token sampled from its logits. Inactive slots write to the
    trash page and their sampled tokens are ignored by the host.

    ``return_logits=True`` additionally returns the step logits [B, vocab]
    (fp32) — the host-side path for grammar-constrained sampling
    (agent/constrained.py), which overrides ``last_tokens`` afterwards.
    """
    B = state.last_tokens.shape[0]
    tokens = state.last_tokens[:, None]  # [B, 1]
    positions = state.context_lens[:, None]  # [B, 1]
    n_valid = active.astype(jnp.int32)  # [B]

    attention = _paged_attention_fn(state.page_table, state.context_lens, n_valid, page_size, attn_backend)
    logits, (k_pages, v_pages) = forward(
        params, tokens, positions,
        config=config, attention=attention,
        cache=(state.k_pages, state.v_pages),
    )
    step_logits = logits[:, 0, :]  # [B, vocab]

    rng, sub = jax.random.split(state.rng)
    next_tokens = sample(step_logits, sub, temperature, top_p, top_k)

    new_state = dataclasses.replace(
        state,
        k_pages=k_pages,
        v_pages=v_pages,
        context_lens=state.context_lens + n_valid,
        last_tokens=jnp.where(active, next_tokens, state.last_tokens),
        rng=rng,
    )
    return new_state, next_tokens, (step_logits if return_logits else None)


class InferenceEngine:
    """Host-side wrapper owning the device state and compiled steps.

    Synchronous single-sequence generation lives here (the minimum
    end-to-end slice, BASELINE config 1); the continuous-batching scheduler
    (engine/scheduler.py) drives the same step functions for many sequences.
    """

    def __init__(self, config: LlamaConfig, params: dict[str, Any], engine_cfg: EngineConfig,
                 mesh=None, attn_backend: str | None = None):
        from finchat_tpu.ops.dispatch import attention_backend

        self.config = config
        self.attn_backend = attn_backend or attention_backend()
        self.engine_cfg = engine_cfg
        self.page_size = engine_cfg.page_size
        self.max_pages_per_seq = min(
            engine_cfg.num_pages - 1,
            -(-engine_cfg.max_seq_len // engine_cfg.page_size),
        )
        self.mesh = mesh
        state = create_state(config, engine_cfg, self.max_pages_per_seq)
        if mesh is not None:
            # TP placement: params sharded Megatron-style, KV pages sharded
            # over KV heads on the model axis; XLA propagates the rest.
            from finchat_tpu.parallel.sharding import (
                llama_param_shardings,
                shard_decode_state,
                shard_params,
            )

            params = shard_params(params, llama_param_shardings(mesh))
            state = shard_decode_state(state, mesh)
        self.params = params
        self.state = state

    # --- low-level ops used by the scheduler ----------------------------
    def set_page_table_row(self, slot: int, pages: list[int]) -> None:
        row = jnp.zeros((self.max_pages_per_seq,), jnp.int32)
        row = row.at[: len(pages)].set(jnp.asarray(pages, jnp.int32))
        self.state = dataclasses.replace(
            self.state, page_table=self.state.page_table.at[slot].set(row)
        )

    def set_last_token(self, slot: int, token: int) -> None:
        """Override a slot's next decode input — used by grammar-constrained
        sampling after a host-side pick replaces the device-sampled token."""
        self.state = dataclasses.replace(
            self.state, last_tokens=self.state.last_tokens.at[slot].set(token)
        )

    def reset_slot(self, slot: int) -> None:
        self.state = dataclasses.replace(
            self.state,
            page_table=self.state.page_table.at[slot].set(0),
            context_lens=self.state.context_lens.at[slot].set(0),
            last_tokens=self.state.last_tokens.at[slot].set(0),
        )

    def prefill(self, slot: int, prompt_ids: list[int]) -> Array:
        """Chunked prefill of a whole prompt into a slot; returns the final
        chunk's last-token logits."""
        C = self.engine_cfg.prefill_chunk
        start = 0
        last_logits = None
        while start < len(prompt_ids):
            chunk = prompt_ids[start : start + C]
            n_valid = len(chunk)
            padded = chunk + [0] * (C - n_valid)
            tokens = jnp.asarray(padded, jnp.int32)[None, :]
            self.state, last_logits = prefill_step(
                self.params, self.state, tokens,
                jnp.int32(slot), jnp.int32(start), jnp.int32(n_valid),
                config=self.config, page_size=self.page_size,
                attn_backend=self.attn_backend,
            )
            start += n_valid
        assert last_logits is not None, "empty prompt"
        return last_logits

    def decode(self, active, temperature, top_p, top_k, return_logits: bool = False):
        self.state, next_tokens, logits = decode_step(
            self.params, self.state, active, temperature, top_p, top_k,
            config=self.config, page_size=self.page_size,
            attn_backend=self.attn_backend, return_logits=return_logits,
        )
        return (next_tokens, logits) if return_logits else next_tokens
