"""Paged KV cache: device arrays + host-side page allocator.

The reference has no KV cache (inference is a remote API call); this is the
memory system that makes long RAG contexts (unbounded history + up to 10,000
retrieved transactions, reference qdrant_tool.py:145 / llm_agent.py:234-236)
servable on fixed TPU HBM:

- Device side: ``k_pages``/``v_pages`` shaped ``[n_layers, num_pages,
  n_kv_heads, page_size, head_dim]`` — head-major, so one head's page is a
  contiguous ``(page_size, head_dim)`` tile, the unit the Pallas paged-
  attention kernel DMAs (Mosaic wants the trailing two dims tile-aligned).
  Physical page 0 is a TRASH page —
  writes from padding lanes and inactive slots are redirected there, which
  keeps every jitted step a fixed-shape scatter with no host branching.
- Host side: ``PageAllocator`` — a free list with ownership tracking and the
  scheduler invariants of SURVEY §5.2 enforced at every call: a page is
  owned by at most one sequence; double-free and foreign-free raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from finchat_tpu.models.llama import LlamaConfig
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

TRASH_PAGE = 0


@dataclass
class PagedKVCache:
    """Device-side paged cache tensors (a pytree; leaves have leading L axis
    so the model's ``lax.scan`` over layers slices one layer's pages)."""

    k_pages: Any  # [L, P, Hkv, page_size, head_dim]
    v_pages: Any  # [L, P, Hkv, page_size, head_dim]
    page_size: int
    num_pages: int

    @classmethod
    def create(cls, config: LlamaConfig, num_pages: int, page_size: int) -> "PagedKVCache":
        shape = (config.n_layers, num_pages, config.n_kv_heads, page_size, config.head_dim)
        return cls(
            k_pages=jnp.zeros(shape, config.dtype),
            v_pages=jnp.zeros(shape, config.dtype),
            page_size=page_size,
            num_pages=num_pages,
        )

    def layers_pytree(self) -> tuple[Any, Any]:
        """The (k, v) pair fed to the model forward as the scan-sliced cache."""
        return (self.k_pages, self.v_pages)

    def hbm_bytes(self) -> int:
        return self.k_pages.nbytes + self.v_pages.nbytes


class PageAllocationError(RuntimeError):
    pass


class PageAllocator:
    """Host-side free-list allocator with ownership invariants.

    Page 0 is reserved as the trash page and never handed out.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one is the trash page)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first
        self._owner: dict[int, str] = {}  # page id -> sequence id

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._owner)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, seq_id: str, n: int) -> list[int]:
        if n > len(self._free):
            raise PageAllocationError(
                f"requested {n} pages for {seq_id}, only {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert p not in self._owner, f"invariant violation: page {p} already owned"
            self._owner[p] = seq_id
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)
        return pages

    def free(self, seq_id: str, pages: list[int]) -> None:
        for p in pages:
            owner = self._owner.get(p)
            if owner is None:
                raise PageAllocationError(f"double free of page {p} by {seq_id}")
            if owner != seq_id:
                raise PageAllocationError(
                    f"sequence {seq_id} freeing page {p} owned by {owner}"
                )
            del self._owner[p]
            self._free.append(p)
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)

    def owned_by(self, seq_id: str) -> list[int]:
        return [p for p, s in self._owner.items() if s == seq_id]

    def check_invariants(self) -> None:
        """Every page is exactly one of {trash, free, owned-once}."""
        free_set = set(self._free)
        owned_set = set(self._owner)
        assert len(free_set) == len(self._free), "duplicate pages in free list"
        assert not (free_set & owned_set), "page both free and owned"
        assert TRASH_PAGE not in free_set and TRASH_PAGE not in owned_set
        assert free_set | owned_set | {TRASH_PAGE} == set(range(self.num_pages))


def pages_needed(n_tokens: int, page_size: int) -> int:
    return max(1, -(-n_tokens // page_size))


def scatter_kv_chunk(
    k_pages_layer: Any,  # [P, Hkv, page_size, hd] one layer's pages
    v_pages_layer: Any,
    k_new: Any,  # [B, C, Hkv, hd]
    v_new: Any,
    page_table: Any,  # [B, max_pages] int32 physical page ids (0 = trash)
    start_pos: Any,  # [B] int32 absolute position of chunk token 0
    n_valid: Any,  # [B] int32 how many of the C tokens are real
    page_size: int,
) -> tuple[Any, Any]:
    """Scatter a chunk of new K/V into the paged layout (fixed shapes).

    Token (b, i) lands at absolute position ``start_pos[b] + i`` →
    logical page ``pos // page_size``, offset ``pos % page_size``, physical
    page ``page_table[b, logical]``. Padding lanes (i >= n_valid[b]) are
    redirected to the trash page.
    """
    B, C = k_new.shape[:2]
    i = jnp.arange(C)[None, :]  # [1, C]
    pos = start_pos[:, None] + i  # [B, C]
    logical = pos // page_size
    offset = pos % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, C]
    valid = i < n_valid[:, None]
    phys = jnp.where(valid, phys, TRASH_PAGE)

    flat_phys = phys.reshape(-1)  # [B*C]
    flat_off = offset.reshape(-1)
    # token (page, head, offset) destination; heads ride along as a slice
    k_flat = k_new.reshape(B * C, *k_new.shape[2:])  # [B*C, Hkv, hd]
    v_flat = v_new.reshape(B * C, *v_new.shape[2:])
    k_pages_layer = k_pages_layer.at[flat_phys, :, flat_off].set(k_flat, mode="drop")
    v_pages_layer = v_pages_layer.at[flat_phys, :, flat_off].set(v_flat, mode="drop")
    return k_pages_layer, v_pages_layer


def gather_kv(
    k_pages_layer: Any,  # [P, Hkv, page_size, hd]
    v_pages_layer: Any,
    page_table: Any,  # [B, max_pages]
    page_size: int,
) -> tuple[Any, Any]:
    """Gather each sequence's pages into a contiguous [B, max_len, Hkv, hd]
    view (max_len = max_pages * page_size). Reference path; the Pallas paged
    kernel reads pages in place instead."""
    B, max_pages = page_table.shape
    k = k_pages_layer[page_table]  # [B, max_pages, Hkv, page_size, hd]
    v = v_pages_layer[page_table]
    k = k.transpose(0, 1, 3, 2, 4).reshape(B, max_pages * page_size, k.shape[2], k.shape[4])
    v = v.transpose(0, 1, 3, 2, 4).reshape(B, max_pages * page_size, v.shape[2], v.shape[4])
    return k, v
