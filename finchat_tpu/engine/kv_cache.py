"""Paged KV cache: device arrays + host-side page allocator.

The reference has no KV cache (inference is a remote API call); this is the
memory system that makes long RAG contexts (unbounded history + up to 10,000
retrieved transactions, reference qdrant_tool.py:145 / llm_agent.py:234-236)
servable on fixed TPU HBM:

- Device side: ``k_pages``/``v_pages`` shaped ``[n_layers, num_pages,
  page_size, n_kv_heads * head_dim]`` — token-major pages with the KV heads
  fused into the minor dim. This layout is chosen for Mosaic's DMA tiling
  rules (measured on v5e, round 4): a page's trailing dims
  ``(page_size, Hkv*hd)`` are tile-aligned, so the in-place decode append
  kernel (ops/kv_append.py) can RMW one whole page per sequence with legal
  full-extent DMAs, and the paged attention kernel (ops/paged_attention.py)
  value-slices per-head ``[PS, hd]`` tiles out of the loaded block. The
  leading layer axis exists because the cache rides the model's layer scan
  as a CARRY (not xs→ys): XLA restacks xs→ys cache updates into a fresh
  buffer every step — a full-cache copy measured at ~22 ms/step for a 1.5 GB
  cache — while kernels with ``input_output_aliases`` update the carried
  buffer in place.
  Physical page 0 is a TRASH page — writes from padding lanes and inactive
  slots are redirected there, which keeps every jitted step a fixed-shape
  write with no host branching.
- Host side: ``PageAllocator`` — a free list with ownership tracking and the
  scheduler invariants of SURVEY §5.2 enforced at every call: a page is
  owned by at most one sequence; double-free and foreign-free raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from finchat_tpu.models.llama import LlamaConfig
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

TRASH_PAGE = 0


def scale_rows(n_kv: int) -> int:
    """Rows of the per-page scale block: KV heads padded to a sublane
    multiple so the ``[rows, page_size]`` trailing dims of the scale arrays
    are Mosaic-tile-aligned (fp32 tiles are (8, 128))."""
    return -(-n_kv // 8) * 8


@dataclass
class PagedKVCache:
    """Device-side paged cache tensors (a pytree; the leading layer axis is
    carried through the model's ``lax.scan`` and indexed per layer by the
    kernels via scalar prefetch).

    ``kv_quant="int8"`` stores pages as int8 with PER-TOKEN-PER-HEAD fp32
    scales in parallel ``[L, P, scale_rows, page_size]`` arrays (~6%
    overhead at head_dim 64): each token row is quantized independently at
    write time, so the append kernel's page RMW never requantizes existing
    rows — no drift — and per-step HBM traffic for the KV read halves.
    When off, the scale leaves are kept as (1,1,1,1) placeholders so the
    engine state pytree structure is identical in both modes."""

    k_pages: Any  # [L, P, page_size, Hkv * head_dim] (dtype or int8)
    v_pages: Any
    k_scales: Any  # [L, P, scale_rows(Hkv), page_size] fp32 (or (1,1,1,1))
    v_scales: Any
    page_size: int
    num_pages: int
    kv_quant: str = ""

    @classmethod
    def create(cls, config: LlamaConfig, num_pages: int, page_size: int,
               kv_quant: str = "") -> "PagedKVCache":
        shape = (
            config.n_layers, num_pages, page_size,
            config.n_kv_heads * config.head_dim,
        )
        if kv_quant:
            if kv_quant != "int8":
                raise ValueError(f"unknown kv_quant mode {kv_quant!r} (supported: 'int8')")
            sshape = (config.n_layers, num_pages, scale_rows(config.n_kv_heads), page_size)
            return cls(
                k_pages=jnp.zeros(shape, jnp.int8),
                v_pages=jnp.zeros(shape, jnp.int8),
                k_scales=jnp.zeros(sshape, jnp.float32),
                v_scales=jnp.zeros(sshape, jnp.float32),
                page_size=page_size, num_pages=num_pages, kv_quant=kv_quant,
            )
        return cls(
            k_pages=jnp.zeros(shape, config.dtype),
            v_pages=jnp.zeros(shape, config.dtype),
            k_scales=jnp.zeros((1, 1, 1, 1), jnp.float32),
            v_scales=jnp.zeros((1, 1, 1, 1), jnp.float32),
            page_size=page_size, num_pages=num_pages,
        )

    def layers_pytree(self) -> tuple[Any, Any, Any, Any]:
        """The (k, v, k_scales, v_scales) tuple carried through the model
        forward as the cache (scales are placeholders when kv_quant is
        off — the attention callbacks always unpack four)."""
        return (self.k_pages, self.v_pages, self.k_scales, self.v_scales)

    def hbm_bytes(self) -> int:
        return (self.k_pages.nbytes + self.v_pages.nbytes
                + self.k_scales.nbytes + self.v_scales.nbytes)


def page_hbm_bytes(config: LlamaConfig, page_size: int, kv_quant: str = "") -> int:
    """HBM bytes ONE page costs across all layers (K+V, plus the int8
    scale rows) — computed WITHOUT allocating, so harnesses can fit a KV
    pool to an HBM budget before engine construction. Mirrors
    ``PagedKVCache.create``'s shapes exactly (asserted in
    tests/test_kv_cache.py)."""
    import numpy as np

    row = config.n_kv_heads * config.head_dim
    itemsize = 1 if kv_quant else np.dtype(config.dtype).itemsize
    per = 2 * config.n_layers * page_size * row * itemsize
    if kv_quant:
        per += 2 * config.n_layers * scale_rows(config.n_kv_heads) * page_size * 4
    return per


class PageAllocationError(RuntimeError):
    pass


class PageAllocator:
    """Host-side free-list allocator with ownership invariants.

    Page 0 is reserved as the trash page and never handed out.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one is the trash page)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first
        self._owner: dict[int, str] = {}  # page id -> sequence id

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._owner)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, seq_id: str, n: int) -> list[int]:
        if n > len(self._free):
            raise PageAllocationError(
                f"requested {n} pages for {seq_id}, only {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert p not in self._owner, f"invariant violation: page {p} already owned"
            self._owner[p] = seq_id
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)
        return pages

    def free(self, seq_id: str, pages: list[int]) -> None:
        for p in pages:
            owner = self._owner.get(p)
            if owner is None:
                raise PageAllocationError(f"double free of page {p} by {seq_id}")
            if owner != seq_id:
                raise PageAllocationError(
                    f"sequence {seq_id} freeing page {p} owned by {owner}"
                )
            del self._owner[p]
            self._free.append(p)
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)

    def owned_by(self, seq_id: str) -> list[int]:
        return [p for p, s in self._owner.items() if s == seq_id]

    def reset(self) -> None:
        """Return EVERY page to the free list, dropping all ownership —
        the engine-rebuild path (scheduler breaker trip): the device KV
        pool was just torn down and recreated, so nothing the old owners
        pointed at exists anymore. Never valid while any owner still
        expects its pages to hold live KV."""
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._owner.clear()
        METRICS.set_gauge("finchat_kv_pages_used", 0)

    def check_invariants(self) -> None:
        """Every page is exactly one of {trash, free, owned-once}."""
        free_set = set(self._free)
        owned_set = set(self._owner)
        assert len(free_set) == len(self._free), "duplicate pages in free list"
        assert not (free_set & owned_set), "page both free and owned"
        assert TRASH_PAGE not in free_set and TRASH_PAGE not in owned_set
        assert free_set | owned_set | {TRASH_PAGE} == set(range(self.num_pages))


def pages_needed(n_tokens: int, page_size: int) -> int:
    return max(1, -(-n_tokens // page_size))


@dataclass(frozen=True)
class BoundedKVPolicy:
    """SnapStream-style bounded-KV serving policy (ISSUE 15): the first
    ``sink_pages`` pages of a row are PINNED (the attention sink) and a
    sliding window of the ``window_pages`` most recent pages survives;
    everything in between is evicted back to the page pool as the context
    grows, so a live 100k-token session occupies at most
    ``sink_pages + window_pages`` pages and decodes at flat per-token cost.

    Eviction is pure host metadata riding the paged indirection: an evicted
    page leaves the row's logical→physical page list (later pages shift one
    logical slot down — physically nothing moves) and returns to the
    allocator. The row tracks ``kv_gap`` — evicted tokens, always a whole
    multiple of ``page_size`` — and every KV WRITE and attention MASK runs
    in COMPACTED coordinates (``absolute - kv_gap``) while positions/rotary
    stay ABSOLUTE (keys carry their original RoPE; relative distances to
    surviving tokens are exact). Compacted-coordinate masking is exact for
    the surviving set: a new token's q position always exceeds every
    evicted position, so ``c_kv <= c_q`` iff ``abs_kv <= abs_q`` for sink
    and window tokens alike (tests/test_bounded_kv.py pins this against the
    unbounded oracle while the context still fits).

    All methods are pure host-side integer math (no device work, no syncs)
    — the scheduler's eviction wave calls them between dispatches, and the
    free-run staging uses them to cap captures at eviction boundaries so a
    captured round's gap schedule is identical to the host-stepped one.
    """

    sink_pages: int
    window_pages: int
    page_size: int

    @property
    def enabled(self) -> bool:
        return self.sink_pages > 0 and self.window_pages > 0

    @property
    def budget_pages(self) -> int:
        """Max pages a bounded row ever occupies (its whole page list)."""
        return self.sink_pages + self.window_pages

    @property
    def sink_tokens(self) -> int:
        return self.sink_pages * self.page_size

    def validate(self, *, prefill_chunk: int, max_pages_per_seq: int,
                 decode_loop_depth: int = 1, spec_tokens: int = 0) -> None:
        """Feasibility at engine construction: the window must always be
        able to make room for the next dispatch's writes by evicting full
        post-sink pages — a chunk (prefill) or a fused/spec burst (decode)
        plus one partial page of already-written tail must fit."""
        if not self.enabled:
            return
        if self.sink_pages < 1 or self.window_pages < 1:
            raise ValueError(
                "bounded KV needs kv_sink_pages >= 1 and kv_window_pages >= 1 "
                f"(got sink={self.sink_pages}, window={self.window_pages}); "
                "set both to 0 for unbounded serving"
            )
        burst = max(prefill_chunk,
                    1 + max(decode_loop_depth - 1, spec_tokens))
        need = -(-burst // self.page_size) + 2  # burst + partial tail + slack
        if self.window_pages < need:
            raise ValueError(
                f"kv_window_pages={self.window_pages} cannot hold a "
                f"{burst}-token dispatch burst between eviction waves; "
                f"need >= {need} pages of {self.page_size} tokens "
                "(grow the window or shrink prefill_chunk)"
            )
        if self.budget_pages > max_pages_per_seq:
            raise ValueError(
                f"bounded budget {self.budget_pages} pages exceeds "
                f"max_pages_per_seq={max_pages_per_seq}; grow max_seq_len "
                "or shrink the sink/window"
            )

    def row_pages(self, n_tokens: int) -> int:
        """Pages a bounded row needs for ``n_tokens`` of (compacted)
        context — the unbounded requirement capped at the budget."""
        return min(pages_needed(n_tokens, self.page_size), self.budget_pages)

    def plan_eviction(self, compacted_ctx: int, incoming: int,
                      capacity_pages: int, pinned_pages: int) -> int:
        """How many whole post-sink pages to evict so the next dispatch's
        ``incoming`` tokens fit the row's ``capacity_pages`` page list.
        ``compacted_ctx`` is the row's compacted written length (absolute
        minus kv_gap, INCLUDING tokens still in flight); ``pinned_pages``
        is the unevictable head (``max(sink_pages, shared head pages)`` —
        a shared-prefix head larger than the sink is pinned whole, an
        effectively larger sink for that row). Returns 0 when everything
        already fits. Deterministic in the written-token count alone — the
        freerun capture-vs-host-stepped identity leans on this."""
        need = -(-(compacted_ctx + incoming) // self.page_size)
        e = max(0, need - capacity_pages)
        if e == 0:
            return 0
        # only FULL post-sink pages are evictable (the newest, possibly
        # partial page holds the live tail; pinned head pages never move)
        evictable = max(0, compacted_ctx // self.page_size - pinned_pages)
        if e > evictable:
            raise PageAllocationError(
                f"bounded eviction infeasible: need {e} pages, only "
                f"{evictable} evictable (ctx={compacted_ctx}, "
                f"incoming={incoming}, capacity={capacity_pages}, "
                f"pinned={pinned_pages})"
            )
        return e


def scatter_kv_chunk(
    k_pages: Any,  # [L, P, page_size, Hkv*hd] full-depth cache
    v_pages: Any,
    k_new: Any,  # [B, C, Hkv, hd]
    v_new: Any,
    page_table: Any,  # [B, max_pages] int32 physical page ids (0 = trash)
    start_pos: Any,  # [B] int32 absolute position of chunk token 0
    n_valid: Any,  # [B] int32 how many of the C tokens are real
    page_size: int,
    layer: Any,  # scalar int32 — which layer's pages to write
) -> tuple[Any, Any]:
    """Scatter a chunk of new K/V into one layer's pages (fixed shapes).

    Token (b, i) lands at absolute position ``start_pos[b] + i`` →
    logical page ``pos // page_size``, offset ``pos % page_size``, physical
    page ``page_table[b, logical]``. Padding lanes (i >= n_valid[b]) are
    redirected to the trash page.

    This is the PREFILL write path (and the jnp reference path for decode):
    an XLA scatter, which costs a full-cache copy per call — fine amortized
    over a whole batched prefill chunk, ruinous per decode token. Decode
    uses the in-place Pallas append (ops/kv_append.py) instead.
    """
    B, C = k_new.shape[:2]
    hd_fused = k_pages.shape[-1]
    i = jnp.arange(C)[None, :]  # [1, C]
    pos = start_pos[:, None] + i  # [B, C]
    logical = pos // page_size
    offset = pos % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, C]
    valid = i < n_valid[:, None]
    phys = jnp.where(valid, phys, TRASH_PAGE)

    lay = jnp.broadcast_to(jnp.asarray(layer, jnp.int32), (B * C,))
    flat_phys = phys.reshape(-1)  # [B*C]
    flat_off = offset.reshape(-1)
    k_flat = k_new.reshape(B * C, hd_fused)  # token rows, heads fused
    v_flat = v_new.reshape(B * C, hd_fused)
    k_pages = k_pages.at[lay, flat_phys, flat_off].set(k_flat, mode="drop")
    v_pages = v_pages.at[lay, flat_phys, flat_off].set(v_flat, mode="drop")
    return k_pages, v_pages


def gather_pages_host(
    k_pages: Any,
    v_pages: Any,
    k_scales: Any,
    v_scales: Any,
    page_ids: list[int],
) -> tuple[Any, Any, Any | None, Any | None]:
    """Copy a set of physical pages device→host across all layers: returns
    ``(k [L, n, PS, row], v, k_scales | None, v_scales | None)`` as numpy.

    Session-cache OFFLOAD path (engine/session_cache.py). Deliberately NOT
    jitted and deliberately synchronous: the gather rides the ordinary
    dispatch stream, so it serializes AFTER every already-dispatched step
    that might still append into these pages, and ``device_get`` blocks
    until the copy lands — the caller frees the pages immediately after,
    so returning before the read completed would race the next sequence's
    writes. Per-turn cost, never on the per-token hot path."""
    import numpy as np

    ids = jnp.asarray(page_ids, jnp.int32)
    quantized = k_pages.dtype == jnp.int8
    k = np.asarray(jax.device_get(jnp.take(k_pages, ids, axis=1)))
    v = np.asarray(jax.device_get(jnp.take(v_pages, ids, axis=1)))
    ks = vs = None
    if quantized:
        ks = np.asarray(jax.device_get(jnp.take(k_scales, ids, axis=1)))
        vs = np.asarray(jax.device_get(jnp.take(v_scales, ids, axis=1)))
    return k, v, ks, vs


def scatter_pages_device(
    k_pages: Any,
    v_pages: Any,
    k_scales: Any,
    v_scales: Any,
    page_ids: list[int],
    host: tuple,
) -> tuple[Any, Any, Any, Any]:
    """Write host page snapshots (``gather_pages_host`` layout, possibly a
    leading slice of one) back into freshly allocated physical pages.

    Session-cache RESTORE path. An XLA scatter — one full-cache copy per
    restore, amortized over a whole turn (the same trade ``scatter_kv_chunk``
    makes per prefill chunk); never called from a jitted step."""
    import numpy as np

    ids = jnp.asarray(page_ids, jnp.int32)
    k, v, ks, vs = host
    n = len(page_ids)
    assert k.shape[1] >= n, f"snapshot holds {k.shape[1]} pages, need {n}"
    # cross-MODE snapshots must fail loudly, not cast silently: an int8
    # snapshot .set() into a bf16 pool (or a bf16 one into int8) would
    # value-cast into plausible-looking garbage KV. Callers refuse earlier
    # (session tier / import guards, counted); this is the last line.
    if np.dtype(k.dtype) != np.dtype(k_pages.dtype):
        raise ValueError(
            f"snapshot dtype {np.dtype(k.dtype).name} does not match the "
            f"page-pool dtype {np.dtype(k_pages.dtype).name} (cross-mode "
            "restore refused)"
        )
    k_pages = k_pages.at[:, ids].set(jnp.asarray(k[:, :n]))
    v_pages = v_pages.at[:, ids].set(jnp.asarray(v[:, :n]))
    if k_pages.dtype == jnp.int8:
        assert ks is not None and vs is not None, "int8 cache needs scale snapshots"
        k_scales = k_scales.at[:, ids].set(jnp.asarray(ks[:, :n]))
        v_scales = v_scales.at[:, ids].set(jnp.asarray(vs[:, :n]))
    return k_pages, v_pages, k_scales, v_scales


def quantize_kv_rows(x: Any, n_kv: int) -> tuple[Any, Any]:
    """Per-token-per-head symmetric int8 quantization of KV rows.

    ``x``: [..., Hkv*hd] float — returns (q8 [..., Hkv*hd] int8,
    scales [..., Hkv] fp32) with scale = amax over the head's channels /
    127 (1.0 for all-zero rows so dequant is exact).
    """
    lead = x.shape[:-1]
    hd = x.shape[-1] // n_kv
    xh = x.reshape(*lead, n_kv, hd).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xh), axis=-1)  # [..., Hkv]
    scales = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(xh / scales[..., None]), -127, 127).astype(jnp.int8)
    return q.reshape(*lead, n_kv * hd), scales


def scatter_kv_chunk_q8(
    k_pages: Any,  # [L, P, page_size, Hkv*hd] int8
    v_pages: Any,
    k_scales: Any,  # [L, P, scale_rows, page_size] fp32
    v_scales: Any,
    k_new: Any,  # [B, C, Hkv, hd] float
    v_new: Any,
    page_table: Any,  # [B, max_pages]
    start_pos: Any,  # [B]
    n_valid: Any,  # [B]
    page_size: int,
    layer: Any,
    n_kv: int,
) -> tuple[Any, Any, Any, Any]:
    """Quantizing variant of ``scatter_kv_chunk``: int8 rows into the data
    pages, per-token-per-head scales into the scale pages. Same trash-page
    redirection; scale writes for trash lanes land in the trash page's
    scale block."""
    B, C = k_new.shape[:2]
    hd_fused = k_pages.shape[-1]
    i = jnp.arange(C)[None, :]
    pos = start_pos[:, None] + i
    logical = pos // page_size
    offset = pos % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)
    valid = i < n_valid[:, None]
    phys = jnp.where(valid, phys, TRASH_PAGE)

    k_q, k_s = quantize_kv_rows(k_new.reshape(B, C, hd_fused), n_kv)
    v_q, v_s = quantize_kv_rows(v_new.reshape(B, C, hd_fused), n_kv)

    lay = jnp.broadcast_to(jnp.asarray(layer, jnp.int32), (B * C,))
    flat_phys = phys.reshape(-1)
    flat_off = offset.reshape(-1)
    k_pages = k_pages.at[lay, flat_phys, flat_off].set(
        k_q.reshape(B * C, hd_fused), mode="drop")
    v_pages = v_pages.at[lay, flat_phys, flat_off].set(
        v_q.reshape(B * C, hd_fused), mode="drop")
    # scale layout is [.., head_row, token_col]: ONE combined scatter per
    # array (a broadcast head-index column) — per-head scatters would each
    # rebuild the full scale buffer (the usual XLA scatter copy)
    heads = jnp.arange(n_kv)[None, :]  # [1, Hkv]
    k_scales = k_scales.at[lay[:, None], flat_phys[:, None], heads, flat_off[:, None]].set(
        k_s.reshape(-1, n_kv), mode="drop")
    v_scales = v_scales.at[lay[:, None], flat_phys[:, None], heads, flat_off[:, None]].set(
        v_s.reshape(-1, n_kv), mode="drop")
    return k_pages, v_pages, k_scales, v_scales


def gather_kv_any(
    k_pages: Any,
    v_pages: Any,
    k_scales: Any,
    v_scales: Any,
    page_table: Any,
    page_size: int,
    layer: Any,
    n_kv: int,
    dtype: Any = jnp.bfloat16,
) -> tuple[Any, Any]:
    """``gather_kv`` dispatching on the cache dtype — the ONE place the
    int8-vs-native READ choice lives for the jnp gather paths (the
    reference attention backend and the SP-segment prefix fold)."""
    if k_pages.dtype == jnp.int8:
        return gather_kv_q8(
            k_pages, v_pages, k_scales, v_scales, page_table, page_size,
            layer, n_kv, dtype=dtype,
        )
    return gather_kv(k_pages, v_pages, page_table, page_size, layer, n_kv)


def gather_kv_q8(
    k_pages: Any,  # [L, P, page_size, Hkv*hd] int8
    v_pages: Any,
    k_scales: Any,  # [L, P, scale_rows, page_size] fp32
    v_scales: Any,
    page_table: Any,  # [B, max_pages]
    page_size: int,
    layer: Any,
    n_kv: int,
    dtype: Any = jnp.bfloat16,
) -> tuple[Any, Any]:
    """Dequantizing variant of ``gather_kv`` (the jnp reference path for
    the int8 cache): returns dense [B, max_len, Hkv, hd] in ``dtype``."""
    B, max_pages = page_table.shape

    def deq(pages, scales):
        p_l = jax.lax.dynamic_index_in_dim(pages, layer, 0, keepdims=False)
        s_l = jax.lax.dynamic_index_in_dim(scales, layer, 0, keepdims=False)
        x = p_l[page_table]  # [B, MP, PS, Hkv*hd] int8
        s = s_l[page_table]  # [B, MP, SPAD, PS] fp32
        PS = x.shape[2]
        hd = x.shape[-1] // n_kv
        xh = x.reshape(B, max_pages, PS, n_kv, hd).astype(jnp.float32)
        s_t = s[:, :, :n_kv, :].transpose(0, 1, 3, 2)  # [B, MP, PS, Hkv]
        out = (xh * s_t[..., None]).astype(dtype)
        return out.reshape(B, max_pages * PS, n_kv, hd)

    return deq(k_pages, k_scales), deq(v_pages, v_scales)


def gather_kv(
    k_pages: Any,  # [L, P, page_size, Hkv*hd]
    v_pages: Any,
    page_table: Any,  # [B, max_pages]
    page_size: int,
    layer: Any,  # scalar int32
    n_kv: int,
) -> tuple[Any, Any]:
    """Gather one layer's pages for each sequence into a contiguous
    [B, max_len, Hkv, hd] view (max_len = max_pages * page_size). Reference
    path; the Pallas paged kernel reads pages in place instead."""
    B, max_pages = page_table.shape
    k_l = jax.lax.dynamic_index_in_dim(k_pages, layer, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(v_pages, layer, 0, keepdims=False)
    k = k_l[page_table]  # [B, max_pages, page_size, Hkv*hd]
    v = v_l[page_table]
    T = max_pages * page_size
    k = k.reshape(B, T, n_kv, k.shape[-1] // n_kv)
    v = v.reshape(B, T, n_kv, v.shape[-1] // n_kv)
    return k, v
