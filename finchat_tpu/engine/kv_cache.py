"""Paged KV cache: device arrays + host-side page allocator.

The reference has no KV cache (inference is a remote API call); this is the
memory system that makes long RAG contexts (unbounded history + up to 10,000
retrieved transactions, reference qdrant_tool.py:145 / llm_agent.py:234-236)
servable on fixed TPU HBM:

- Device side: ``k_pages``/``v_pages`` shaped ``[n_layers, num_pages,
  page_size, n_kv_heads * head_dim]`` — token-major pages with the KV heads
  fused into the minor dim. This layout is chosen for Mosaic's DMA tiling
  rules (measured on v5e, round 4): a page's trailing dims
  ``(page_size, Hkv*hd)`` are tile-aligned, so the in-place decode append
  kernel (ops/kv_append.py) can RMW one whole page per sequence with legal
  full-extent DMAs, and the paged attention kernel (ops/paged_attention.py)
  value-slices per-head ``[PS, hd]`` tiles out of the loaded block. The
  leading layer axis exists because the cache rides the model's layer scan
  as a CARRY (not xs→ys): XLA restacks xs→ys cache updates into a fresh
  buffer every step — a full-cache copy measured at ~22 ms/step for a 1.5 GB
  cache — while kernels with ``input_output_aliases`` update the carried
  buffer in place.
  Physical page 0 is a TRASH page — writes from padding lanes and inactive
  slots are redirected there, which keeps every jitted step a fixed-shape
  write with no host branching.
- Host side: ``PageAllocator`` — a free list with ownership tracking and the
  scheduler invariants of SURVEY §5.2 enforced at every call: a page is
  owned by at most one sequence; double-free and foreign-free raise.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp

from finchat_tpu.models.llama import LlamaConfig
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

TRASH_PAGE = 0


@dataclass
class PagedKVCache:
    """Device-side paged cache tensors (a pytree; the leading layer axis is
    carried through the model's ``lax.scan`` and indexed per layer by the
    kernels via scalar prefetch)."""

    k_pages: Any  # [L, P, page_size, Hkv * head_dim]
    v_pages: Any
    page_size: int
    num_pages: int

    @classmethod
    def create(cls, config: LlamaConfig, num_pages: int, page_size: int) -> "PagedKVCache":
        shape = (
            config.n_layers, num_pages, page_size,
            config.n_kv_heads * config.head_dim,
        )
        return cls(
            k_pages=jnp.zeros(shape, config.dtype),
            v_pages=jnp.zeros(shape, config.dtype),
            page_size=page_size,
            num_pages=num_pages,
        )

    def layers_pytree(self) -> tuple[Any, Any]:
        """The (k, v) pair carried through the model forward as the cache."""
        return (self.k_pages, self.v_pages)

    def hbm_bytes(self) -> int:
        return self.k_pages.nbytes + self.v_pages.nbytes


class PageAllocationError(RuntimeError):
    pass


class PageAllocator:
    """Host-side free-list allocator with ownership invariants.

    Page 0 is reserved as the trash page and never handed out.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (one is the trash page)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first
        self._owner: dict[int, str] = {}  # page id -> sequence id

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def used_count(self) -> int:
        return len(self._owner)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) >= n

    def allocate(self, seq_id: str, n: int) -> list[int]:
        if n > len(self._free):
            raise PageAllocationError(
                f"requested {n} pages for {seq_id}, only {len(self._free)} free"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            assert p not in self._owner, f"invariant violation: page {p} already owned"
            self._owner[p] = seq_id
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)
        return pages

    def free(self, seq_id: str, pages: list[int]) -> None:
        for p in pages:
            owner = self._owner.get(p)
            if owner is None:
                raise PageAllocationError(f"double free of page {p} by {seq_id}")
            if owner != seq_id:
                raise PageAllocationError(
                    f"sequence {seq_id} freeing page {p} owned by {owner}"
                )
            del self._owner[p]
            self._free.append(p)
        METRICS.set_gauge("finchat_kv_pages_used", self.used_count)

    def owned_by(self, seq_id: str) -> list[int]:
        return [p for p, s in self._owner.items() if s == seq_id]

    def check_invariants(self) -> None:
        """Every page is exactly one of {trash, free, owned-once}."""
        free_set = set(self._free)
        owned_set = set(self._owner)
        assert len(free_set) == len(self._free), "duplicate pages in free list"
        assert not (free_set & owned_set), "page both free and owned"
        assert TRASH_PAGE not in free_set and TRASH_PAGE not in owned_set
        assert free_set | owned_set | {TRASH_PAGE} == set(range(self.num_pages))


def pages_needed(n_tokens: int, page_size: int) -> int:
    return max(1, -(-n_tokens // page_size))


def scatter_kv_chunk(
    k_pages: Any,  # [L, P, page_size, Hkv*hd] full-depth cache
    v_pages: Any,
    k_new: Any,  # [B, C, Hkv, hd]
    v_new: Any,
    page_table: Any,  # [B, max_pages] int32 physical page ids (0 = trash)
    start_pos: Any,  # [B] int32 absolute position of chunk token 0
    n_valid: Any,  # [B] int32 how many of the C tokens are real
    page_size: int,
    layer: Any,  # scalar int32 — which layer's pages to write
) -> tuple[Any, Any]:
    """Scatter a chunk of new K/V into one layer's pages (fixed shapes).

    Token (b, i) lands at absolute position ``start_pos[b] + i`` →
    logical page ``pos // page_size``, offset ``pos % page_size``, physical
    page ``page_table[b, logical]``. Padding lanes (i >= n_valid[b]) are
    redirected to the trash page.

    This is the PREFILL write path (and the jnp reference path for decode):
    an XLA scatter, which costs a full-cache copy per call — fine amortized
    over a whole batched prefill chunk, ruinous per decode token. Decode
    uses the in-place Pallas append (ops/kv_append.py) instead.
    """
    B, C = k_new.shape[:2]
    hd_fused = k_pages.shape[-1]
    i = jnp.arange(C)[None, :]  # [1, C]
    pos = start_pos[:, None] + i  # [B, C]
    logical = pos // page_size
    offset = pos % page_size
    phys = jnp.take_along_axis(page_table, logical, axis=1)  # [B, C]
    valid = i < n_valid[:, None]
    phys = jnp.where(valid, phys, TRASH_PAGE)

    lay = jnp.broadcast_to(jnp.asarray(layer, jnp.int32), (B * C,))
    flat_phys = phys.reshape(-1)  # [B*C]
    flat_off = offset.reshape(-1)
    k_flat = k_new.reshape(B * C, hd_fused)  # token rows, heads fused
    v_flat = v_new.reshape(B * C, hd_fused)
    k_pages = k_pages.at[lay, flat_phys, flat_off].set(k_flat, mode="drop")
    v_pages = v_pages.at[lay, flat_phys, flat_off].set(v_flat, mode="drop")
    return k_pages, v_pages


def gather_kv(
    k_pages: Any,  # [L, P, page_size, Hkv*hd]
    v_pages: Any,
    page_table: Any,  # [B, max_pages]
    page_size: int,
    layer: Any,  # scalar int32
    n_kv: int,
) -> tuple[Any, Any]:
    """Gather one layer's pages for each sequence into a contiguous
    [B, max_len, Hkv, hd] view (max_len = max_pages * page_size). Reference
    path; the Pallas paged kernel reads pages in place instead."""
    B, max_pages = page_table.shape
    k_l = jax.lax.dynamic_index_in_dim(k_pages, layer, 0, keepdims=False)
    v_l = jax.lax.dynamic_index_in_dim(v_pages, layer, 0, keepdims=False)
    k = k_l[page_table]  # [B, max_pages, page_size, Hkv*hd]
    v = v_l[page_table]
    T = max_pages * page_size
    k = k.reshape(B, T, n_kv, k.shape[-1] // n_kv)
    v = v.reshape(B, T, n_kv, v.shape[-1] // n_kv)
    return k, v
