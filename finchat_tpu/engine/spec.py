"""Prompt-lookup draft proposal for speculative decoding.

No reference counterpart (the reference's LLM is a remote API). The
workload argument: the reference stuffs retrieved transaction rows and
chat history into the prompt (``qdrant_tool.py:145``, ``llm_agent.py:
234-236``) and the model's answers quote them back — generated text
heavily overlaps the prompt. Prompt-lookup decoding (n-gram matching
against the sequence's own token history) drafts those continuations for
free on the host: no draft model, no extra device memory, and the verify
step (engine.verify_step) scores all drafts in one weights-read. On a
miss the sequence degrades to plain one-token decode — token-for-token
identical to the non-speculative path under greedy, and each verify step
costs about the same device time as a decode step (measured ~1.07x, see
PERF_r04.md). Throughput is not strictly never-worse, though: the
scheduler's spec mode runs depth-1 (dispatch then consume serially), so
on sustained all-miss traffic it gives up the depth-2 device/host
overlap of the plain decode path. The scheduler therefore drops a
sequence back to the pipelined non-spec path after
``SPEC_MISS_DEMOTE`` consecutive empty/all-rejected proposals.

``NgramIndex`` is incremental — O(n-gram widths) per appended token and
O(1) per proposal — because the scheduler proposes on the asyncio event
loop every verify step for every greedy slot; rescanning a few thousand
history tokens per slot per step would stall the very decode cadence
speculation is meant to speed up.
"""

from __future__ import annotations


class NgramIndex:
    """Incremental most-recent-occurrence index over a token history.

    For each n in ``[min_ngram, ngram]`` tracks where the latest and
    second-latest occurrence of every n-gram CONTINUES (the position right
    after it). ``propose`` matches the history's suffix n-gram (longest n
    first) against its second-latest occurrence — the latest is always the
    suffix itself — and drafts the tokens that followed it.
    """

    def __init__(self, history: list[int] | None = None, *,
                 ngram: int = 3, min_ngram: int = 2, max_history: int = 4096):
        assert 1 <= min_ngram <= ngram
        self._ns = tuple(range(ngram, min_ngram - 1, -1))  # longest first
        self._h: list[int] = []
        self._latest: dict[tuple, int] = {}
        self._prev: dict[tuple, int] = {}
        # cap the initial build: indexing a 32k-token ring-prefilled prompt
        # would do ~2 dict inserts per token ON THE EVENT LOOP (the
        # scheduler builds lazily at the first spec step); matches the
        # one-shot wrapper's cap below
        for tok in (history or [])[-max_history:]:
            self.push(tok)

    def push(self, token: int) -> None:
        """Append one token and index the n-grams it completes."""
        h = self._h
        h.append(token)
        L = len(h)
        for n in self._ns:
            if L >= n:
                key = (n, *h[L - n:])
                old = self._latest.get(key)
                if old is not None:
                    self._prev[key] = old
                self._latest[key] = L  # continuation starts here

    def propose(self, k: int) -> list[int]:
        """Up to ``k`` draft tokens continuing the current history, or
        ``[]`` when no suffix n-gram recurred earlier."""
        h = self._h
        L = len(h)
        if k <= 0:
            return []
        for n in self._ns:
            if L < n + 1:
                continue
            key = (n, *h[L - n:])
            start = self._latest.get(key)
            if start == L:  # the suffix's own entry; use the one before
                start = self._prev.get(key)
            if start is not None and start < L:
                return h[start:start + k]
        return []


def propose_ngram_drafts(
    history: list[int],
    k: int,
    *,
    ngram: int = 3,
    min_ngram: int = 2,
    max_history: int = 4096,
) -> list[int]:
    """One-shot convenience wrapper over ``NgramIndex`` (callers with a
    live sequence keep a persistent index instead — see the scheduler)."""
    if k <= 0:
        return []
    return NgramIndex(
        history, ngram=ngram, min_ngram=min_ngram, max_history=max_history
    ).propose(k)
