"""Cluster-wide warm-state fabric (ISSUE 17; ROBUSTNESS.md §6).

The fleet's warm state — retired conversations' KV snapshots and the
shared prompt heads' KV — was per-replica until now: each replica had its
own ``SessionDiskTier`` subdirectory, each re-prefilled the system prompt
on its own device state, and route-time migration discovered a sibling's
deeper entry by scanning every replica pairwise. At the north-star scale
(millions of mostly-idle conversations over a handful of replicas) that
triples the cold-start surface for no reason: the bytes are
device-independent by construction (``export_entry`` is the wire format).

``WarmFabric`` makes warm state a FLEET resource:

- **Shared backing store**: ONE ``SessionDiskTier`` instance (one
  directory, one write-behind worker — so there are no cross-process file
  races to reason about) replaces the per-replica subdirectories. Every
  replica's session cache writes through to it and restores from it, so
  ANY replica resumes ANY conversation warm via the ordinary
  RAM-miss → disk-restore admission path, even if it never saw the
  conversation. Its durability metrics label as ``replica="fabric"``.
- **Global RAM index**: ``note``/``forget``/``holder`` track which
  replica's host-RAM cache holds each session key and how deep. The
  route-time deeper-entry-wins migration (``serve/fleet.py``) becomes an
  O(1) index lookup instead of an O(replicas) pairwise scan, and the
  source's RAM copy is dropped WITHOUT deleting the shared record the
  target just refreshed (``SessionKVCache.drop_local``).
- **Shared prompt heads**: the first replica to prefill a registered
  prefix head snapshots its pages (``engine.offload_pages``) into the
  fabric, keyed by a hash of the head's rendered bytes (the token ids ARE
  the deterministic tokenization of ``render_chat_prefix``'s output, so
  hashing their bytes keys the rendered prefix). Every later registration
  of the same head — sibling replicas at boot, respawned replicas
  re-registering after a rebuild — restores the pages with one H2D
  scatter (``engine.restore_pages``) instead of re-running the prefill.
  The system-prompt prefill is paid once per FLEET, not once per replica
  per rebuild.

Hit/miss/refusal accounting lives with the CALLERS (scheduler
``register_prefix`` / ``_restore_session_from_disk``) on their per-replica
labeled metrics views — the fabric itself is passive storage, and a
cross-mode record refused by the tier at load additionally counts on the
tier's own ``finchat_quant_dequant_fallbacks_total{replica="fabric"}``.

Head snapshots populate on the SYNC ``register_prefix`` path (startup
registration and respawn re-registration both land there after a fabric
miss); the chunked ``register_prefix_async`` path restores from the
fabric when it can but never writes it — its job machinery retires pages
incrementally and a partial snapshot would be garbage.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from finchat_tpu.engine.session_cache import SessionDiskTier
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

# disk-record key prefix for shared-head snapshots: namespaced away from
# conversation ids (which are user-derived and could otherwise collide)
_HEAD_NS = "__fabric_head__"

# RAM-cached head snapshots kept per process (LRU): heads are a handful of
# pages each and a fleet registers a handful of heads, so this is a small
# bound against pathological churn, not a real budget
_HEAD_RAM_CAP = 32


def head_key(ids: list[int]) -> str:
    """Stable fabric key for a shared prompt head: hash of the head's
    token bytes. The ids are the deterministic tokenization of the
    rendered chat prefix (``render_chat_prefix``), so equal rendered
    bytes ⇒ equal ids ⇒ equal key, across replicas and restarts."""
    raw = np.asarray(ids, np.int32).tobytes()
    return _HEAD_NS + hashlib.sha1(raw).hexdigest()


class WarmFabric:
    """One per process; every replica's scheduler holds a reference."""

    def __init__(self, path: str, budget_bytes: int, kv_quant: str = ""):
        # the ONE shared disk tier: all replicas spill to / restore from it
        self.tier = SessionDiskTier(
            path, budget_bytes,
            metrics=METRICS.labeled(replica="fabric"),
            kv_quant=kv_quant,
        )
        # session key -> (replica_id, n_tokens): which replica's host-RAM
        # cache holds the key, and how deep. Maintained by SessionKVCache
        # put/drop hooks; read by the fleet router's migration lookup.
        self._index: dict[str, tuple[str, int]] = {}
        # head key -> snapshot tuple (host page arrays, offload_pages shape)
        self._heads: OrderedDict[str, tuple] = OrderedDict()
        # replicas share one asyncio loop, but disk-writer and breaker
        # rebuild threads exist — cheap lock, never held across I/O
        self._lock = threading.Lock()

    # --- session index ---------------------------------------------------
    def note(self, key: str, replica_id: str | None, n_tokens: int) -> None:
        """Record that ``replica_id``'s RAM cache now holds ``key`` at
        ``n_tokens`` depth (last writer wins — puts replace)."""
        if replica_id is None:
            return
        with self._lock:
            self._index[key] = (replica_id, int(n_tokens))

    def forget(self, key: str, replica_id: str | None) -> None:
        """Clear ``key``'s index entry IF ``replica_id`` still holds it —
        holder-guarded so a source replica's post-migration drop cannot
        erase the target's fresher claim (the target's put noted first)."""
        with self._lock:
            cur = self._index.get(key)
            if cur is not None and cur[0] == replica_id:
                del self._index[key]

    def holder(self, key: str) -> tuple[str, int] | None:
        """(replica_id, n_tokens) of the RAM holder, or None."""
        with self._lock:
            return self._index.get(key)

    # --- shared prompt heads ---------------------------------------------
    def load_head(self, ids: list[int]) -> tuple | None:
        """The head's host KV snapshot, or None (fabric miss). RAM first;
        a disk record is verified to carry exactly these token ids (hash
        collision / truncated-record guard) before its snapshot is
        trusted. Cross-mode disk records are refused by the tier itself
        (counted there); the caller still mode-checks RAM hits."""
        key = head_key(ids)
        with self._lock:
            snap = self._heads.get(key)
            if snap is not None:
                self._heads.move_to_end(key)
                return snap
        if key not in self.tier:
            return None
        payload = self.tier.load(key)
        if payload is None or payload["snap"] is None:
            return None
        if not np.array_equal(payload["token_ids"],
                              np.asarray(ids, np.int32)):
            logger.warning("warm fabric: head record %s carries different "
                           "token ids; ignoring", key)
            return None
        snap = payload["snap"]
        with self._lock:
            self._heads[key] = snap
            while len(self._heads) > _HEAD_RAM_CAP:
                self._heads.popitem(last=False)
        return snap

    def store_head(self, ids: list[int], snap: tuple | None) -> None:
        """Publish a freshly-prefilled head's snapshot fleet-wide: RAM for
        in-process siblings, disk record (write-behind) for restarts and
        any replica whose RAM copy ages out."""
        if snap is None:
            return
        key = head_key(ids)
        with self._lock:
            self._heads[key] = snap
            self._heads.move_to_end(key)
            while len(self._heads) > _HEAD_RAM_CAP:
                self._heads.popitem(last=False)
        # prefix_len 0: the head snapshot IS the whole record (no nested
        # shared head below it); gap fields 0 — heads are never bounded
        # past the sink clamp _prefix_prep already applied to ``ids``
        self.tier.spill(key, np.asarray(ids, np.int32), 0, snap)

    # --- lifecycle -------------------------------------------------------
    def flush(self) -> None:
        self.tier.flush()

    def close(self) -> None:
        self.tier.close()
