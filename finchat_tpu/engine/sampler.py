"""Token sampling, fully inside jit (no host round-trip per token).

Per-sequence sampling params are device arrays so one decode step samples a
heterogeneous batch (different temperatures/top-p per conversation). Greedy
is temperature == 0. Default temperature 0.5 for parity with the reference's
both LLM roles (llm_agent.py:37,44).

Because ``sample`` is already device-resident, the fused multi-step decode
loop (engine/engine.py ``decode_loop_step``) calls it once per
``fori_loop`` iteration with a fresh ``jax.random.split`` of the carried
state rng — K tokens sample on-device per dispatch with the SAME
per-iteration math and rng discipline as K single ``decode_step`` calls,
which is what makes the greedy block bit-reproducible against single-step
decode (tests/test_decode_loop.py).

TPU note: a full-vocab ``argsort`` costs ~26 ms/step for [64, 32000] on
v5e (measured, benchmarks/profile_decode.py) — nearly half the decode step.
Two paths, chosen at runtime inside jit (``lax.cond``):

- NO truncating slot in the batch (every ``top_k == 0`` and ``top_p >= 1``
  — the engine default): EXACT full-vocab categorical via Gumbel-argmax,
  no sort of any kind (greedy rows get zero noise → plain argmax);
- otherwise, sampling runs over the top ``CANDIDATES`` logits via
  ``lax.top_k`` (a partial reduction XLA lowers efficiently, no full
  sort). Semantics on this path:
  - greedy (temperature <= 0): exact, full-vocab argmax;
  - top-k: exact for ``top_k <= CANDIDATES`` (clamped above it);
  - top-p: the nucleus is computed over the candidate set with
    probabilities normalized by the FULL-vocab logsumexp, so prefix mass
    is exact; the approximation is only that the nucleus cannot extend
    past the top ``CANDIDATES`` tokens (for a trained LM at temperature
    <= 1 the mass beyond the top-64 logits is negligible).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import Array

# Static candidate-set size for the top-k partial reduction. 64 keeps the
# per-step sampling cost ~1 ms at [64, 32000] while covering any realistic
# nucleus; raise it if a caller needs wider exploratory sampling.
CANDIDATES = 64


@dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling controls.

    TRUNCATION CONTRACT: when a batch contains any truncating slot
    (``top_k > 0`` or ``top_p < 1``), non-greedy sampling draws from the
    top ``CANDIDATES`` (64) logits — ``top_k = 0`` then means "no cap
    below the candidate set", and ``top_k > CANDIDATES`` is clamped (the
    scheduler warns at submission). For a trained LM at temperature ≤ 1
    the mass beyond the top-64 is negligible; the trade buys ~24 ms per
    decode step at [64, 32k] on v5e vs a full-vocab sort. When NO slot
    truncates (the engine default: top_p=1, top_k=0) sampling is an EXACT
    full-vocab categorical via Gumbel-argmax, skipping the partial sort
    entirely. Greedy (temperature 0) is always exact."""

    temperature: float = 0.5
    top_p: float = 1.0
    top_k: int = 0  # 0 = uncapped within CANDIDATES; clamped to CANDIDATES
    max_new_tokens: int = 1024
    seed: int = 0
    # named output grammar ("tool_call") for constrained decoding
    # (agent/constrained.py); None = unconstrained
    grammar: str | None = None


def sample(
    logits: Array,  # [B, vocab] fp32
    rng: Array,
    temperature: Array,  # [B]
    top_p: Array,  # [B]
    top_k: Array,  # [B] int32, 0 = disabled
    *,
    candidates: int = CANDIDATES,
) -> Array:
    """Sample next token ids [B] with per-sequence temperature/top-p/top-k.

    Runtime-branched (``lax.cond``): if no slot truncates, one full-vocab
    Gumbel-argmax (exact categorical; greedy rows get zero noise).
    Otherwise ``lax.top_k`` once (descending candidates), combined
    top-k/top-p keep-mask over the candidates, Gumbel trick, map back
    through the candidate indices — with greedy rows short-circuiting
    through a full-vocab argmax. See the module docstring for the
    truncation contract.
    """
    B, V = logits.shape
    C = min(candidates, V)
    greedy = temperature <= 0.0

    safe_temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    # Fast path — taken at runtime when NO slot truncates (top_k disabled,
    # top_p >= 1): full-vocab Gumbel-argmax is an exact categorical draw and
    # skips the lax.top_k partial sort (~1.5 ms of the 9.6 ms decode step at
    # [64, 32k] on v5e). This is the engine-default config (EngineConfig
    # top_p=1.0, top_k=0), so the bench/serving hot path stays on it; any
    # truncating slot in the batch falls back to the candidate-set path.
    def _full_categorical(_):
        gumbel = jax.random.gumbel(rng, scaled.shape, scaled.dtype)
        noise = jnp.where(greedy[:, None], 0.0, gumbel)  # greedy = pure argmax
        return jnp.argmax(scaled + noise, axis=-1).astype(jnp.int32)

    def _truncated(_):
        return _sample_truncated(
            logits, scaled, rng, greedy, top_p, top_k, C
        )

    no_truncation = jnp.all((top_k <= 0) & (top_p >= 1.0))
    return jax.lax.cond(no_truncation, _full_categorical, _truncated, None)


def _sample_truncated(
    logits: Array, scaled: Array, rng: Array, greedy: Array,
    top_p: Array, top_k: Array, C: int,
) -> Array:
    """Candidate-set sampling (the truncation-contract path)."""
    top_vals, top_idx = jax.lax.top_k(scaled, C)  # [B, C] descending

    # top-k mask in candidate space (clamped to the candidate cap)
    ranks = jnp.arange(C)[None, :]
    k_eff = jnp.where(top_k > 0, jnp.minimum(top_k, C), C)[:, None]
    keep = ranks < k_eff

    # top-p (nucleus) mask: probabilities normalized over the FULL vocab so
    # the cumulative prefix mass is exact; keep the smallest prefix whose
    # cumulative probability exceeds top_p (always keep rank 0)
    lse = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)  # [B, 1]
    probs = jnp.exp(top_vals - lse)  # [B, C]
    cumprobs = jnp.cumsum(probs, axis=-1)
    keep = keep & ((cumprobs - probs) < top_p[:, None])
    keep = keep | (ranks == 0)

    masked = jnp.where(keep, top_vals, -jnp.inf)
    gumbel = jax.random.gumbel(rng, masked.shape, masked.dtype)
    choice = jnp.argmax(masked + gumbel, axis=-1)  # [B] candidate rank
    sampled = jnp.take_along_axis(top_idx, choice[:, None], axis=-1)[:, 0]

    argmax = jnp.argmax(logits, axis=-1)
    return jnp.where(greedy, argmax, sampled).astype(jnp.int32)
