"""Token sampling, fully inside jit (no host round-trip per token).

Per-sequence sampling params are device arrays so one decode step samples a
heterogeneous batch (different temperatures/top-p per conversation). Greedy
is temperature == 0. Default temperature 0.5 for parity with the reference's
both LLM roles (llm_agent.py:37,44).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax import Array


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.5
    top_p: float = 1.0
    top_k: int = 0  # 0 = disabled
    max_new_tokens: int = 1024
    seed: int = 0
    # named output grammar ("tool_call") for constrained decoding
    # (agent/constrained.py); None = unconstrained
    grammar: str | None = None


def sample(
    logits: Array,  # [B, vocab] fp32
    rng: Array,
    temperature: Array,  # [B]
    top_p: Array,  # [B]
    top_k: Array,  # [B] int32, 0 = disabled
) -> Array:
    """Sample next token ids [B] with per-sequence temperature/top-p/top-k.

    Implementation: sort once descending, build the combined top-k/top-p
    keep-mask in sorted order, renormalize, sample via Gumbel trick, undo the
    sort. Greedy (temperature <= 0) short-circuits through the same path.
    """
    B, V = logits.shape
    greedy = temperature <= 0.0

    safe_temp = jnp.where(greedy, 1.0, temperature)
    scaled = logits / safe_temp[:, None]

    sort_idx = jnp.argsort(-scaled, axis=-1)  # descending
    sorted_logits = jnp.take_along_axis(scaled, sort_idx, axis=-1)

    # top-k mask in sorted space
    ranks = jnp.arange(V)[None, :]
    k_eff = jnp.where(top_k > 0, top_k, V)[:, None]
    keep = ranks < k_eff

    # top-p (nucleus) mask in sorted space: keep the smallest prefix whose
    # cumulative probability exceeds top_p (always keep rank 0)
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cumprobs = jnp.cumsum(probs, axis=-1)
    keep = keep & ((cumprobs - probs) < top_p[:, None])
    keep = keep | (ranks == 0)

    masked = jnp.where(keep, sorted_logits, -jnp.inf)
    gumbel = jax.random.gumbel(rng, masked.shape, masked.dtype)
    choice_sorted = jnp.argmax(masked + gumbel, axis=-1)  # [B]
    sampled = jnp.take_along_axis(sort_idx, choice_sorted[:, None], axis=-1)[:, 0]

    argmax = jnp.argmax(logits, axis=-1)
    return jnp.where(greedy, argmax, sampled).astype(jnp.int32)
