"""Training step: next-token CE + AdamW, sharded over the full mesh.

The reference is inference-only (no model in-process at all); training is
new framework surface so fine-tuning the served model (e.g. domain-adapting
Penny on transaction dialogue) needs no second framework. Sharding story:

- DP over ``data`` (batch), TP over ``model`` (via llama_param_shardings),
  SP over ``seq`` (ring attention for the sequence dimension).
- All expressed as GSPMD constraints on params + batch; XLA inserts the
  gradient all-reduces and TP collectives.
- ``jax.checkpoint`` (remat) around each scanned layer trades FLOPs for
  HBM on long sequences.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from finchat_tpu.models.llama import LlamaConfig, forward, make_causal_attention
from finchat_tpu.ops.ring_attention import ring_attention
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@jax.tree_util.register_dataclass
@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def _ring_attention_callback(mesh: Mesh) -> Callable:
    """Model attention callback using sequence-parallel ring attention."""

    def attention(q, k, v, layer_cache, layer_idx):
        out = ring_attention(q, k, v, mesh=mesh, axis="seq", batch_axis="data", head_axis="model", causal=True)
        return out, layer_cache

    return attention


def _ulysses_attention_callback(mesh: Mesh) -> Callable:
    """SP via Ulysses head scatter (ops/ulysses.py) — two all-to-alls per
    layer instead of a ring; needs heads divisible by the seq axis.
    Composition note: heads here are the LOCAL (TP-sharded) head count, so
    the divisibility requirement applies after the model axis split."""
    from finchat_tpu.ops.ulysses import ulysses_attention

    def attention(q, k, v, layer_cache, layer_idx):
        out = ulysses_attention(
            q, k, v, mesh=mesh, axis="seq", batch_axis="data",
            head_axis="model", causal=True,
        )
        return out, layer_cache

    return attention


def make_optimizer(learning_rate: float = 1e-4, weight_decay: float = 0.01) -> optax.GradientTransformation:
    return optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay)


def init_train_state(config: LlamaConfig, params: Any, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))


def make_train_step(
    config: LlamaConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh | None = None,
    *,
    use_ring_attention: bool = False,
    sp_mode: str = "ring",  # "ring" | "ulysses" (when use_ring_attention)
    remat: bool = True,
):
    """Build the jitted train step.

    ``batch``: token ids [B, S] (B sharded on ``data``, S on ``seq`` when
    SP is on). Loss is next-token CE over positions 0..S-2. ``sp_mode``
    picks the sequence-parallel attention: ``ring`` (K/V rotate the ICI
    ring; any head count, S beyond one chip) or ``ulysses`` (two
    all-to-alls; needs per-TP-shard heads divisible by the seq axis).
    """
    if use_ring_attention:
        assert mesh is not None, "sequence parallelism needs a mesh"
        if sp_mode not in ("ring", "ulysses"):
            raise ValueError(f"unknown sp_mode {sp_mode!r} (want 'ring' or 'ulysses')")
        if sp_mode == "ulysses":
            attention = _ulysses_attention_callback(mesh)
        else:
            attention = _ring_attention_callback(mesh)
    else:
        # resolve the backend NOW (build time), not at trace time — the jit
        # cache below is not keyed on env state (see ops/dispatch.py)
        from finchat_tpu.ops.dispatch import attention_backend

        attention = make_causal_attention(attention_backend())

    def loss_fn(params, tokens):
        B, S = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))

        logits, _ = forward(
            params, tokens, positions,
            config=config, attention=attention, cache=None, remat=remat,
        )
        # predict token t+1 from position t
        targets = tokens[:, 1:]
        pred = logits[:, :-1, :]
        ce = optax.softmax_cross_entropy_with_integer_labels(pred, targets)
        return ce.mean()

    # donate the state so params + opt_state (~3x model size) update in
    # place instead of double-buffering every step
    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, tokens: jax.Array) -> tuple[TrainState, jax.Array]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params=params, opt_state=opt_state, step=state.step + 1), loss

    return train_step


def shard_batch(tokens: jax.Array, mesh: Mesh, *, seq_sharded: bool) -> jax.Array:
    spec = P("data", "seq") if seq_sharded else P("data")
    return jax.device_put(tokens, NamedSharding(mesh, spec))
