"""finchat-lint framework: project index, rule registry, suppressions,
baseline.

The framework is deliberately self-contained (stdlib ``ast`` + ``tokenize``
only — no third-party lint deps, per the image constraint) and builds ONE
shared :class:`ProjectIndex` that every rule visitor reads:

- modules → classes → functions (nested defs included, qualnames like
  ``engine.scheduler.Scheduler._trip_breaker``),
- per-module import maps (so ``sleep(...)`` after ``from time import
  sleep`` still resolves to ``time.sleep``),
- per-class attribute types inferred from ``self.x = ClassName(...)``
  assignments and annotated ``__init__`` params (so ``self.engine.foo()``
  resolves into ``InferenceEngine.foo``),
- per-function call sites with off-loop boundaries already marked
  (``asyncio.to_thread`` / ``run_in_executor`` / executor ``submit`` /
  ``threading.Thread`` — a lambda handed to one of those runs OFF the
  loop, while its sibling arguments still evaluate ON it),
- loop-callback registrations (``add_done_callback`` / ``call_soon`` /
  ...), which R1 treats as roots alongside ``async def`` bodies.

Suppressions: ``# finchat-lint: disable=<rule>[,<rule>] -- <why>`` on the
finding's line, or on a ``def``/``class`` line to cover that whole scope.
The ``-- why`` justification is mandatory; a bare disable is itself
reported by the ``suppression-discipline`` meta rule. ``# finchat-lint:
hot`` on a ``def`` line opts a function into R2's hot set.

Baseline: ``LINT_BASELINE.json`` maps finding fingerprints (stable across
line drift — no line numbers inside) to their descriptions. The gate is
one-directional: a finding not in the baseline fails the run; a baseline
entry with no matching finding is stale and only ``--update-baseline``
removes it. The file may only shrink.
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

# ---------------------------------------------------------------------------
# findings
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(
    r"finchat-lint:\s*(?P<kind>disable|hot)"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_,\- ]+?))?"
    r"\s*(?:--\s*(?P<why>.+?))?\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation. ``message`` must be stable (no line numbers, no
    absolute paths) — the baseline fingerprints it."""

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str  # enclosing qualname ("" for module-level findings)
    message: str

    def fingerprint(self) -> str:
        raw = f"{self.rule}|{self.path}|{self.symbol}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{where}: {self.rule}{sym}: {self.message}"


@dataclass
class Suppression:
    line: int
    rules: tuple[str, ...]
    justified: bool
    used: bool = False


# ---------------------------------------------------------------------------
# project model
# ---------------------------------------------------------------------------

_OFF_LOOP_WRAPPERS = (
    "to_thread",
    "run_in_executor",
    "submit",
    "Thread",
    "run_coroutine_threadsafe",
)

_CALLBACK_REGISTRARS = (
    "add_done_callback",
    "call_soon",
    "call_soon_threadsafe",
    "call_later",
    "call_at",
)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass(eq=False)
class CallSite:
    node: ast.Call
    dotted: str | None  # unresolved dotted form ("self.engine.reset_slot")
    off_loop_wrapper: bool  # the call IS to_thread/submit/... itself


@dataclass(eq=False)
class FunctionInfo:
    qualname: str  # module-relative: "Scheduler._trip_breaker"
    module: "ModuleInfo"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    is_async: bool
    cls: "ClassInfo | None"
    calls: list[CallSite] = field(default_factory=list)
    # function refs registered as loop callbacks inside this function
    registered_callbacks: list[str] = field(default_factory=list)
    # local name -> class simple name (from ``x = ClassName(...)`` and
    # annotated params)
    local_types: dict[str, str] = field(default_factory=dict)
    is_loop_callback: bool = False  # set by index linking

    @property
    def name(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def full_qualname(self) -> str:
        return f"{self.module.modname}.{self.qualname}"


@dataclass(eq=False)
class ClassInfo:
    name: str
    qualname: str
    module: "ModuleInfo"
    node: ast.ClassDef
    bases: list[str] = field(default_factory=list)
    methods: dict[str, FunctionInfo] = field(default_factory=dict)
    attr_types: dict[str, str] = field(default_factory=dict)  # self.x -> cls


@dataclass(eq=False)
class ModuleInfo:
    path: Path
    relpath: str
    modname: str  # "finchat_tpu.engine.scheduler"
    tree: ast.Module
    source: str
    imports: dict[str, str] = field(default_factory=dict)  # alias -> dotted
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    classes: dict[str, ClassInfo] = field(default_factory=dict)
    suppressions: list[Suppression] = field(default_factory=list)
    hot_marks: set[int] = field(default_factory=set)  # def lines marked hot
    # (lineno, end_lineno, def_lineno) for every class/function scope
    scopes: list[tuple[int, int, int]] = field(default_factory=list)


def _annotation_class(node: ast.AST | None) -> str | None:
    """Best-effort simple class name out of an annotation: ``Foo``,
    ``"Foo"``, ``Foo | None``, ``Optional[Foo]``."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value.replace("Optional[", "").rstrip("]")
        name = name.split("|")[0].strip()
        return name.rsplit(".", 1)[-1] if name.isidentifier() or "." in name else None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        for side in (node.left, node.right):
            got = _annotation_class(side)
            if got and got != "None":
                return got
        return None
    if isinstance(node, ast.Subscript):  # Optional[Foo], list[Foo] -> Foo-ish
        base = _annotation_class(node.value)
        if base == "Optional":
            return _annotation_class(node.slice)
        return None
    return None


class _FunctionBodyVisitor(ast.NodeVisitor):
    """Collect call sites / callback registrations / local types for ONE
    function, without descending into nested defs (indexed separately).
    Lambdas passed to off-loop wrappers are skipped entirely (their bodies
    run on a worker thread); all other arguments of those wrappers still
    evaluate on the calling thread and are visited."""

    def __init__(self, info: FunctionInfo):
        self.info = info
        self._depth = 0

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if self._depth == 0:
            self._depth += 1
            # annotated params are typed locals
            args = node.args
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                cls = _annotation_class(a.annotation)
                if cls:
                    self.info.local_types[a.arg] = cls
            self.generic_visit(node)
            self._depth -= 1
        # nested def: do not descend (its body belongs to the nested fn)

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    def visit_Assign(self, node: ast.Assign) -> None:
        if (
            isinstance(node.value, ast.Call)
            and isinstance(node.value.func, ast.Name)
            and node.value.func.id[:1].isupper()
        ):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.info.local_types[tgt.id] = node.value.func.id
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        dotted = dotted_name(node.func)
        tail = dotted.rsplit(".", 1)[-1] if dotted else (
            node.func.attr if isinstance(node.func, ast.Attribute) else None
        )
        off_loop = tail in _OFF_LOOP_WRAPPERS
        self.info.calls.append(CallSite(node, dotted, off_loop))
        if tail in _CALLBACK_REGISTRARS:
            for arg in node.args:
                ref = dotted_name(arg)
                if ref:
                    self.info.registered_callbacks.append(ref)
        # visit children; for off-loop wrappers skip Lambda args only
        self.visit(node.func) if not isinstance(node.func, ast.Name) else None
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if off_loop and isinstance(arg, ast.Lambda):
                continue
            self.visit(arg)


class ProjectIndex:
    """All analyzed modules plus cross-module resolution helpers."""

    def __init__(self, root: Path):
        self.root = root
        self.modules: dict[str, ModuleInfo] = {}  # relpath -> info
        self._classes_by_name: dict[str, list[ClassInfo]] = {}

    # -- construction ------------------------------------------------------
    @classmethod
    def build(cls, root: Path, paths: list[Path]) -> "ProjectIndex":
        index = cls(root)
        for p in _collect_py_files(paths):
            index._add_file(p)
        index._link()
        return index

    def _add_file(self, path: Path) -> None:
        try:
            source = path.read_text()
            tree = ast.parse(source)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            # an unparseable file is itself reported (rule "parse-error")
            # by run_analysis; record a stub so the finding has a home
            rel = self._rel(path)
            mod = ModuleInfo(path, rel, _modname(rel), ast.Module(body=[], type_ignores=[]), "")
            mod.suppressions = []
            self.modules[rel] = mod
            mod.parse_error = str(e)  # type: ignore[attr-defined]
            return
        rel = self._rel(path)
        mod = ModuleInfo(path, rel, _modname(rel), tree, source)
        self._scan_comments(mod)
        self._scan_imports(mod)
        self._scan_defs(mod)
        self.modules[rel] = mod

    def _rel(self, path: Path) -> str:
        try:
            return path.resolve().relative_to(self.root.resolve()).as_posix()
        except ValueError:
            return path.as_posix()

    def _scan_comments(self, mod: ModuleInfo) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(mod.source).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT or "finchat-lint" not in tok.string:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if not m:
                    continue
                if m.group("kind") == "hot":
                    mod.hot_marks.add(tok.start[0])
                    continue
                rules = tuple(
                    r.strip() for r in (m.group("rules") or "").split(",") if r.strip()
                )
                mod.suppressions.append(
                    Suppression(tok.start[0], rules, bool(m.group("why")))
                )
        except tokenize.TokenError:
            pass

    def _scan_imports(self, mod: ModuleInfo) -> None:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        mod.imports[alias.asname] = alias.name
                    else:
                        # `import a.b` binds the name `a` (to package a),
                        # NOT `a.b` — mapping 'a' -> 'a.b' would resolve
                        # `a.x(...)` as 'a.b.x' and silently miss e.g.
                        # os.fsync under `import os.path`
                        head = alias.name.split(".")[0]
                        mod.imports[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    mod.imports[alias.asname or alias.name] = f"{node.module}.{alias.name}"

    def _scan_defs(self, mod: ModuleInfo) -> None:
        def add_function(node, prefix: str, cls: ClassInfo | None) -> None:
            qual = f"{prefix}{node.name}"
            info = FunctionInfo(
                qualname=qual,
                module=mod,
                node=node,
                is_async=isinstance(node, ast.AsyncFunctionDef),
                cls=cls,
            )
            _FunctionBodyVisitor(info).visit(node)
            mod.functions[qual] = info
            mod.scopes.append((node.lineno, node.end_lineno or node.lineno, node.lineno))
            if cls is not None and "." not in qual[len(cls.qualname) + 1 :]:
                cls.methods[node.name] = info
            # nested defs
            for child in ast.walk(node):
                if child is node:
                    continue
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if _innermost_parent(node, child) is node:
                        add_function(child, f"{qual}.", cls)

        def add_class(node: ast.ClassDef, prefix: str) -> None:
            cls = ClassInfo(
                name=node.name,
                qualname=f"{prefix}{node.name}",
                module=mod,
                node=node,
                bases=[b for b in (dotted_name(x) for x in node.bases) if b],
            )
            mod.classes[cls.qualname] = cls
            mod.scopes.append((node.lineno, node.end_lineno or node.lineno, node.lineno))
            self._classes_by_name.setdefault(node.name, []).append(cls)
            for child in node.body:
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    add_function(child, f"{cls.qualname}.", cls)
                elif isinstance(child, ast.ClassDef):
                    add_class(child, f"{cls.qualname}.")

        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                add_function(node, "", None)
            elif isinstance(node, ast.ClassDef):
                add_class(node, "")

    def _link(self) -> None:
        """Second pass: infer class attr types and mark loop callbacks."""
        for mod in self.modules.values():
            for cls in mod.classes.values():
                self._infer_attr_types(cls)
        # registered callbacks become loop roots
        for mod in self.modules.values():
            for fn in mod.functions.values():
                for ref in fn.registered_callbacks:
                    target = self._resolve_callable_ref(ref, fn)
                    if target is not None:
                        target.is_loop_callback = True

    def _infer_attr_types(self, cls: ClassInfo) -> None:
        for fn in cls.methods.values():
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Assign):
                    value_cls = self._value_class(node.value, fn)
                    if not value_cls:
                        continue
                    for tgt in node.targets:
                        if (
                            isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                        ):
                            cls.attr_types.setdefault(tgt.attr, value_cls)
                elif isinstance(node, ast.AnnAssign):
                    tgt = node.target
                    if (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        got = _annotation_class(node.annotation)
                        if got:
                            cls.attr_types.setdefault(tgt.attr, got)

    def _value_class(self, value: ast.AST, fn: FunctionInfo) -> str | None:
        """Best-effort class of an assigned expression: a constructor
        call, a return-annotated factory call, a typed name, or the first
        typeable operand of an ``x or default()`` fallback chain."""
        if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
            if value.func.id[:1].isupper():
                return value.func.id
            return self._factory_return(value.func.id, fn.module)
        if isinstance(value, ast.Name):
            return fn.local_types.get(value.id)
        if isinstance(value, ast.BoolOp):
            for operand in value.values:
                got = self._value_class(operand, fn)
                if got:
                    return got
        if isinstance(value, ast.IfExp):
            return self._value_class(value.body, fn) or self._value_class(
                value.orelse, fn
            )
        return None

    def _factory_return(self, name: str, mod: ModuleInfo) -> str | None:
        """Return-annotation class of a module-level function called by
        bare name (same module or imported)."""
        fn = mod.functions.get(name)
        if fn is None:
            imp = mod.imports.get(name)
            hits = self._by_dotted(imp) if imp else []
            fn = hits[0] if hits else None
        if fn is None:
            return None
        return _annotation_class(getattr(fn.node, "returns", None))

    # -- resolution --------------------------------------------------------
    def class_by_name(self, name: str) -> ClassInfo | None:
        hits = self._classes_by_name.get(name) or []
        return hits[0] if len(hits) == 1 else None

    def _method_of(self, cls: ClassInfo, name: str) -> FunctionInfo | None:
        seen: set[str] = set()
        stack = [cls]
        while stack:
            c = stack.pop()
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            for base in c.bases:
                bc = self.class_by_name(base.rsplit(".", 1)[-1])
                if bc is not None:
                    stack.append(bc)
        return None

    def _resolve_callable_ref(self, ref: str, ctx: FunctionInfo) -> FunctionInfo | None:
        """Resolve a bare callable REFERENCE (not a call): ``_done``,
        ``self._on_tick`` — used for loop-callback registration."""
        parts = ref.split(".")
        if len(parts) == 1:
            # nested function of the current function chain, else module fn
            probe = ctx.qualname
            while probe:
                cand = ctx.module.functions.get(f"{probe}.{parts[0]}")
                if cand is not None:
                    return cand
                probe = probe.rsplit(".", 1)[0] if "." in probe else ""
            return ctx.module.functions.get(parts[0])
        if parts[0] == "self" and len(parts) == 2 and ctx.cls is not None:
            return self._method_of(ctx.cls, parts[1])
        return None

    def resolve_call(self, site: CallSite, ctx: FunctionInfo) -> list[FunctionInfo]:
        """Package-internal callee candidates for a call site (possibly
        empty). External calls resolve to [] — use ``external_target`` for
        the dotted stdlib form."""
        dotted = site.dotted
        if not dotted:
            return []
        parts = dotted.split(".")
        mod = ctx.module
        if len(parts) == 1:
            name = parts[0]
            # nested function of this function (or an enclosing one)
            got = self._resolve_callable_ref(name, ctx)
            if got is not None:
                return [got]
            # imported function from a package module
            imp = mod.imports.get(name)
            if imp:
                return self._by_dotted(imp)
            # class constructor
            cls = mod.classes.get(name) or self.class_by_name(name)
            if cls is not None:
                init = self._method_of(cls, "__init__")
                return [init] if init else []
            return []
        if parts[0] == "self" and ctx.cls is not None:
            if len(parts) == 2:
                got = self._method_of(ctx.cls, parts[1])
                return [got] if got else []
            if len(parts) == 3:
                attr_cls = ctx.cls.attr_types.get(parts[1])
                if attr_cls:
                    cls = self.class_by_name(attr_cls)
                    if cls is not None:
                        got = self._method_of(cls, parts[2])
                        return [got] if got else []
            return []
        if len(parts) == 2:
            root, meth = parts
            # typed local / annotated param
            local_cls = ctx.local_types.get(root)
            if local_cls:
                cls = self.class_by_name(local_cls)
                if cls is not None:
                    got = self._method_of(cls, meth)
                    return [got] if got else []
            # imported module or name
            imp = mod.imports.get(root)
            if imp:
                return self._by_dotted(f"{imp}.{meth}")
            # class method via class name
            cls = mod.classes.get(root) or self.class_by_name(root)
            if cls is not None:
                got = self._method_of(cls, meth)
                return [got] if got else []
        return []

    def _by_dotted(self, dotted: str) -> list[FunctionInfo]:
        """``finchat_tpu.engine.scheduler.Scheduler.submit`` (or any
        suffix-qualified package function) -> FunctionInfo."""
        for mod in self.modules.values():
            if dotted.startswith(mod.modname + "."):
                qual = dotted[len(mod.modname) + 1 :]
                if qual in mod.functions:
                    return [mod.functions[qual]]
                # ClassName alone: constructor
                if qual in mod.classes:
                    init = self._method_of(mod.classes[qual], "__init__")
                    return [init] if init else []
        return []

    def external_target(self, site: CallSite, ctx: FunctionInfo) -> str | None:
        """The import-resolved dotted name (``time.sleep``) when the call
        does NOT resolve inside the package."""
        dotted = site.dotted
        if not dotted:
            return None
        parts = dotted.split(".")
        imp = ctx.module.imports.get(parts[0])
        if imp:
            return ".".join([imp] + parts[1:])
        return dotted

    def all_functions(self):
        for mod in self.modules.values():
            yield from mod.functions.values()

    # -- suppressions ------------------------------------------------------
    def suppression_for(self, finding: Finding) -> Suppression | None:
        mod = self.modules.get(finding.path)
        if mod is None:
            return None
        candidates: list[tuple[int, Suppression]] = []
        for sup in mod.suppressions:
            if sup.rules and finding.rule not in sup.rules:
                continue
            if sup.line == finding.line:
                candidates.append((0, sup))
                continue
            # scope suppression: comment sits on a def/class line whose
            # scope contains the finding
            for lo, hi, def_line in mod.scopes:
                if sup.line == def_line and lo <= finding.line <= hi:
                    candidates.append((hi - lo, sup))
                    break
        if not candidates:
            return None
        candidates.sort(key=lambda c: c[0])  # innermost scope wins
        sup = candidates[0][1]
        sup.used = True
        return sup


def _innermost_parent(root: ast.AST, target: ast.AST) -> ast.AST | None:
    """The innermost def/class between ``root`` and ``target`` (``root``
    itself when the def is directly nested)."""
    parent = root
    found = root

    def walk(node: ast.AST, scope: ast.AST) -> None:
        nonlocal found
        for child in ast.iter_child_nodes(node):
            if child is target:
                found = scope
                return
            next_scope = (
                child
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
                else scope
            )
            walk(child, next_scope)

    walk(parent, parent)
    return found


def _modname(relpath: str) -> str:
    parts = Path(relpath).with_suffix("").parts
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _collect_py_files(paths: list[Path]) -> list[Path]:
    out: list[Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(
                f
                for f in sorted(p.rglob("*.py"))
                if "__pycache__" not in f.parts and ".git" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


class Rule:
    """Base class: subclasses set ``name``/``code``/``description`` and
    implement ``run``."""

    name = "abstract"
    code = "R0"
    description = ""

    def run(self, project: ProjectIndex) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


def default_rules() -> list[Rule]:
    # imported here to avoid import cycles (rule modules import core)
    from finchat_tpu.analysis.rules_blocking import EventLoopBlockingRule
    from finchat_tpu.analysis.rules_config import KnobConsistencyRule
    from finchat_tpu.analysis.rules_hotpath import HotPathHostSyncRule
    from finchat_tpu.analysis.rules_metrics import MetricsDisciplineRule
    from finchat_tpu.analysis.rules_resources import ResourcePairingRule

    return [
        EventLoopBlockingRule(),
        HotPathHostSyncRule(),
        ResourcePairingRule(),
        KnobConsistencyRule(),
        MetricsDisciplineRule(),
    ]


@dataclass
class AnalysisResult:
    findings: list[Finding]  # unsuppressed
    suppressed: list[tuple[Finding, Suppression]]
    meta_findings: list[Finding]  # suppression-discipline, parse errors
    unused_suppressions: list[tuple[str, int]]  # (path, line)


def run_analysis(
    root: Path,
    paths: list[Path],
    rules: list[Rule] | None = None,
    rule_filter: set[str] | None = None,
) -> AnalysisResult:
    project = ProjectIndex.build(root, paths)
    rules = rules if rules is not None else default_rules()
    if rule_filter:
        rules = [r for r in rules if r.name in rule_filter or r.code in rule_filter]

    findings: list[Finding] = []
    suppressed: list[tuple[Finding, Suppression]] = []
    meta: list[Finding] = []

    for mod in project.modules.values():
        err = getattr(mod, "parse_error", None)
        if err:
            meta.append(Finding("parse-error", mod.relpath, 1, "", f"cannot parse: {err}"))

    for rule in rules:
        for finding in rule.run(project):
            sup = project.suppression_for(finding)
            if sup is not None:
                suppressed.append((finding, sup))
            else:
                findings.append(finding)

    # suppression discipline: every disable needs a justification; unused
    # disables are surfaced so dead suppressions don't hide future drift
    unused: list[tuple[str, int]] = []
    for mod in project.modules.values():
        for sup in mod.suppressions:
            if not sup.justified:
                meta.append(
                    Finding(
                        "suppression-discipline",
                        mod.relpath,
                        sup.line,
                        "",
                        "suppression lacks a justification "
                        "(write `# finchat-lint: disable=<rule> -- why`)",
                    )
                )
            if not sup.used:
                unused.append((mod.relpath, sup.line))

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return AnalysisResult(findings, suppressed, meta, unused)


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> dict[str, dict]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text())
    return data.get("findings", {})


def write_baseline(path: Path, findings: list[Finding]) -> None:
    payload = {
        "version": 1,
        "comment": (
            "finchat-lint baseline: pre-existing findings tolerated by CI. "
            "This file may only SHRINK — fix or inline-suppress (with "
            "justification) instead of adding entries. Regenerate with "
            "`python -m finchat_tpu.analysis --update-baseline` after "
            "removing a finding."
        ),
        "findings": {
            f.fingerprint(): {
                "rule": f.rule,
                "path": f.path,
                "symbol": f.symbol,
                "message": f.message,
            }
            for f in findings
        },
    }
    path.write_text(json.dumps(payload, indent=2, sort_keys=True) + "\n")
