"""Runtime sanitizers: the dynamic teeth behind finchat-lint R1 and R3.

Static rules catch the *shape* of a bug; these catch the *behavior*, wired
into the scheduler/fleet/durability test suites by ``tests/conftest.py``:

- :class:`StallSanitizer` — an instrumented event loop (asyncio debug mode
  + ``slow_callback_duration``) that records every loop callback exceeding
  a threshold. A test that blocks the loop — an inline device rebuild, a
  serialize+fsync spill, a synchronous D2H fetch — fails with the exact
  callback and duration, instead of silently stretching every sibling
  stream's inter-token gap the way the pre-PR-8 ``_trip_breaker`` rebuild
  did. Threshold via ``FINCHAT_STALL_THRESHOLD_S`` (default 1.0 s — the
  historical bug class was *seconds*; CPU-test jit compiles stay under
  it), allowlist regexes via ``FINCHAT_STALL_ALLOW`` (comma-separated).

- :func:`scheduler_leak_report` — invariant audit of a STOPPED scheduler:
  every allocator page is owned by a live shared-prefix entry (nothing
  else may hold pages after stop), every engine slot is back on the free
  list, every ``_PrefixEntry.refs`` equals the number of session-cache
  entries referencing it, no in-flight prefix jobs, and the session disk
  tier's write-behind queue is quiescent. One autouse fixture replaces
  the bespoke per-bug regression assertions PRs 5-7 kept hand-writing
  (``_fail_prefix_job`` slot leak, cancel-delegation page leak, drain
  zero-leak checks).

- :func:`track` / :func:`tracked_instances` — lightweight construction
  tracking (conftest patches ``__init__``) so the fixture can find every
  scheduler/journal a test created without threading them through
  fixtures.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import re

_DEFAULT_THRESHOLD_S = 1.0


class StallSanitizer:
    """Fail-on-slow-callback instrumentation for one event loop.

    Uses asyncio's own debug machinery: ``loop.set_debug(True)`` +
    ``slow_callback_duration`` makes the loop emit ``Executing <handle>
    took <dt> seconds`` warnings on the ``asyncio`` logger; a capturing
    handler turns those into hard test failures. That keeps the timing
    measurement in the loop itself (no monkeypatching of private
    ``Handle`` internals) and inherits asyncio's coverage: callbacks,
    task steps, and ``call_soon`` handles all route through it.
    """

    def __init__(self, threshold_s: float | None = None,
                 allow: tuple[str, ...] = ()):
        if threshold_s is None:
            threshold_s = float(
                os.environ.get("FINCHAT_STALL_THRESHOLD_S", _DEFAULT_THRESHOLD_S)
            )
        env_allow = tuple(
            p for p in os.environ.get("FINCHAT_STALL_ALLOW", "").split(",") if p
        )
        self.threshold_s = threshold_s
        self.allow = tuple(allow) + env_allow
        self.stalls: list[str] = []
        self._handler: logging.Handler | None = None

    @classmethod
    def from_env(cls) -> "StallSanitizer":
        return cls()

    def install(self, loop: asyncio.AbstractEventLoop) -> None:
        loop.set_debug(True)
        loop.slow_callback_duration = self.threshold_s
        sanitizer = self

        class _Capture(logging.Handler):
            def emit(self, record: logging.LogRecord) -> None:
                msg = record.getMessage()
                if msg.startswith("Executing"):
                    sanitizer.stalls.append(msg)

        self._handler = _Capture(level=logging.WARNING)
        logging.getLogger("asyncio").addHandler(self._handler)

    def uninstall(self) -> None:
        if self._handler is not None:
            logging.getLogger("asyncio").removeHandler(self._handler)
            self._handler = None

    def violations(self) -> list[str]:
        """Stalls not matching the allowlist."""
        return [
            s for s in self.stalls
            if not any(re.search(p, s) for p in self.allow)
        ]

    def run(self, coro) -> object:
        """``asyncio.run`` with the sanitizer installed; raises
        ``RuntimeError`` listing violations after the coroutine finishes
        (the test body ran to completion — the failure is the stall)."""
        loop = asyncio.new_event_loop()
        self.install(loop)
        try:
            asyncio.set_event_loop(loop)
            result = loop.run_until_complete(coro)
        finally:
            # mirror asyncio.run's teardown: cancel what the test left
            # pending (running its finally/cleanup — a failing test that
            # never reached sched.stop() must not strand the scheduler
            # loop task, which would both bleed threads into later tests
            # and leave _running=True so the leak fixture skips auditing
            # exactly the scheduler that leaked), then drain asyncgens
            try:
                _cancel_pending_tasks(loop)
                loop.run_until_complete(loop.shutdown_asyncgens())
            finally:
                self.uninstall()
                asyncio.set_event_loop(None)
                loop.close()
        bad = self.violations()
        if bad:
            raise RuntimeError(
                "event-loop stall sanitizer: %d callback(s) blocked the "
                "loop past %.2fs (finchat-lint R1 class):\n  %s"
                % (len(bad), self.threshold_s, "\n  ".join(bad))
            )
        return result


def _cancel_pending_tasks(loop: asyncio.AbstractEventLoop) -> None:
    """asyncio.runners._cancel_all_tasks, minimally: cancel every pending
    task and let each run its cleanup to completion."""
    tasks = [t for t in asyncio.all_tasks(loop) if not t.done()]
    if not tasks:
        return
    for t in tasks:
        t.cancel()
    loop.run_until_complete(asyncio.gather(*tasks, return_exceptions=True))
    for t in tasks:
        if t.cancelled():
            continue
        if t.exception() is not None:
            loop.call_exception_handler({
                "message": "unhandled exception during sanitizer loop shutdown",
                "exception": t.exception(),
                "task": t,
            })


# ---------------------------------------------------------------------------
# leak sanitizer
# ---------------------------------------------------------------------------

# STRONG references, cleared by the fixture's clear_tracked() at teardown:
# a scheduler created as a test-body local is unreferenced the moment the
# coroutine returns, and a weak set would let GC drop exactly the leaked
# instance before the audit runs (nondeterministic coverage). The strong
# ref lives only from construction to the end of the owning test.
_TRACKED: dict[str, list] = {}


def track(kind: str, obj: object) -> None:
    _TRACKED.setdefault(kind, []).append(obj)


def tracked_instances(kind: str) -> list[object]:
    return list(_TRACKED.get(kind, ()))


def clear_tracked() -> None:
    _TRACKED.clear()


@contextlib.contextmanager
def track_constructions(cls: type, kind: str):
    """Patch ``cls.__init__`` so every construction during the context is
    recorded under ``kind`` (strongly, until ``clear_tracked``)."""
    orig = cls.__init__

    def wrapped(self, *args, **kwargs):
        orig(self, *args, **kwargs)
        track(kind, self)

    cls.__init__ = wrapped
    try:
        yield
    finally:
        cls.__init__ = orig


def scheduler_leak_report(sched) -> list[str]:
    """Invariant audit of one scheduler. Empty list = clean.

    Only meaningful for a STOPPED (or never-started) scheduler — live
    streams legitimately hold slots and pages; callers skip running ones.
    """
    problems: list[str] = []
    try:
        allocator = sched.allocator
        engine = sched.engine
    except AttributeError:
        return problems  # not a real scheduler (test double)

    # A plain stop() deliberately leaves live streams in place (only
    # shutdown_drain preempts them), and unit tests drive _admit on
    # never-started schedulers — so live handles and in-flight prefix
    # jobs are ACCOUNTED owners, not leaks. A leak is a resource owned
    # by NOTHING: a page whose owner died, a slot on neither the free
    # list nor a live handle/job, a refcount with no referent.
    live_prefix_owners = {e.owner for e in sched._prefixes}
    live_handles = list(sched.decoding.values()) + list(sched.prefilling)
    handle_owners = {h.seq_id for h in live_handles}
    job_owners = {j.owner for j in sched._prefix_jobs}

    owners = getattr(allocator, "_owner", {})
    stray = {
        owner
        for owner in owners.values()
        if owner not in live_prefix_owners
        and owner not in handle_owners
        and owner not in job_owners
    }
    if stray:
        pages = [p for p, o in owners.items() if o in stray]
        problems.append(
            f"{len(pages)} KV page(s) leaked by dead owner(s) {sorted(stray)}"
        )

    # every slot is on the free list or held by a live handle/prefix job
    max_seqs = engine.engine_cfg.max_seqs
    free = len(sched.free_slots)
    handle_slots = {h.slot for h in live_handles if h.slot >= 0}
    in_use = len(handle_slots) + len(sched._prefix_jobs)
    if free + in_use != max_seqs:
        problems.append(
            f"slot accounting broken: {free} free + {in_use} in use "
            f"(live handles/jobs) != max_seqs {max_seqs}"
        )
    if len(set(sched.free_slots)) != free:
        problems.append("duplicate slots on the free list")

    # prefix-head refcounts == session entries referencing them (live
    # handles already reported; a stopped scheduler has none)
    session_refs: dict[int, int] = {}
    cache = sched.session_cache
    if cache is not None:
        for entry in getattr(cache, "_entries", {}).values():
            if entry.prefix_entry is not None:
                session_refs[id(entry.prefix_entry)] = (
                    session_refs.get(id(entry.prefix_entry), 0) + 1
                )
    for e in sched._prefixes:
        expected = session_refs.get(id(e), 0) + sum(
            1 for h in live_handles if h.prefix_entry is e
        )
        if e.refs != expected:
            problems.append(
                f"prefix entry ({e.shared_len} tokens) refs={e.refs} but "
                f"{expected} referent(s) exist — ref leak"
            )

    # allocator's own cross-checks (double-free / free-and-owned overlap)
    try:
        allocator.check_invariants()
    except AssertionError as e:
        problems.append(f"allocator invariants: {e}")

    return problems


def close_journals() -> list[str]:
    """Close tracked AnsweredJournal handles left open by a test (fd
    hygiene across a 350-test suite); returns what was closed."""
    closed = []
    for journal in tracked_instances("journal"):
        if getattr(journal, "_fh", None) is not None:
            journal.close()
            closed.append(str(getattr(journal, "path", "?")))
    return closed
