"""finchat-lint: AST-based serving-plane discipline checker (ISSUE 8).

PRs 4-7 caught the same three bug classes by hand on every review round:
seconds-class blocking work on the asyncio scheduler loop (inline device
rebuilds, serialize+fsync spills), host-sync calls sneaking into the
one-dispatch-per-iteration hot path, and slot/page/ref leaks on cleanup
paths that each needed a bespoke regression test. Those invariants are
load-bearing across ~10 modules but lived only in reviewers' heads; this
package machine-checks them on every push.

Rule catalog (see STATIC_ANALYSIS.md for the full contract):

- R1 ``event-loop-blocking`` — blocking primitives (fsync, time.sleep,
  ``block_until_ready``, device-rebuild entry points, executor joins,
  blocking file opens) reachable from ``async def`` bodies or registered
  loop callbacks, via a package-wide call graph. Off-loop seams
  (``asyncio.to_thread``, ``run_in_executor``, executor ``submit``,
  threads) prune the walk.
- R2 ``hot-path-host-sync`` — ``.item()`` / ``np.asarray`` / ``float()``
  / implicit ``__bool__`` on device values inside hot scopes (``ops/``,
  ``engine/engine.py``, the scheduler dispatch/consume paths), protecting
  the dispatches-per-iteration contract of PR 4 / ROADMAP item 1.
- R3 ``resource-pairing`` — allocator acquires / slot claims /
  ``refs += 1`` must release or escape on every exit path, and cleanup
  paths must not run unguarded device ops ahead of their releases (the
  ``_fail_prefix_job`` bug class PR 6 fixed).
- R4 ``knob-consistency`` — every ``utils/config.py`` knob's env var,
  CLI flag, and README mention must agree (drift check).
- R5 ``metrics-discipline`` — ``finchat_`` naming, counter/gauge/
  histogram suffix conventions, and the PR 6 labeled-vs-unlabeled family
  convention (fleet-level series emit unlabeled on the global registry).

Usage::

    python -m finchat_tpu.analysis finchat_tpu/ tests/
    python -m finchat_tpu.analysis --list-rules
    python -m finchat_tpu.analysis --update-baseline

Inline suppressions: ``# finchat-lint: disable=<rule>[,<rule>] -- why``
on the offending line, or on a ``def``/``class`` line to cover the scope.
The justification after ``--`` is mandatory (checked by the
``suppression-discipline`` meta rule). The checked-in baseline
(``LINT_BASELINE.json``) may only shrink: new findings fail the run.

The package also ships the runtime complements (``sanitizers.py``): an
asyncio stall sanitizer (instrumented loop that fails a test when any
callback exceeds a threshold — the dynamic teeth behind R1) and a leak
sanitizer (asserts allocator/slots/pages/session-cache refs/journal
handles are clean after scheduler/fleet/durability tests — the dynamic
teeth behind R3). ``tests/conftest.py`` wires both in.
"""

from finchat_tpu.analysis.core import (  # noqa: F401
    Finding,
    ProjectIndex,
    Rule,
    load_baseline,
    run_analysis,
    write_baseline,
)

__all__ = [
    "Finding",
    "ProjectIndex",
    "Rule",
    "run_analysis",
    "load_baseline",
    "write_baseline",
]
