"""R1 ``event-loop-blocking``: blocking primitives reachable from the
asyncio scheduler loop.

The shipped bug class (PRs 5-7 review rounds): seconds-class work — device
rebuilds, serialize+fsync spills — running directly on the event loop that
every in-flight stream (and, since the fleet, every SIBLING replica)
shares. The blessed pattern is a worker-thread seam (``asyncio.to_thread``,
``run_in_executor``, the session disk tier's write-behind worker); this
rule finds the paths that skip it.

Mechanics: every ``async def`` body and every function registered as a
loop callback (``add_done_callback`` / ``call_soon`` / ...) is a root.
The package call graph is walked from the roots — including into *sync*
callees (a sync helper called from a coroutine still runs on the loop)
and *awaited async* callees (awaiting doesn't offload) — and every
reachable blocking primitive is reported at its own line, with the
root-to-primitive chain in the message. Off-loop boundaries prune the
walk: a callable passed BY REFERENCE to ``to_thread`` / ``submit`` /
``run_in_executor`` / ``Thread`` never creates an edge, and a lambda
argument of those wrappers is skipped entirely; their sibling arguments
still evaluate on the loop and ARE visited.

Blocking primitives:

- ``time.sleep``
- ``os.fsync`` / ``os.fdatasync`` / ``os.sync`` (and any ``.fsync()``)
- ``jax.block_until_ready`` / any ``.block_until_ready()``
- device-rebuild entry points (``rebuild_device_state``)
- executor joins (``....submit(...).result()``)
- blocking file opens (builtin ``open``)
- blocking socket I/O (``socket.create_connection`` and the
  ``.recv()`` / ``.sendall()`` / ``.accept()`` method tails) — the pod
  liaison must use asyncio streams or an off-loop worker

Allowlist (the blessed off-loop seams, per STATIC_ANALYSIS.md): the
session disk tier's writer-thread bodies — reachable inline only in the
sync-write test mode — are pruned here; everything else blessed in-tree
carries an inline suppression WITH its justification at the seam itself,
so the why lives next to the code.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass

from finchat_tpu.analysis.core import (
    CallSite,
    Finding,
    FunctionInfo,
    ProjectIndex,
    Rule,
    dotted_name,
)

# import-resolved dotted names that block the calling thread
BLOCKING_DOTTED = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync",
    "os.fdatasync": "os.fdatasync",
    "os.sync": "os.sync",
    "jax.block_until_ready": "jax.block_until_ready",
    # blocking socket dial (ISSUE 20: the pod liaison must be asyncio
    # streams or an off-loop worker, like every other I/O seam)
    "socket.create_connection": "socket.create_connection",
}

# attribute tails that block regardless of receiver type
BLOCKING_METHODS = {
    "block_until_ready": "device sync (.block_until_ready)",
    "rebuild_device_state": "device-state rebuild (seconds of device work)",
    "fsync": "fsync",
    # blocking socket I/O (ISSUE 20): a liaison channel built on raw
    # sockets would stall every in-flight stream for a peer's RTT — the
    # asyncio-streams transport in serve/pod.py is the blessed path.
    # These tails are socket-specific by convention in this codebase
    # (asyncio writers use write/drain, never sendall/recv/accept).
    "recv": "blocking socket `.recv()`",
    "sendall": "blocking socket `.sendall()`",
    "accept": "blocking socket `.accept()`",
}

# blessed off-loop seams: traversal never descends into (or reports
# inside) functions whose full qualname ends with one of these. Keep this
# list SHORT — prefer an inline suppression at the seam, where the
# justification lives with the code. These two are the session disk
# tier's writer-thread bodies: on the production path they only ever run
# on the write-behind worker; the inline fallback exists for the
# sync-write test mode.
ALLOWED_SEAMS = (
    "SessionDiskTier._write_record",
    "SessionDiskTier._discard_now",
)

# async functions that are STARTUP/BOOT paths, not serving-loop paths:
# they run before any stream is live (App.start launches the consume task
# as its last act), so blocking there is the documented boot cost —
# checkpoint loads, warmup compiles, journal replay. They are skipped as
# roots; their helpers are still checked when some serving-path root
# reaches them.
STARTUP_ROOTS = ("start", "main")


@dataclass(frozen=True)
class _Primitive:
    line: int
    desc: str


class EventLoopBlockingRule(Rule):
    name = "event-loop-blocking"
    code = "R1"
    description = (
        "blocking calls (fsync/sleep/device sync/rebuild/executor join/"
        "file open) reachable from async defs or registered loop callbacks"
    )

    def run(self, project: ProjectIndex) -> list[Finding]:
        primitives = {fn: self._primitives(fn, project) for fn in project.all_functions()}
        edges = {fn: self._edges(fn, project) for fn in project.all_functions()}

        roots = [
            fn
            for fn in project.all_functions()
            if (fn.is_async or fn.is_loop_callback)
            and not _allowlisted(fn)
            and fn.name not in STARTUP_ROOTS
        ]
        # BFS from all roots at once; per function remember the shortest
        # chain (list of qualnames root..fn) that reaches it
        chain: dict[FunctionInfo, list[str]] = {}
        q: deque[FunctionInfo] = deque()
        for root in sorted(roots, key=lambda f: (f.module.relpath, f.qualname)):
            if root not in chain:
                chain[root] = [root.qualname]
                q.append(root)
        while q:
            fn = q.popleft()
            for callee in edges[fn]:
                if callee in chain or _allowlisted(callee):
                    continue
                chain[callee] = chain[fn] + [callee.qualname]
                q.append(callee)

        findings: list[Finding] = []
        seen: set[tuple[str, int, str]] = set()
        for fn, path in chain.items():
            for prim in primitives[fn]:
                key = (fn.module.relpath, prim.line, prim.desc)
                if key in seen:
                    continue
                seen.add(key)
                if len(path) == 1:
                    via = f"directly in async `{path[0]}`"
                else:
                    via = f"reachable from `{path[0]}` via " + " -> ".join(path[1:])
                findings.append(
                    Finding(
                        self.name,
                        fn.module.relpath,
                        prim.line,
                        fn.qualname,
                        f"{prim.desc} may block the event loop; {via} "
                        "(move it behind asyncio.to_thread / the write-"
                        "behind worker, or suppress with a justification)",
                    )
                )
        return findings

    # -- per-function scans ------------------------------------------------
    def _primitives(self, fn: FunctionInfo, project: ProjectIndex) -> list[_Primitive]:
        out: list[_Primitive] = []
        for site in fn.calls:
            node = site.node
            # builtin open()
            if isinstance(node.func, ast.Name) and node.func.id == "open":
                if "open" not in fn.module.imports:
                    out.append(_Primitive(node.lineno, "blocking file `open()`"))
                continue
            # executor join: <...>.submit(...).result()
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "result"
                and isinstance(node.func.value, ast.Call)
            ):
                inner = dotted_name(node.func.value.func)
                if inner and inner.rsplit(".", 1)[-1] == "submit":
                    out.append(
                        _Primitive(node.lineno, "executor join (`.submit(...).result()`)")
                    )
                    continue
            ext = project.external_target(site, fn)
            if ext in BLOCKING_DOTTED:
                out.append(_Primitive(node.lineno, f"`{BLOCKING_DOTTED[ext]}`"))
                continue
            if isinstance(node.func, ast.Attribute):
                tail = node.func.attr
                if tail in BLOCKING_METHODS:
                    # the NAME is the contract (a rebuild_device_state is
                    # seconds of device work no matter how it resolves)
                    out.append(_Primitive(node.lineno, BLOCKING_METHODS[tail]))
        return out

    def _edges(self, fn: FunctionInfo, project: ProjectIndex) -> list[FunctionInfo]:
        out: list[FunctionInfo] = []
        for site in fn.calls:
            if site.off_loop_wrapper:
                continue  # the callable arg runs on a worker thread
            out.extend(project.resolve_call(site, fn))
        return out


def _allowlisted(fn: FunctionInfo) -> bool:
    full = fn.full_qualname
    return any(full.endswith(seam) for seam in ALLOWED_SEAMS)
