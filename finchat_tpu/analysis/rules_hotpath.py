"""R2 ``hot-path-host-sync``: device→host synchronization inside the hot
dispatch/consume paths.

PR 4's contract — ONE ragged dispatch per scheduler iteration — and the
free-running-loop direction (ROADMAP item 5) both die by a thousand
``.item()`` calls: any host materialization of a device value inside the
dispatch path serializes the pipeline (the host blocks until the device
catches up) and reintroduces the per-round sync PR 1/PR 4 removed. The
blessed pattern is batching every host fetch into the single
``await asyncio.to_thread(...)`` consume seam.

Hot scopes (the ISSUE 8 set):

- every function in ``finchat_tpu/ops/`` (kernel wrappers),
- ``finchat_tpu/engine/engine.py`` except construction/teardown
  (``__init__`` / ``create_state`` / ``warmup`` / ``rebuild_device_state``
  — warmup *exists* to pay syncs up front),
- the scheduler's dispatch/consume path functions (by name),
- any function whose ``def`` line carries ``# finchat-lint: hot``.

Flagged inside a hot scope (off-loop lambdas handed to ``to_thread`` /
``submit`` are exempt — that's the blessed seam):

- ``.item()`` — always a device sync,
- ``np.asarray`` / ``np.array`` / ``jax.device_get`` on a device-tainted
  value (D2H transfer),
- ``float()`` / ``int()`` / ``bool()`` on a device-tainted value,
- ``.block_until_ready()``,
- an ``if`` / ``while`` / ``assert`` test on a device-tainted value —
  the implicit ``__bool__`` is a hidden blocking transfer.

"Device-tainted" is a per-function dataflow approximation: ``jnp.*`` /
``lax.*`` call results seed it; assignments, arithmetic, subscripts,
and method calls on tainted values propagate it; array METADATA
(``x.shape``, ``jnp.ndim(x)``) and identity tests (``x is None``) are
host-side and never taint. Cross-function: a resolved call taints only
when the callee itself "returns device" — inferred by checking whether
its own ``return`` expressions are tainted (fixpoint over the call
graph), so host helpers living in hot modules (backend-name lookups,
shape math) correctly taint nothing. Function parameters are untainted
by default (the consume seam hands *host* arrays around).
"""

from __future__ import annotations

import ast

from finchat_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    ProjectIndex,
    Rule,
    dotted_name,
)

_OFF_LOOP_TAILS = ("to_thread", "run_in_executor", "submit")

SCHEDULER_HOT = {
    "_dispatch_decode",
    "_dispatch_decode_loop",
    "_ragged_round",
    "_prefill_round",
    "_run_spec_step",
    "_consume_step",
    "_consume_block",
    "_consume_inflight",
    "_drain_inflight",
    "_deliver",
    "_pack_prefill_rows",
}

# the freerun-consume check (ISSUE 13): the free-running loop's ring-drain
# seam joins the hot set by name — a block_until_ready / .item() /
# implicit __bool__ on the drain path would re-serialize the host against
# the very capture the loop exists to overlap (the token ring must be
# fetched through the off-loop to_thread seam, never synced inline)
FREERUN_HOT = {
    "_dispatch_freerun",
    "_consume_ring",
}
SCHEDULER_HOT |= FREERUN_HOT

ENGINE_COLD = {"__init__", "create_state", "warmup", "rebuild_device_state"}

_TAINT_ROOTS = {"jnp", "lax"}
_D2H_CALLS = {"numpy.asarray", "numpy.array", "jax.device_get"}
_CAST_BUILTINS = {"float", "int", "bool"}


def is_hot(fn: FunctionInfo) -> bool:
    rel = fn.module.relpath
    if fn.node.lineno in fn.module.hot_marks:
        return True
    if "/ops/" in f"/{rel}":
        return True
    if rel.endswith("engine/engine.py"):
        return fn.name not in ENGINE_COLD
    if rel.endswith("engine/scheduler.py"):
        return fn.name in SCHEDULER_HOT
    return False


def _is_hot_module(relpath: str) -> bool:
    return "/ops/" in f"/{relpath}" or relpath.endswith("engine/engine.py")


class HotPathHostSyncRule(Rule):
    name = "hot-path-host-sync"
    code = "R2"
    description = (
        "host sync (.item()/np.asarray/float()/implicit __bool__/"
        "block_until_ready) on device values inside hot dispatch paths"
    )

    def run(self, project: ProjectIndex) -> list[Finding]:
        self._returns_device = _infer_returns_device(project)
        findings: list[Finding] = []
        for fn in project.all_functions():
            if is_hot(fn):
                findings.extend(self._check(fn, project))
        return findings

    def _check(self, fn: FunctionInfo, project: ProjectIndex) -> list[Finding]:
        tainted = self._taint(fn, project)
        findings: list[Finding] = []

        def hit(node: ast.AST, msg: str) -> None:
            findings.append(
                Finding(
                    self.name,
                    fn.module.relpath,
                    node.lineno,
                    fn.qualname,
                    f"{msg} in hot path (one-dispatch-per-iteration "
                    "contract); batch it into the off-loop consume seam "
                    "or suppress with a justification",
                )
            )

        returns_device = self._returns_device

        def is_tainted(expr: ast.AST) -> bool:
            return _expr_tainted(expr, tainted, fn, project, returns_device)

        class V(ast.NodeVisitor):
            def __init__(self) -> None:
                self._top = True

            def visit_FunctionDef(self, node):  # nested defs scanned on their own
                if self._top:
                    self._top = False
                    self.generic_visit(node)

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(self, node: ast.Call) -> None:
                d = dotted_name(node.func)
                tail = d.rsplit(".", 1)[-1] if d else (
                    node.func.attr if isinstance(node.func, ast.Attribute) else None
                )
                if isinstance(node.func, ast.Attribute):
                    if node.func.attr == "item":
                        hit(node, "`.item()` device sync")
                    elif node.func.attr == "block_until_ready":
                        hit(node, "`.block_until_ready()` device sync")
                if d and node.args:
                    ext = _external(d, fn)
                    if ext in _D2H_CALLS and is_tainted(node.args[0]):
                        hit(node, f"`{d}` D2H transfer of a device value")
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id in _CAST_BUILTINS
                    and node.args
                    and is_tainted(node.args[0])
                ):
                    hit(node, f"`{node.func.id}()` on a device value")
                # recurse, skipping off-loop lambda bodies
                off = tail in _OFF_LOOP_TAILS
                for child in list(node.args) + [kw.value for kw in node.keywords]:
                    if off and isinstance(child, ast.Lambda):
                        continue
                    self.visit(child)
                if not isinstance(node.func, ast.Name):
                    self.visit(node.func)

            def visit_If(self, node: ast.If) -> None:
                if is_tainted(node.test):
                    hit(node, "implicit `__bool__` (if) on a device value")
                self.generic_visit(node)

            def visit_While(self, node: ast.While) -> None:
                if is_tainted(node.test):
                    hit(node, "implicit `__bool__` (while) on a device value")
                self.generic_visit(node)

            def visit_Assert(self, node: ast.Assert) -> None:
                if is_tainted(node.test):
                    hit(node, "implicit `__bool__` (assert) on a device value")
                self.generic_visit(node)

        V().visit(fn.node)
        return findings

    def _check_taint(self, fn, project):
        return _local_taint(fn, project, self._returns_device)

    def _taint(self, fn: FunctionInfo, project: ProjectIndex) -> set[str]:
        return _local_taint(fn, project, self._returns_device)


def _taint_target(tgt: ast.AST, tainted: set[str]) -> None:
    if isinstance(tgt, ast.Name):
        tainted.add(tgt.id)
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            _taint_target(elt, tainted)
    elif isinstance(tgt, ast.Starred):
        _taint_target(tgt.value, tainted)


def _external(dotted: str, fn: FunctionInfo) -> str:
    parts = dotted.split(".")
    imp = fn.module.imports.get(parts[0])
    return ".".join([imp] + parts[1:]) if imp else dotted


# array metadata accessors return HOST values (ints/tuples/dtypes), not
# device buffers — both as attributes (``x.shape``) and as jnp/np helper
# calls (``jnp.ndim(x)``)
_HOST_META_ATTRS = {"shape", "dtype", "ndim", "size", "nbytes", "itemsize"}
_HOST_META_CALLS = {"ndim", "shape", "size", "result_type", "iinfo", "finfo"}


def _local_taint(fn, project, returns_device) -> set[str]:
    """Fixpoint over assignments: names bound (directly or through
    arithmetic/subscripts) to jnp/lax call results or to calls of
    functions inferred to return device values."""
    tainted: set[str] = set()
    for _ in range(2):
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Assign):
                if _expr_tainted(node.value, tainted, fn, project, returns_device):
                    for tgt in node.targets:
                        _taint_target(tgt, tainted)
            elif isinstance(node, ast.AugAssign):
                if _expr_tainted(node.value, tainted, fn, project, returns_device):
                    _taint_target(node.target, tainted)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if _expr_tainted(node.value, tainted, fn, project, returns_device):
                    _taint_target(node.target, tainted)
    return tainted


def _infer_returns_device(project: ProjectIndex) -> dict:
    """One-level interprocedural inference: a function "returns device"
    when any of its ``return`` expressions is device-tainted under its own
    local taint. Host helpers living in hot modules (backend-name lookups,
    shape math) correctly come out False — calling them taints nothing."""
    returns_device: dict = {}
    fns = list(project.all_functions())
    for _ in range(3):  # fixpoint across call chains
        changed = False
        for fn in fns:
            tainted = _local_taint(fn, project, returns_device)
            val = False
            for node in ast.walk(fn.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    if _expr_tainted(node.value, tainted, fn, project, returns_device):
                        val = True
                        break
            if returns_device.get(fn) != val:
                returns_device[fn] = val
                changed = True
        if not changed:
            break
    return returns_device


def _expr_tainted(
    expr: ast.AST,
    tainted: set[str],
    fn: FunctionInfo,
    project: ProjectIndex,
    returns_device: dict,
) -> bool:
    def rec(e: ast.AST) -> bool:
        return _expr_tainted(e, tainted, fn, project, returns_device)

    if isinstance(expr, ast.Name):
        return expr.id in tainted
    if isinstance(expr, ast.Attribute):
        if expr.attr in _HOST_META_ATTRS:
            return False
        return rec(expr.value)
    if isinstance(expr, ast.Subscript):
        return rec(expr.value)
    if isinstance(expr, ast.Call):
        # a method call on a tainted value stays device-side
        # (logits.argmax(), x.astype(...), x.reshape(...))
        if isinstance(expr.func, ast.Attribute) and rec(expr.func.value):
            return expr.func.attr not in _HOST_META_CALLS
        d = dotted_name(expr.func)
        if d:
            parts = d.split(".")
            if parts[0] in _TAINT_ROOTS:
                return parts[-1] not in _HOST_META_CALLS
            for target in project.resolve_call(
                # a lightweight CallSite stand-in: resolve_call only reads
                # .dotted
                type("S", (), {"dotted": d, "node": expr, "off_loop_wrapper": False})(),
                fn,
            ):
                if returns_device.get(target):
                    return True
        return False
    if isinstance(expr, ast.BinOp):
        return rec(expr.left) or rec(expr.right)
    if isinstance(expr, ast.UnaryOp):
        return rec(expr.operand)
    if isinstance(expr, ast.Compare):
        # identity tests never touch __bool__ on the array
        if all(isinstance(op, (ast.Is, ast.IsNot)) for op in expr.ops):
            return False
        return rec(expr.left) or any(rec(c) for c in expr.comparators)
    if isinstance(expr, ast.BoolOp):
        return any(rec(v) for v in expr.values)
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(rec(e) for e in expr.elts)
    if isinstance(expr, ast.IfExp):
        return rec(expr.body) or rec(expr.orelse)
    if isinstance(expr, ast.Starred):
        return rec(expr.value)
    return False
