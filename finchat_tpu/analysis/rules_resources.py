"""R3 ``resource-pairing``: allocator acquires, slot claims, and refcount
bumps must be released (or ownership-transferred) on every exit path.

Two shipped bug shapes drive the checks (both fixed by hand in PR 5/6
review rounds, each with its own bespoke regression test):

1. **Early-exit leak** — a function allocates pages / pops a slot /
   bumps a refcount, then returns or raises on some path without freeing
   and without transferring ownership (storing the pages on a handle,
   returning them, passing them to a successor). Check (a) walks each
   function with a small branch-aware interpreter and reports resources
   still open at a ``return`` / ``raise`` / fall-through.

2. **Unguarded device op on a cleanup path** — ``_fail_prefix_job``
   originally called ``engine.reset_slot`` bare; on a wedged device the
   raise skipped ``free_slots.append`` and the future resolution,
   stranding the awaiter forever. Check (b) flags device-op calls that
   are (i) inside a cleanup-named function (``*fail*`` / ``*evict*`` /
   ``*release*`` / ``*preempt*`` / ``*drop*`` / ``*cleanup*`` /
   ``*reap*``) or (ii) inside any ``finally`` / ``except`` block, are
   NOT wrapped in their own ``try``, and are followed by a release
   statement that the raise would skip.

Ownership-transfer is deliberately lenient: a resource that escapes
ANYWHERE in the function (stored into an attribute, returned, passed to
a non-release call) is treated as transferred and exempt from (a) —
the scheduler's handle/page-list plumbing hands pages around
constantly, and a false-positive lint on the serving plane would just
breed reflexive suppressions.
"""

from __future__ import annotations

import ast
import re

from finchat_tpu.analysis.core import (
    Finding,
    FunctionInfo,
    ProjectIndex,
    Rule,
    dotted_name,
)

_CLEANUP_NAME = re.compile(r"(fail|evict|release|preempt|drop|cleanup|reap)")

_DEVICE_OPS = {
    "reset_slot",
    "reset_slots",
    "set_page_table_row",
    "set_page_table_rows",
    "set_context_lens_rows",
    "set_last_token",
    "prefill",
    "restore_pages",
    "offload_pages",
    "rebuild_device_state",
}

_RELEASE_TAILS = {"free", "append", "appendleft", "set_result", "put_nowait"}

# calls that can neither raise meaningfully nor take ownership
_SAFE_CALL_ROOTS = {"logger", "logging"}
_SAFE_BUILTINS = {
    "len", "list", "min", "max", "sum", "set", "sorted", "enumerate",
    "zip", "range", "iter", "reversed", "isinstance", "print", "repr",
    "str", "tuple", "dict", "abs", "id",
}


class ResourcePairingRule(Rule):
    name = "resource-pairing"
    code = "R3"
    description = (
        "allocator acquires / slot claims / ref bumps released on all "
        "exit paths; no unguarded device ops ahead of cleanup releases"
    )

    def run(self, project: ProjectIndex) -> list[Finding]:
        findings: list[Finding] = []
        for fn in project.all_functions():
            findings.extend(self._check_pairing(fn))
            findings.extend(self._check_cleanup_guard(fn))
        return findings

    # -- (a) acquire/release pairing --------------------------------------
    def _check_pairing(self, fn: FunctionInfo) -> list[Finding]:
        body = getattr(fn.node, "body", [])
        opens = _collect_opens(body)
        if not opens:
            return []
        escaped = _escaping_vars(body, opens)
        tracked = {v: line for v, line in opens.items() if v not in escaped}
        if not tracked:
            return []
        findings: list[Finding] = []

        def report(node: ast.AST, var: str, what: str) -> None:
            findings.append(
                Finding(
                    self.name,
                    fn.module.relpath,
                    node.lineno,
                    fn.qualname,
                    f"resource `{var}` (acquired in this function) is "
                    f"still open at {what}; release it or transfer "
                    "ownership on every exit path",
                )
            )

        _Interp(tracked, report).run(body)
        return findings

    # -- (b) unguarded device ops on cleanup paths ------------------------
    def _check_cleanup_guard(self, fn: FunctionInfo) -> list[Finding]:
        findings: list[Finding] = []
        seen: set[int] = set()
        cleanup_fn = bool(_CLEANUP_NAME.search(fn.name))

        def unguarded(stmts: list[ast.stmt]):
            """Nodes in these statements NOT under a nested try or def."""
            for s in stmts:
                if isinstance(s, ast.Try):
                    continue
                yield from _walk_skipping(s, skip_try=True)

        def releases(stmts: list[ast.stmt]) -> list[int]:
            return [
                n.lineno
                for s in stmts
                for n in _walk_skipping(s, skip_try=False)
                if _is_release(n)
            ]

        def scan(stmts: list[ast.stmt], active: bool) -> None:
            if active:
                rel = releases(stmts)
                for n in unguarded(stmts):
                    if (
                        isinstance(n, ast.Call)
                        and _is_device_op(n)
                        and n.lineno not in seen
                        and any(line > n.lineno for line in rel)
                    ):
                        seen.add(n.lineno)
                        findings.append(
                            Finding(
                                self.name,
                                fn.module.relpath,
                                n.lineno,
                                fn.qualname,
                                "unguarded device op "
                                f"`{dotted_name(n.func)}` on a cleanup "
                                "path with releases after it; if it "
                                "raises, the releases are skipped "
                                "(the _fail_prefix_job bug class) — "
                                "wrap it in try/except",
                            )
                        )
            # except/finally blocks are cleanup contexts in ANY function;
            # recurse into try bodies (not flagged themselves — they are
            # guarded) only to discover the trys nested inside them
            for t in _outermost_trys(stmts):
                for h in t.handlers:
                    scan(h.body, True)
                scan(t.finalbody, True)
                scan(t.body, False)
                scan(t.orelse, False)

        scan(getattr(fn.node, "body", []), cleanup_fn)
        return findings


def _walk_skipping(node: ast.AST, skip_try: bool):
    """Yield ``node`` and descendants, never descending into nested defs,
    and (optionally) never into ``try`` statements."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if skip_try and isinstance(child, ast.Try):
            continue
        yield from _walk_skipping(child, skip_try)


def _outermost_trys(stmts: list[ast.stmt]) -> list[ast.Try]:
    """Try statements within ``stmts`` that are not nested inside another
    try (nested defs excluded)."""
    out: list[ast.Try] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, ast.Try):
                out.append(child)
                continue
            walk(child)

    for s in stmts:
        if isinstance(s, ast.Try):
            out.append(s)
        else:
            walk(s)
    return out


def _is_device_op(node: ast.Call) -> bool:
    d = dotted_name(node.func)
    if not d:
        return False
    parts = d.split(".")
    if parts[-1] not in _DEVICE_OPS:
        return False
    recv = parts[:-1]
    return bool(recv) and recv[-1] in ("engine", "eng", "_engine")


def _is_release(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        d = dotted_name(node.func)
        return bool(d) and d.rsplit(".", 1)[-1] in _RELEASE_TAILS
    if isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Sub):
        tgt = dotted_name(node.target)
        return bool(tgt) and tgt.endswith(".refs")
    return False


# -- open/close/escape helpers ----------------------------------------------


def _collect_opens(body: list[ast.stmt]) -> dict[str, int]:
    """var name -> line for ``x = *.allocate(...)`` and
    ``x = free_slots.pop()`` assignments."""
    opens: dict[str, int] = {}
    for s in body:
        for node in ast.walk(s):
            if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                continue
            d = dotted_name(node.value.func)
            if not d:
                continue
            tail = d.rsplit(".", 1)[-1]
            acquire = (tail == "allocate" and "allocator" in d) or (
                tail == "pop" and "free_slots" in d
            )
            if acquire:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        opens[tgt.id] = node.lineno
    return opens


def _escaping_vars(body: list[ast.stmt], opens: dict[str, int]) -> set[str]:
    """Vars whose value is ever transferred: returned/yielded, stored into
    an attribute/subscript/other name, or passed to a call that is not a
    release/safe call."""
    escaped: set[str] = set()

    def uses(expr: ast.AST, var: str) -> bool:
        return any(isinstance(n, ast.Name) and n.id == var for n in ast.walk(expr))

    for s in body:
        for node in ast.walk(s):
            if isinstance(node, (ast.Return, ast.Yield)) and node.value is not None:
                escaped.update(v for v in opens if uses(node.value, v))
            elif isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)):
                        escaped.update(v for v in opens if uses(node.value, v))
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, (ast.Attribute, ast.Subscript)):
                    escaped.update(v for v in opens if uses(node.value, v))
            elif isinstance(node, ast.Call):
                d = dotted_name(node.func) or ""
                tail = d.rsplit(".", 1)[-1]
                if tail in _RELEASE_TAILS or tail in _SAFE_BUILTINS:
                    continue
                if d.split(".")[0] in _SAFE_CALL_ROOTS:
                    continue
                if d.rsplit(".", 1)[-1] == "allocate":
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    escaped.update(v for v in opens if uses(arg, v))
    return escaped


class _Interp:
    """Branch-aware linear walk tracking the open set; reports resources
    open at return/raise/fall-through. ``try`` blocks whose handlers or
    ``finally`` release a var treat that var as protected."""

    def __init__(self, tracked: dict[str, int], report) -> None:
        self.tracked = tracked
        self.report = report

    def run(self, body: list[ast.stmt]) -> None:
        leftover = self._block(body, set())
        if leftover and body:
            last = body[-1]
            # fall-through off the end with open resources
            if not isinstance(last, (ast.Return, ast.Raise)):
                for var in sorted(leftover):
                    self.report(last, var, "function exit")

    def _block(self, stmts: list[ast.stmt], open_set: set[str]) -> set[str]:
        open_set = set(open_set)
        for s in stmts:
            open_set = self._stmt(s, open_set)
        return open_set

    def _stmt(self, s: ast.stmt, open_set: set[str]) -> set[str]:
        if isinstance(s, ast.Assign) and isinstance(s.value, ast.Call):
            d = dotted_name(s.value.func) or ""
            tail = d.rsplit(".", 1)[-1]
            if (tail == "allocate" and "allocator" in d) or (
                tail == "pop" and "free_slots" in d
            ):
                for tgt in s.targets:
                    if isinstance(tgt, ast.Name) and tgt.id in self.tracked:
                        open_set.add(tgt.id)
                return open_set
        closed = self._closes_in(s)
        open_set -= closed
        if isinstance(s, ast.Return):
            for var in sorted(open_set):
                self.report(s, var, "a return")
            return set()
        if isinstance(s, ast.Raise):
            for var in sorted(open_set):
                self.report(s, var, "a raise")
            return set()
        if isinstance(s, ast.If):
            a = self._block(s.body, open_set)
            b = self._block(s.orelse, open_set)
            return a | b
        if isinstance(s, (ast.For, ast.While, ast.AsyncFor)):
            a = self._block(s.body, open_set)
            b = self._block(s.orelse, a)
            return b
        if isinstance(s, (ast.With, ast.AsyncWith)):
            return self._block(s.body, open_set)
        if isinstance(s, ast.Try):
            protected = set()
            for h in s.handlers:
                protected |= self._closes_anywhere(h.body)
            protected |= self._closes_anywhere(s.finalbody)
            inner = self._block(s.body, open_set - protected)
            inner = self._block(s.orelse, inner)
            # finally closes apply on the straight-line path too
            inner -= self._closes_anywhere(s.finalbody)
            return inner
        return open_set

    def _closes_in(self, s: ast.stmt) -> set[str]:
        closed: set[str] = set()
        for node in ast.walk(s):
            if isinstance(node, ast.Call):
                d = dotted_name(node.func) or ""
                tail = d.rsplit(".", 1)[-1]
                if tail in _RELEASE_TAILS:
                    for arg in node.args:
                        for n in ast.walk(arg):
                            if isinstance(n, ast.Name) and n.id in self.tracked:
                                closed.add(n.id)
        return closed

    def _closes_anywhere(self, stmts: list[ast.stmt]) -> set[str]:
        closed: set[str] = set()
        for s in stmts:
            closed |= self._closes_in(s)
        return closed
