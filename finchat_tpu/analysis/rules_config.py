"""R4 ``knob-consistency``: config knobs, env vars, CLI flags, and README
docs must agree.

The config tree (``utils/config.py``) grew ~50 knobs across PRs 1-7, each
supposed to ship with its env var, its CLI flag where one is declared,
and a README mention. The drift is real: at ISSUE 8 time, 23 env vars
wired in ``load_config`` had never made it into README.md. This rule
makes the contract mechanical:

- (a) every env var name read in ``load_config`` must appear in
  ``README.md`` (docs drift),
- (b) every ``cfg.<section>.<key>`` assignment in ``load_config`` must
  target a declared dataclass field (wiring typos),
- (c) every CLI override key in ``__main__.py``
  (``overrides["section.key"] = ...``) must target a declared field
  (flag drift),
- (d) every ``*Config`` dataclass field must be wired to an env var in
  ``load_config`` — knobs that are deliberately config-file/CLI-only
  carry an inline suppression on the field (or class) line saying so.

The rule is self-scoping: it runs only when the analyzed set contains a
``utils/config.py``; fixtures exercise it with a miniature tree.
"""

from __future__ import annotations

import ast
from pathlib import Path

from finchat_tpu.analysis.core import Finding, ModuleInfo, ProjectIndex, Rule, dotted_name

_ENV_READERS = {"_env", "_env_bool", "_env_int", "_env_float"}


class KnobConsistencyRule(Rule):
    name = "knob-consistency"
    code = "R4"
    description = (
        "config knobs <-> env vars <-> CLI flags <-> README stay in sync"
    )

    def run(self, project: ProjectIndex) -> list[Finding]:
        cfg_mod = next(
            (m for m in project.modules.values() if m.relpath.endswith("utils/config.py")),
            None,
        )
        if cfg_mod is None:
            return []
        findings: list[Finding] = []

        # --- declared fields per Config class ---
        fields: dict[str, dict[str, int]] = {}  # class -> field -> line
        for cls in cfg_mod.classes.values():
            if not cls.name.endswith("Config"):
                continue
            fields[cls.name] = {}
            for node in cls.node.body:
                if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                    fields[cls.name][node.target.id] = node.lineno

        # --- section name -> Config class (from AppConfig fields) ---
        sections: dict[str, str] = {}
        app_cls = cfg_mod.classes.get("AppConfig")
        if app_cls is not None:
            for node in app_cls.node.body:
                if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                    cls_name = _field_class(node)
                    if cls_name:
                        sections[node.target.id] = cls_name

        def field_exists(section: str, key: str) -> bool:
            cls_name = sections.get(section)
            return bool(cls_name) and key in fields.get(cls_name, {})

        # --- env wiring in load_config ---
        load_fn = cfg_mod.functions.get("load_config")
        env_names: dict[str, int] = {}  # env var -> line
        wired: set[tuple[str, str]] = set()  # (section, key)
        if load_fn is not None:
            for node in ast.walk(load_fn.node):
                if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
                    continue
                func = node.value.func
                if not (isinstance(func, ast.Name) and func.id in _ENV_READERS):
                    continue
                if node.value.args and isinstance(node.value.args[0], ast.Constant):
                    env_names[str(node.value.args[0].value)] = node.lineno
                for tgt in node.targets:
                    d = dotted_name(tgt)
                    if d and d.startswith("cfg.") and d.count(".") == 2:
                        _, section, key = d.split(".")
                        wired.add((section, key))
                        if not field_exists(section, key):
                            findings.append(
                                Finding(
                                    self.name,
                                    cfg_mod.relpath,
                                    node.lineno,
                                    "load_config",
                                    f"env wiring targets `{section}.{key}` "
                                    "but no such config field is declared",
                                )
                            )

        # --- (a) README mentions ---
        readme = _read_readme(project.root)
        for env, line in sorted(env_names.items()):
            if env not in readme:
                findings.append(
                    Finding(
                        self.name,
                        cfg_mod.relpath,
                        line,
                        "load_config",
                        f"env var `{env}` is wired but never mentioned in "
                        "README.md (add it to the configuration reference)",
                    )
                )

        # --- (c) CLI override keys in __main__.py ---
        main_mod = next(
            (m for m in project.modules.values() if m.relpath.endswith("__main__.py")
             and "analysis" not in m.relpath),
            None,
        )
        if main_mod is not None:
            for node in ast.walk(main_mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                for tgt in node.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.slice, ast.Constant)
                        and isinstance(tgt.slice.value, str)
                        and "." in tgt.slice.value
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "overrides"
                    ):
                        section, _, key = tgt.slice.value.partition(".")
                        if not field_exists(section, key):
                            findings.append(
                                Finding(
                                    self.name,
                                    main_mod.relpath,
                                    node.lineno,
                                    "main",
                                    f"CLI override targets `{section}.{key}` "
                                    "but no such config field is declared",
                                )
                            )

        # --- (d) every declared field has env wiring ---
        reverse_sections = {v: k for k, v in sections.items()}
        for cls_name, cls_fields in sorted(fields.items()):
            section = reverse_sections.get(cls_name)
            if section is None:
                continue  # not reachable from AppConfig
            for key, line in sorted(cls_fields.items()):
                if (section, key) not in wired:
                    findings.append(
                        Finding(
                            self.name,
                            cfg_mod.relpath,
                            line,
                            cls_name,
                            f"knob `{section}.{key}` has no env var wired in "
                            "load_config (wire one, or suppress on the "
                            "field line if it is config-file/CLI-only by "
                            "design)",
                        )
                    )
        return findings


def _field_class(node: ast.AnnAssign) -> str | None:
    ann = node.annotation
    if isinstance(ann, ast.Name) and ann.id.endswith("Config"):
        return ann.id
    return None


def _read_readme(root: Path) -> str:
    p = root / "README.md"
    try:
        return p.read_text()
    except OSError:
        return ""
