"""CLI: ``python -m finchat_tpu.analysis [paths...]``.

Exit codes: 0 = clean (every finding suppressed or baselined), 1 = new
unsuppressed findings (or a missing-justification suppression), 2 = usage
error. The baseline (``LINT_BASELINE.json`` at the repo root) may only
shrink: ``--update-baseline`` rewrites it from the current findings and
is the ONLY sanctioned way to change it (reviewers diff it).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from finchat_tpu.analysis.core import (
    Finding,
    _collect_py_files,
    default_rules,
    load_baseline,
    run_analysis,
    write_baseline,
)

DEFAULT_PATHS = ["finchat_tpu"]
BASELINE_NAME = "LINT_BASELINE.json"


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m finchat_tpu.analysis",
        description="finchat-lint: serving-plane discipline checker "
        "(rules R1-R5; see STATIC_ANALYSIS.md)",
    )
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to analyze (default: finchat_tpu)")
    p.add_argument("--root", default=".",
                   help="repo root (baseline + README live here)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline file (default: <root>/{BASELINE_NAME})")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from the current findings")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (report everything)")
    p.add_argument("--rule", action="append", default=None,
                   help="run only this rule (name or R-code; repeatable)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--show-suppressed", action="store_true",
                   help="also list suppressed findings")
    args = p.parse_args(argv)

    if args.list_rules:
        for rule in default_rules():
            print(f"{rule.code}  {rule.name:<24} {rule.description}")
        print("--  suppression-discipline   "
              "every `# finchat-lint: disable=` carries a `-- why`")
        return 0

    root = Path(args.root)
    paths = [Path(x) for x in (args.paths or DEFAULT_PATHS)]
    for path in paths:
        if not path.exists():
            print(f"error: no such path: {path}", file=sys.stderr)
            return 2

    result = run_analysis(root, paths, rule_filter=set(args.rule) if args.rule else None)

    baseline_path = Path(args.baseline) if args.baseline else root / BASELINE_NAME
    baseline = {} if args.no_baseline else load_baseline(baseline_path)

    new_findings = [f for f in result.findings if f.fingerprint() not in baseline]
    baselined = [f for f in result.findings if f.fingerprint() in baseline]
    stale = sorted(
        set(baseline) - {f.fingerprint() for f in result.findings}
    )

    if args.update_baseline:
        if args.rule:
            # a rule-filtered run sees only a slice of the findings;
            # regenerating from it would silently delete every other
            # rule's entries and turn them into NEW findings on the next
            # full run
            print("error: --update-baseline cannot be combined with "
                  "--rule (the baseline must be regenerated from a full "
                  "rule run)", file=sys.stderr)
            return 2
        # entries for files OUTSIDE the analyzed set are preserved — a
        # narrowed-path run must only update what it actually looked at
        analyzed = set()
        for f in _collect_py_files(paths):
            try:  # mirror ProjectIndex._rel for paths outside the root
                analyzed.add(f.resolve().relative_to(root.resolve()).as_posix())
            except ValueError:
                analyzed.add(f.as_posix())
        keep = [
            Finding(e["rule"], e["path"], 0, e["symbol"], e["message"])
            for fp, e in load_baseline(baseline_path).items()
            if e["path"] not in analyzed
        ]
        write_baseline(baseline_path, result.findings + keep)
        print(f"baseline written: {baseline_path} "
              f"({len(result.findings)} finding(s)"
              + (f" + {len(keep)} kept for unanalyzed files" if keep else "")
              + ")")
        return 0

    failing = new_findings + result.meta_findings

    if args.format == "json":
        print(json.dumps(
            {
                "findings": [f.__dict__ | {"fingerprint": f.fingerprint()}
                             for f in new_findings],
                "meta": [f.__dict__ for f in result.meta_findings],
                "baselined": len(baselined),
                "suppressed": len(result.suppressed),
                "stale_baseline_entries": stale,
            },
            indent=2,
        ))
        return 1 if failing else 0

    for f in new_findings:
        print(f.render())
    for f in result.meta_findings:
        print(f.render())
    if args.show_suppressed:
        for f, sup in result.suppressed:
            print(f"suppressed: {f.render()}")
    for path, line in result.unused_suppressions:
        print(f"note: {path}:{line}: unused suppression (safe to delete)")
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} — the finding is gone; "
              "run --update-baseline to shrink the file")

    n_sup = len(result.suppressed)
    print(
        f"finchat-lint: {len(new_findings)} new finding(s), "
        f"{len(baselined)} baselined, {n_sup} suppressed, "
        f"{len(result.meta_findings)} meta finding(s)"
    )
    return 1 if failing else 0


if __name__ == "__main__":
    sys.exit(main())
