"""R5 ``metrics-discipline``: metric naming, the labeled-vs-unlabeled
family convention, and span/trace-event name discipline.

The Prometheus surface is the product's north star (utils/metrics.py);
PR 6 established the convention this rule enforces mechanically:

- every series is ``finchat_*``,
- counters (``inc``) end ``_total``; histograms (``observe`` / ``Timer``)
  end ``_seconds``; gauges (``set_gauge``) end in neither,
- per-engine families are emitted through the replica's ``LabeledMetrics``
  view (``self.metrics`` — the ``replica`` label rides implicitly), while
  **fleet-level** series (``finchat_fleet_*``) are emitted UNLABELED on
  the global ``METRICS`` registry — one reader sees the whole family. A
  fleet counter emitted through a labeled view was exactly the PR 6
  review catch (per-replica ``finchat_fleet_drain_failures_total`` series
  that no dashboard summed),
- one series name must not mix explicit-``labels`` and label-free call
  sites (the render groups by base name; a mixed family splits).

Span discipline (ISSUE 12): every ``span.mark("...")`` literal must come
from ``utils/tracing.py``'s ``SPAN_MARKS``, every ``TRACER.event("...")``
literal from the full ``TRACE_EVENT_NAMES`` registry, and every
``TRACER.anomaly("...")`` literal from ``ANOMALY_KINDS`` — a typo'd name
otherwise just silently vanishes from every timeline and flight dump.
Literal names are checked wherever they appear, INCLUDING through the
repo's forwarding helpers (a call to a ``_trace``-named helper whose
literal string argument carries the event name); a forwarding helper's
own non-literal pass-through is exempt by construction, because its call
sites carry the literals. The registries are read from the analyzed
set's ``utils/tracing.py`` (fixtures supply a miniature one); with no
tracing module in scope, the span checks are skipped.

Emission sites are found by shape, not receiver type: a call to
``inc`` / ``set_gauge`` / ``observe`` whose first argument is a string
literal (or a conditional between string literals), or a ``Timer(...,
"name")`` construction. Sites outside ``finchat_tpu/`` (tests, bench
fixtures) are ignored.
"""

from __future__ import annotations

import ast

from finchat_tpu.analysis.core import Finding, ProjectIndex, Rule, dotted_name

_EMITTERS = {"inc", "set_gauge", "observe"}
# the tracing-registry names read out of utils/tracing.py
_REGISTRY_VARS = ("SPAN_MARKS", "TRACE_EVENTS", "ANOMALY_KINDS")


class MetricsDisciplineRule(Rule):
    name = "metrics-discipline"
    code = "R5"
    description = (
        "finchat_* naming, _total/_seconds suffix conventions, and the "
        "fleet-family unlabeled-emission convention"
    )

    def run(self, project: ProjectIndex) -> list[Finding]:
        findings: list[Finding] = []
        # name -> list of (has_explicit_labels, Finding-location tuple)
        sites: dict[str, list[tuple[bool, str, int, str]]] = {}

        for mod in project.modules.values():
            if not mod.modname.startswith("finchat_tpu."):
                continue
            if mod.relpath.endswith("utils/metrics.py"):
                continue  # the registry's own internals
            for fn in mod.functions.values():
                labeled_view = _class_uses_labeled_view(fn)
                for site in fn.calls:
                    for kind, name, node in _emissions(site.node):
                        receiver = (site.dotted or "").rsplit(".", 1)[0]
                        has_labels = any(kw.arg == "labels" for kw in node.keywords)
                        site_labeled = labeled_view and receiver.endswith("metrics")
                        if ".labeled(" in ast.unparse(node.func):
                            site_labeled = True
                        if name is None:
                            continue
                        sites.setdefault(name, []).append(
                            (has_labels, mod.relpath, node.lineno, fn.qualname)
                        )
                        findings.extend(
                            self._check_one(
                                kind, name, receiver, has_labels, site_labeled,
                                mod.relpath, node.lineno, fn.qualname,
                            )
                        )

        findings.extend(self._span_discipline(project))

        # mixed labeled/unlabeled families
        for name, occurrences in sorted(sites.items()):
            kinds = {has for has, *_ in occurrences}
            if len(kinds) == 2:
                for has, relpath, line, qual in occurrences:
                    if not has:
                        findings.append(
                            Finding(
                                self.name,
                                relpath,
                                line,
                                qual,
                                f"`{name}` is emitted both with and "
                                "without explicit labels across the "
                                "package; a mixed family splits the "
                                "Prometheus series grouping",
                            )
                        )
        return findings

    def _check_one(
        self,
        kind: str,
        name: str,
        receiver: str,
        has_labels: bool,
        labeled_view: bool,
        relpath: str,
        line: int,
        qual: str,
    ) -> list[Finding]:
        out: list[Finding] = []

        def bad(msg: str) -> None:
            out.append(Finding(self.name, relpath, line, qual, msg))

        if not name.startswith("finchat_"):
            bad(f"metric `{name}` must be namespaced `finchat_*`")
        if kind == "inc" and not name.endswith("_total"):
            bad(f"counter `{name}` must end `_total`")
        if kind in ("observe", "timer") and not name.endswith("_seconds"):
            bad(f"histogram `{name}` must end `_seconds`")
        if kind == "set_gauge" and (
            name.endswith("_total") or name.endswith("_seconds")
        ):
            bad(
                f"gauge `{name}` must not use a counter/histogram suffix "
                "(_total/_seconds)"
            )
        if name.startswith("finchat_fleet_"):
            # PR 6 convention: fleet-level series are unlabeled — never
            # through a replica's LabeledMetrics view and never with
            # explicit labels. A plain registry receiver (METRICS itself,
            # or a self.metrics that is never built from `.labeled(...)`)
            # is fine.
            if has_labels or labeled_view:
                bad(
                    f"fleet-family series `{name}` must be emitted "
                    "unlabeled on the plain METRICS registry (a labeled "
                    "view would split it into per-replica series no "
                    "dashboard sums — the PR 6 convention)"
                )
        return out


    # --- span/trace-event name discipline (ISSUE 12) --------------------
    def _span_discipline(self, project: ProjectIndex) -> list[Finding]:
        registries = _tracing_registries(project)
        if registries is None:
            return []  # no tracing module in the analyzed set
        span_marks, trace_events, anomaly_kinds = registries
        all_names = span_marks | trace_events | anomaly_kinds
        findings: list[Finding] = []

        def bad(mod, node, fn, msg: str) -> None:
            findings.append(Finding(self.name, mod.relpath, node.lineno,
                                    fn.qualname, msg))

        for mod in project.modules.values():
            if not mod.modname.startswith("finchat_tpu."):
                continue
            if mod.relpath.endswith("utils/tracing.py"):
                continue  # the registry's own internals
            for fn in mod.functions.values():
                for site in fn.calls:
                    node = site.node
                    func = node.func
                    if not isinstance(func, ast.Attribute):
                        continue
                    receiver = (dotted_name(func.value) or "")
                    head = receiver.split(".")[-1]
                    if func.attr == "mark" and head == "span":
                        for name in _name_literals(node):
                            if name not in span_marks:
                                bad(mod, node, fn,
                                    f"span mark `{name}` is not declared in "
                                    "SPAN_MARKS (utils/tracing.py) — a typo'd "
                                    "mark silently vanishes from every timeline")
                    elif func.attr == "event" and head.lower().endswith("tracer"):
                        for name in _name_literals(node):
                            if name not in all_names:
                                bad(mod, node, fn,
                                    f"trace event `{name}` is not declared in "
                                    "the tracing registries (utils/tracing.py)")
                    elif func.attr == "anomaly" and head.lower().endswith("tracer"):
                        for name in _name_literals(node):
                            if name not in anomaly_kinds:
                                bad(mod, node, fn,
                                    f"anomaly kind `{name}` is not declared in "
                                    "ANOMALY_KINDS (utils/tracing.py)")
                    elif func.attr == "_trace":
                        # forwarding-helper convention: the literal event
                        # name rides the helper call (the helper's own
                        # pass-through to TRACER.event is non-literal and
                        # exempt — the literals are checked HERE)
                        for name in _name_literals(node, anywhere=True):
                            if name in all_names:
                                break
                            bad(mod, node, fn,
                                f"trace name `{name}` forwarded through a "
                                "_trace helper is not declared in the tracing "
                                "registries (utils/tracing.py)")
                            break
        return findings


def _name_literals(node: ast.Call, anywhere: bool = False) -> list[str]:
    """The event-name string literal(s) of a tracing call: the first
    positional arg (or ``name=`` keyword); with ``anywhere``, the first
    string-literal positional at any position (forwarding helpers take
    ``(state, "name")``-style signatures)."""
    exprs: list[ast.AST] = []
    if anywhere:
        for arg in node.args:
            if _const_strings(arg):
                exprs.append(arg)
                break
    else:
        if node.args:
            exprs.append(node.args[0])
        for kw in node.keywords:
            if kw.arg == "name":
                exprs.append(kw.value)
    out: list[str] = []
    for e in exprs:
        out.extend(_const_strings(e))
    return out


def _tracing_registries(project: ProjectIndex):
    """(SPAN_MARKS, TRACE_EVENTS, ANOMALY_KINDS) string sets from the
    analyzed set's ``utils/tracing.py``, or None when absent."""
    mod = next(
        (m for m in project.modules.values()
         if m.relpath.endswith("utils/tracing.py")),
        None,
    )
    if mod is None:
        return None
    sets: dict[str, set[str]] = {name: set() for name in _REGISTRY_VARS}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id in sets:
                for inner in ast.walk(node.value):
                    if isinstance(inner, ast.Constant) and isinstance(inner.value, str):
                        sets[tgt.id].add(inner.value)
    return (sets["SPAN_MARKS"], sets["TRACE_EVENTS"], sets["ANOMALY_KINDS"])


def _class_uses_labeled_view(fn) -> bool:
    """True when the function's enclosing class ever builds its
    ``self.metrics`` from a ``.labeled(...)`` view — i.e. instances emit
    per-replica series implicitly (the scheduler/session-cache pattern)."""
    cls = fn.cls
    if cls is None:
        return False
    for meth in cls.methods.values():
        for node in ast.walk(meth.node):
            if not isinstance(node, ast.Assign):
                continue
            tgt_hit = any(
                isinstance(t, ast.Attribute)
                and t.attr == "metrics"
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
                for t in node.targets
            )
            if not tgt_hit:
                continue
            for inner in ast.walk(node.value):
                if (
                    isinstance(inner, ast.Call)
                    and isinstance(inner.func, ast.Attribute)
                    and inner.func.attr == "labeled"
                ):
                    return True
    return False


def _emissions(node: ast.Call):
    """Yield (kind, metric_name, call_node) for emission-shaped calls.
    Conditional names (``inc("a" if x else "b")``) yield once per arm."""
    func = node.func
    # Timer(registry, "name")
    if isinstance(func, ast.Name) and func.id == "Timer" and len(node.args) >= 2:
        for name in _const_strings(node.args[1]):
            yield "timer", name, node
        return
    if not isinstance(func, ast.Attribute) or func.attr not in _EMITTERS:
        return
    if not node.args:
        return
    names = _const_strings(node.args[0])
    receiver = dotted_name(func.value) or ""
    for name in names:
        # only metric-shaped literals (avoids unrelated .observe/.inc APIs)
        if name.startswith("finchat_") or "metrics" in receiver.lower() or receiver == "METRICS":
            yield func.attr, name, node


def _const_strings(expr: ast.AST) -> list[str]:
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        return [expr.value]
    if isinstance(expr, ast.IfExp):
        return _const_strings(expr.body) + _const_strings(expr.orelse)
    return []
