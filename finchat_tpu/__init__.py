"""finchat_tpu — a TPU-native streaming RAG agent framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of
``kyshu11027/financial-chatbot-llm`` (the Kafka-driven "Penny" financial
chatbot): the external Gemini/OpenAI API calls in the reference
(``llm_agent.py:34-45``, ``tools/qdrant_tool.py:28``) are replaced by an
in-tree TPU inference stack — a pjit'd autoregressive decode engine with
Pallas flash/paged attention, a paged KV cache, a continuous-batching
scheduler fed by the Kafka consumer, and a TPU-batched embedding encoder
backing an on-device vector index.

Subpackages
-----------
- ``utils``    config (env-compatible with reference ``config.py``), logging,
               metrics, tracing.
- ``io``       message transport (Kafka semantics) + document store (Mongo
               semantics) + wire schemas (reference ``main.py:86-121``).
- ``models``   Llama-family decoder and BERT-family encoder in pure JAX.
- ``ops``      Pallas TPU kernels (flash attention, paged decode attention,
               ring attention) with jnp reference implementations.
- ``parallel`` device mesh construction, sharding rules, multi-host init.
- ``engine``   paged KV cache, sampler, prefill/decode step functions,
               continuous-batching scheduler, streaming generators.
- ``embed``    TPU-batched embedding encoder + on-device vector index.
- ``agent``    the 3-node agent graph (decide → retrieve → generate) and the
               streaming event protocol (reference ``llm_agent.py:57-79``).
- ``tools``    retrieve_transactions + create_financial_plot.
- ``serve``    stdlib asyncio HTTP server (/health, /chat, /metrics) and the
               Kafka worker loop (reference ``main.py``).
- ``checkpoints`` HF safetensors → sharded jax params.
- ``train``    training step (CE loss + optax) sharded over the same mesh.
"""

__version__ = "0.1.0"
