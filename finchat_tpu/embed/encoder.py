"""TPU-batched embedding encoder (BERT/bge family).

Replaces the reference's OpenAI embeddings API call
(``tools/qdrant_tool.py:28,137``) with an in-tree bidirectional encoder:
token+position embeddings → post-LN transformer stack → pooling (CLS for the
bge recipe, masked mean as an option) → L2 normalization. Layer semantics
match HuggingFace ``BertModel`` (biases everywhere, exact GELU, token-type
row 0 folded into the position table) so real bge-base-en checkpoints load
via ``checkpoints/bert_loader.py`` and reproduce HF outputs — see
tests/test_bert_loader.py for the torch parity proof. Queries are batched
and padded to fixed buckets so the encoder is one compiled function per
bucket (no recompiles per request), and upserts ride the same batched path.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import Array

from finchat_tpu.models.quant import dense as quant_dense
from finchat_tpu.models.quant import quantize_stacked
from finchat_tpu.models.tokenizer import Tokenizer
from finchat_tpu.ops.refs import mha_reference

# the encoder's matmul leaves — what int8 weight-only quantization covers
# (embeddings are gathers, LayerNorm scales/biases are precision-sensitive
# and tiny; biases ride unquantized like the decoder's norms)
BERT_QUANT_LEAVES = ("qkv", "attn_out", "mlp_in", "mlp_out")


def quantize_bert_params(params: dict[str, Any]) -> dict[str, Any]:
    """Int8-quantize the encoder's stacked matmul weights (ISSUE 14): the
    SAME ``QTensor`` machinery as the decoder (models/quant.py — per-slice
    ``quantize_stacked``, per-output-column scales, inline dequant fused
    into the dot), so the retrieval plane rides the serving quant mode.
    Idempotent on already-quantized trees."""
    from finchat_tpu.models.quant import Q4Tensor, QTensor

    layers = {
        name: (leaf if isinstance(leaf, (QTensor, Q4Tensor))
               or name not in BERT_QUANT_LEAVES
               else quantize_stacked(leaf))
        for name, leaf in params["layers"].items()
    }
    return {**params, "layers": layers}


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 260
    dim: int = 64
    n_layers: int = 2
    n_heads: int = 4
    hidden_dim: int = 128
    max_position: int = 512
    norm_eps: float = 1e-12
    dtype: Any = jnp.bfloat16
    pooling: str = "mean"  # "mean" | "cls" (bge uses CLS)

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


EMBED_PRESETS: dict[str, BertConfig] = {
    # byte-vocab debug/bench encoder
    "bge-tiny": BertConfig(),
    # bge-base-en architecture (BAAI/bge-base-en-v1.5 card): BERT-base,
    # CLS pooling + L2 norm
    "bge-base-en": BertConfig(
        vocab_size=30_522, dim=768, n_layers=12, n_heads=12, hidden_dim=3072,
        max_position=512, pooling="cls",
    ),
}


def init_bert_params(config: BertConfig, key: Array) -> dict[str, Any]:
    c = config
    keys = jax.random.split(key, 8)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) * fan_in ** -0.5).astype(c.dtype)

    L, D, F = c.n_layers, c.dim, c.hidden_dim
    return {
        "tok_embed": dense(keys[0], (c.vocab_size, D), D),
        "pos_embed": dense(keys[1], (c.max_position, D), D),
        "embed_ln_scale": jnp.ones((D,), c.dtype),
        "embed_ln_bias": jnp.zeros((D,), c.dtype),
        "layers": {
            "qkv": dense(keys[2], (L, D, 3 * D), D),
            "qkv_bias": jnp.zeros((L, 3 * D), c.dtype),
            "attn_out": dense(keys[3], (L, D, D), D),
            "attn_out_bias": jnp.zeros((L, D), c.dtype),
            "ln1_scale": jnp.ones((L, D), c.dtype),
            "ln1_bias": jnp.zeros((L, D), c.dtype),
            "mlp_in": dense(keys[4], (L, D, F), D),
            "mlp_in_bias": jnp.zeros((L, F), c.dtype),
            "mlp_out": dense(keys[5], (L, F, D), F),
            "mlp_out_bias": jnp.zeros((L, D), c.dtype),
            "ln2_scale": jnp.ones((L, D), c.dtype),
            "ln2_bias": jnp.zeros((L, D), c.dtype),
        },
    }


def _layer_norm(x: Array, scale: Array, bias: Array, eps: float) -> Array:
    x32 = x.astype(jnp.float32)
    mean = x32.mean(axis=-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(axis=-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return out.astype(x.dtype) * scale + bias


@partial(jax.jit, static_argnames=("config", "qm_backend"))
def encode_batch(
    params: dict[str, Any],
    tokens: Array,  # [B, S] int32 (right-padded)
    lengths: Array,  # [B] int32 valid lengths
    *,
    config: BertConfig,
    qm_backend: str = "ref",
) -> Array:
    """Encode a padded batch → L2-normalized embeddings [B, dim] fp32."""
    c = config
    B, S = tokens.shape
    x = params["tok_embed"][tokens] + params["pos_embed"][:S][None, :, :]
    x = _layer_norm(x, params["embed_ln_scale"], params["embed_ln_bias"], c.norm_eps)

    valid = (jnp.arange(S)[None, :] < lengths[:, None])  # [B, S]

    def body(x, layer):
        # quant_dense = plain ``x @ w`` on unquantized leaves; QTensor
        # leaves (the embed.quant path, quantize_bert_params) route via
        # ops/dispatch.quant_matmul — the inline-dequant reference on
        # CPU, the fused packed-read Pallas kernel under qm_backend
        qkv = quant_dense(x, layer["qkv"], qm_backend=qm_backend) + layer["qkv_bias"]  # [B,S,3D]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, S, c.n_heads, c.head_dim)
        k = k.reshape(B, S, c.n_heads, c.head_dim)
        v = v.reshape(B, S, c.n_heads, c.head_dim)
        attn = mha_reference(q, k, v, causal=False, kv_len=lengths)
        x = _layer_norm(
            x + quant_dense(attn.reshape(B, S, -1), layer["attn_out"],
                            qm_backend=qm_backend)
            + layer["attn_out_bias"],
            layer["ln1_scale"], layer["ln1_bias"], c.norm_eps,
        )
        # exact (erf) GELU — what BERT/bge checkpoints were trained with
        h = jax.nn.gelu(
            (quant_dense(x, layer["mlp_in"], qm_backend=qm_backend)
             + layer["mlp_in_bias"]).astype(jnp.float32),
            approximate=False,
        ).astype(x.dtype)
        x = _layer_norm(
            x + quant_dense(h, layer["mlp_out"], qm_backend=qm_backend)
            + layer["mlp_out_bias"],
            layer["ln2_scale"], layer["ln2_bias"], c.norm_eps,
        )
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])

    if c.pooling == "cls":
        pooled = x[:, 0, :].astype(jnp.float32)
    else:  # masked mean
        mask = valid[:, :, None].astype(jnp.float32)
        pooled = (x.astype(jnp.float32) * mask).sum(axis=1) / jnp.maximum(mask.sum(axis=1), 1.0)
    return pooled / jnp.maximum(jnp.linalg.norm(pooled, axis=-1, keepdims=True), 1e-9)


_BUCKETS = (32, 64, 128, 256, 512)


class EmbeddingEncoder:
    """Host-side wrapper: tokenize, bucket-pad, encode on device.

    ``batch_size`` (EmbedConfig.batch_size) caps the rows per device call so
    a 10k-row ingest doesn't materialize one giant activation tensor.
    """

    def __init__(self, config: BertConfig, params: dict[str, Any], tokenizer: Tokenizer,
                 *, batch_size: int = 64, quant: str = ""):
        if quant and quant != "int8":
            raise ValueError(
                f"unknown embed quant mode {quant!r} (supported: 'int8')"
            )
        self.config = config
        # embed.quant: the retrieval plane rides the serving quant mode —
        # int8 weight-only via the decoder's QTensor machinery (ISSUE 14);
        # quality gate: quantized-vs-fp32 top-k overlap >= 0.99
        # (tests/test_quant_serving.py, bench --quant-sweep)
        self.params = quantize_bert_params(params) if quant else params
        self.quant = quant
        # resolve the fused-matmul backend ONCE (ops/dispatch discipline:
        # env must not be read inside the jitted encode); unquantized
        # encoders pin "ref" so they don't add a compiled variant per env
        if quant:
            from finchat_tpu.ops.dispatch import quant_matmul_backend

            self.qm_backend = quant_matmul_backend()
        else:
            self.qm_backend = "ref"
        self.tokenizer = tokenizer
        self.batch_size = batch_size

    @property
    def dim(self) -> int:
        return self.config.dim

    def _bucket(self, n: int) -> int:
        for b in _BUCKETS:
            if n <= b and b <= self.config.max_position:
                return b
        return min(_BUCKETS[-1], self.config.max_position)

    def embed_batch(self, texts: list[str]) -> np.ndarray:
        """Embed texts → [n, dim] fp32 numpy (one device call per micro-batch)."""
        out = np.empty((len(texts), self.dim), np.float32)
        for lo in range(0, len(texts), self.batch_size):
            out[lo : lo + self.batch_size] = self._embed_micro(texts[lo : lo + self.batch_size])
        return out

    def _embed_micro(self, texts: list[str]) -> np.ndarray:
        encode = getattr(self.tokenizer, "encode_with_specials", self.tokenizer.encode)
        ids = [encode(t)[: self.config.max_position] for t in texts]
        lengths = [max(1, len(i)) for i in ids]
        bucket = self._bucket(max(lengths))
        padded = np.zeros((len(ids), bucket), np.int32)
        for row, seq in enumerate(ids):
            padded[row, : len(seq)] = seq[:bucket]
        out = encode_batch(
            self.params, jnp.asarray(padded), jnp.asarray(lengths, jnp.int32),
            config=self.config, qm_backend=self.qm_backend,
        )
        return np.asarray(out)

    def embed_query(self, text: str) -> np.ndarray:
        return self.embed_batch([text])[0]
