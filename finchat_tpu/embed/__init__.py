from finchat_tpu.embed.batcher import EmbedMicrobatcher
from finchat_tpu.embed.encoder import BertConfig, EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex, QuerySpec, VectorPoint

__all__ = [
    "BertConfig",
    "EMBED_PRESETS",
    "EmbedMicrobatcher",
    "EmbeddingEncoder",
    "init_bert_params",
    "DeviceVectorIndex",
    "QuerySpec",
    "VectorPoint",
]
