from finchat_tpu.embed.encoder import BertConfig, EMBED_PRESETS, EmbeddingEncoder, init_bert_params
from finchat_tpu.embed.index import DeviceVectorIndex, VectorPoint

__all__ = [
    "BertConfig",
    "EMBED_PRESETS",
    "EmbeddingEncoder",
    "init_bert_params",
    "DeviceVectorIndex",
    "VectorPoint",
]
