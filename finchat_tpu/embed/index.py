"""On-device vector index.

The reference searches a remote Qdrant over HNSW (``tools/qdrant_tool.py``).
The TPU-native default is exact brute-force cosine on the MXU: one
``scores = V @ q`` matmul over the whole collection per query — for the
collection sizes this product sees (per-user bank transactions), exact
search on-device beats a network round-trip to an approximate index, and
security filtering stays in-process.

Data model parity (SURVEY §2.4): points carry payload
``{page_content: str, metadata: {user_id, date: unix-ts, ...}}``; filters
are ``must user_id == X`` plus optional ``metadata.date >= now - N days``
(qdrant_tool.py:105-126).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from functools import partial
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class VectorPoint:
    id: str
    vector: np.ndarray  # [dim] fp32 (normalized or not; scoring normalizes)
    payload: dict[str, Any] = field(default_factory=dict)

    @property
    def metadata(self) -> dict[str, Any]:
        return self.payload.get("metadata", {}) or {}


@partial(jax.jit, static_argnames=("k",))
def _topk_scores(vectors: jnp.ndarray, mask: jnp.ndarray, query: jnp.ndarray, *, k: int):
    """scores = V·q with invalid rows masked to -inf; returns (scores, idx)."""
    scores = vectors @ query  # [N] — the MXU does the work
    scores = jnp.where(mask, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


class DeviceVectorIndex:
    """Append-mostly vector store with device-side scoring.

    Host keeps payloads + filter columns (user_id, date) as numpy; the
    device keeps a padded, L2-normalized matrix [capacity, dim]. Capacity
    doubles on overflow (re-upload); deletes are tombstones.
    """

    def __init__(self, dim: int, initial_capacity: int = 1024):
        self.dim = dim
        self._lock = threading.Lock()
        self._capacity = initial_capacity
        self._count = 0
        self._points: list[VectorPoint] = []
        self._user_ids: list[str] = []
        self._dates: np.ndarray = np.zeros((initial_capacity,), np.float64)
        self._alive: np.ndarray = np.zeros((initial_capacity,), bool)
        self._host_vectors = np.zeros((initial_capacity, dim), np.float32)
        self._device_vectors = jnp.zeros((initial_capacity, dim), jnp.float32)
        self._dirty = False

    def __len__(self) -> int:
        return sum(self._alive[: self._count])

    @staticmethod
    def _normalize(v: np.ndarray) -> np.ndarray:
        norm = np.linalg.norm(v, axis=-1, keepdims=True)
        return v / np.maximum(norm, 1e-9)

    def _grow(self, needed: int) -> None:
        new_cap = self._capacity
        while new_cap < needed:
            new_cap *= 2
        pad = new_cap - self._capacity
        self._host_vectors = np.concatenate([self._host_vectors, np.zeros((pad, self.dim), np.float32)])
        self._dates = np.concatenate([self._dates, np.zeros((pad,), np.float64)])
        self._alive = np.concatenate([self._alive, np.zeros((pad,), bool)])
        self._capacity = new_cap

    def upsert(self, points: list[VectorPoint]) -> None:
        with self._lock:
            if self._count + len(points) > self._capacity:
                self._grow(self._count + len(points))
            for p in points:
                row = self._count
                self._host_vectors[row] = self._normalize(np.asarray(p.vector, np.float32))
                self._dates[row] = float(p.metadata.get("date", 0) or 0)
                self._alive[row] = True
                self._points.append(p)
                self._user_ids.append(str(p.metadata.get("user_id", "")))
                self._count += 1
            self._dirty = True

    def _sync_device(self) -> None:
        if self._dirty:
            self._device_vectors = jnp.asarray(self._host_vectors)
            self._dirty = False

    def query_points(
        self,
        query_vector: np.ndarray,
        *,
        limit: int,
        user_id: str | None = None,
        date_gte: float | None = None,
    ) -> list[VectorPoint]:
        """Top-``limit`` cosine matches under the must-filters, best first."""
        with self._lock:
            if self._count == 0:
                return []
            self._sync_device()
            mask = self._alive[: self._capacity].copy()
            mask[self._count :] = False
            if user_id is not None:
                uid = np.asarray(self._user_ids) == user_id
                mask[: self._count] &= uid
            if date_gte is not None:
                mask[: self._count] &= self._dates[: self._count] >= date_gte
            if not mask.any():
                return []
            q = self._normalize(np.asarray(query_vector, np.float32))
            k = min(limit, self._capacity)
            scores, idx = _topk_scores(self._device_vectors, jnp.asarray(mask), jnp.asarray(q), k=k)
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            out: list[VectorPoint] = []
            for s, i in zip(scores, idx):
                if not np.isfinite(s):
                    break
                out.append(self._points[int(i)])
            return out

    # --- durability (VERDICT r1 task 5) ---------------------------------
    # The reference's collection lives in an external, durable Qdrant
    # (qdrant_tool.py:24-37); the on-device index persists to a local
    # snapshot instead so retrieval is not empty-at-boot.

    def save(self, path: str) -> None:
        """Atomic snapshot: vectors as .npz, payloads as .jsonl sidecar."""
        with self._lock:
            n = self._count
            base = Path(path)
            base.parent.mkdir(parents=True, exist_ok=True)
            # np.savez appends ".npz" unless the name already ends with it
            tmp_vec = str(base) + ".tmp.npz"
            np.savez_compressed(
                tmp_vec,
                vectors=self._host_vectors[:n],
                dates=self._dates[:n],
                alive=self._alive[:n],
            )
            tmp_pay = str(base) + ".jsonl.tmp"
            with open(tmp_pay, "w") as f:
                for p in self._points:
                    f.write(json.dumps({"id": p.id, "payload": p.payload}) + "\n")
            os.replace(tmp_vec, str(base) + ".npz")
            os.replace(tmp_pay, str(base) + ".jsonl")
        logger.info("vector index saved: %d points -> %s.{npz,jsonl}", n, path)

    @classmethod
    def load(cls, path: str, dim: int) -> "DeviceVectorIndex":
        """Restore a snapshot; a missing snapshot yields an empty index."""
        base = Path(path)
        vec_file, pay_file = Path(str(base) + ".npz"), Path(str(base) + ".jsonl")
        index = cls(dim=dim)
        if not (vec_file.exists() and pay_file.exists()):
            logger.info("no vector snapshot at %s; starting empty", path)
            return index
        data = np.load(vec_file)
        vectors, dates, alive = data["vectors"], data["dates"], data["alive"]
        with open(pay_file) as f:
            records = [json.loads(line) for line in f]
        if len(records) != len(vectors):
            # a crash between the two os.replace calls in save() can tear
            # the snapshot; fail with a clear message, not an IndexError
            raise ValueError(
                f"snapshot mismatch at {path}: {len(vectors)} vectors vs "
                f"{len(records)} payloads (torn snapshot?)"
            )
        points = [
            VectorPoint(id=rec["id"], vector=vectors[row], payload=rec["payload"])
            for row, rec in enumerate(records)
        ]
        index.upsert(points)
        # restore tombstones + original dates exactly
        index._alive[: len(points)] = alive
        index._dates[: len(points)] = dates
        logger.info("vector index restored: %d points from %s", len(points), path)
        return index
