"""On-device vector index.

The reference searches a remote Qdrant over HNSW (``tools/qdrant_tool.py``).
The TPU-native default is exact brute-force cosine on the MXU: one
``scores = V @ Q^T`` matmul over the whole collection per dispatch — for the
collection sizes this product sees (per-user bank transactions), exact
search on-device beats a network round-trip to an approximate index, and
security filtering stays in-process.

Two query planes, golden-equivalent (tests/test_retrieval_plane.py):

- ``query_points`` — the serial host-mask path: the boolean filter mask is
  built in numpy per query, then one ``V @ q`` scoring dispatch. Kept as
  the reference implementation and fallback.
- ``query_points_batch`` — the batched device-filter path the retrieval
  plane uses: B queries score in ONE ``V @ Q^T`` dispatch, and the
  must-filters (user_id equality, date >= bound) evaluate ON DEVICE
  against int-coded filter columns (interned user codes + dates) that
  live device-resident and are maintained incrementally on upsert — no
  per-query host mask rebuild, no whole-matrix re-upload when new rows
  land (``dynamic_update_slice`` splices just the new rows).

Data model parity (SURVEY §2.4): points carry payload
``{page_content: str, metadata: {user_id, date: unix-ts, ...}}``; filters
are ``must user_id == X`` plus optional ``metadata.date >= now - N days``
(qdrant_tool.py:105-126).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from functools import partial
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# user-code sentinels for the device-side filter: NO_FILTER matches every
# row; NO_MATCH (an unknown user_id — no row can carry it) matches none
NO_FILTER_CODE = -1
NO_MATCH_CODE = -2


@dataclass
class VectorPoint:
    id: str
    vector: np.ndarray  # [dim] fp32 (normalized or not; scoring normalizes)
    payload: dict[str, Any] = field(default_factory=dict)

    @property
    def metadata(self) -> dict[str, Any]:
        return self.payload.get("metadata", {}) or {}


@dataclass(frozen=True)
class QuerySpec:
    """One query of a batched ``query_points_batch`` call."""

    vector: np.ndarray
    limit: int
    user_id: str | None = None
    date_gte: float | None = None


@partial(jax.jit, static_argnames=("k",))
def _topk_scores(vectors: jnp.ndarray, mask: jnp.ndarray, query: jnp.ndarray, *, k: int):
    """scores = V·q with invalid rows masked to -inf; returns (scores, idx)."""
    scores = vectors @ query  # [N] — the MXU does the work
    scores = jnp.where(mask, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _split_f64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Double-single split: float64 → (hi, lo) float32 pair with
    ``hi + lo == x`` to ~48-bit precision. Unix timestamps (~2^31 s) are
    far beyond float32's 24-bit mantissa (128 s spacing at current
    epoch), so a single-f32 date column would mis-filter rows within
    ~2 min of the cutoff where the serial float64 host path classifies
    them exactly; the lexicographic (hi, lo) compare below keeps the
    batched plane golden-equivalent down to sub-millisecond date
    resolution."""
    x = np.asarray(x, np.float64)
    hi = x.astype(np.float32)
    # -inf encodes "no date filter": its hi alone decides every compare,
    # so pin lo to 0 there (inf - inf would be NaN)
    finite = np.isfinite(x)
    lo = np.zeros_like(x)
    np.subtract(x, hi.astype(np.float64), out=lo, where=finite)
    return hi, lo.astype(np.float32)


@partial(jax.jit, static_argnames=("k",))
def _topk_scores_batch(
    vectors: jnp.ndarray,      # [N, dim] fp32
    alive: jnp.ndarray,        # [N] bool
    user_codes: jnp.ndarray,   # [N] int32 (interned user ids)
    dates_hi: jnp.ndarray,     # [N] fp32 unix ts (double-single hi)
    dates_lo: jnp.ndarray,     # [N] fp32 unix ts (double-single lo)
    q: jnp.ndarray,            # [B, dim] fp32 (rows L2-normalized)
    q_codes: jnp.ndarray,      # [B] int32 (NO_FILTER_CODE = no user filter)
    q_date_hi: jnp.ndarray,    # [B] fp32 (-inf = no date filter)
    q_date_lo: jnp.ndarray,    # [B] fp32
    *,
    k: int,
):
    """B queries in one dispatch: scores = V @ Q^T with the must-filter
    masks built ON DEVICE from the resident filter columns (no host-side
    mask rebuild per query); returns ([B, k] scores, [B, k] idx)."""
    scores = (vectors @ q.T).T  # [B, N]
    user_ok = (q_codes[:, None] == NO_FILTER_CODE) | (
        user_codes[None, :] == q_codes[:, None]
    )
    # date >= cutoff, exact over the double-single pairs: lexicographic on
    # (hi, lo) — valid because both sides come from the same split
    hi_n, lo_n = dates_hi[None, :], dates_lo[None, :]
    hi_q, lo_q = q_date_hi[:, None], q_date_lo[:, None]
    date_ok = (hi_n > hi_q) | ((hi_n == hi_q) & (lo_n >= lo_q))
    mask = alive[None, :] & user_ok & date_ok  # [B, N]
    scores = jnp.where(mask, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


@partial(jax.jit, donate_argnums=(0,))
def _splice_rows(dst: jnp.ndarray, rows: jnp.ndarray, start: jnp.ndarray):
    """Incremental device upload: write ``rows`` into ``dst`` at row
    ``start`` in place (donated) — upserting M new rows moves M·dim
    floats host→device instead of re-uploading the whole matrix."""
    return jax.lax.dynamic_update_slice(dst, rows, (start,) + (0,) * (dst.ndim - 1))


class DeviceVectorIndex:
    """Append-mostly vector store with device-side scoring.

    Host keeps payloads + filter columns (user_id, date) as numpy; the
    device keeps a padded, L2-normalized matrix [capacity, dim] plus the
    int-coded filter columns. Capacity doubles on overflow (full
    re-upload); within a capacity, new rows splice in incrementally.
    Deletes are tombstones.
    """

    def __init__(self, dim: int, initial_capacity: int = 1024):
        self.dim = dim
        self._lock = threading.Lock()
        # serializes whole snapshots against each other (two concurrent
        # save() calls would race on the same .tmp paths) without making
        # queries wait on compression/file IO
        self._save_lock = threading.Lock()
        self._capacity = initial_capacity
        self._count = 0
        self._points: list[VectorPoint] = []
        self._user_ids: list[str] = []
        # interned user codes: one int per distinct user_id, maintained
        # incrementally on upsert so no query path ever rebuilds an array
        # from the Python string list
        self._user_interner: dict[str, int] = {}
        self._user_codes: np.ndarray = np.full((initial_capacity,), NO_MATCH_CODE, np.int32)
        self._dates: np.ndarray = np.zeros((initial_capacity,), np.float64)
        self._alive: np.ndarray = np.zeros((initial_capacity,), bool)
        self._host_vectors = np.zeros((initial_capacity, dim), np.float32)
        self._device_vectors = jnp.zeros((initial_capacity, dim), jnp.float32)
        self._device_alive = jnp.zeros((initial_capacity,), bool)
        self._device_user_codes = jnp.full((initial_capacity,), NO_MATCH_CODE, jnp.int32)
        # dates as a double-single (hi, lo) float32 pair — see _split_f64
        self._device_dates_hi = jnp.zeros((initial_capacity,), jnp.float32)
        self._device_dates_lo = jnp.zeros((initial_capacity,), jnp.float32)
        self._synced_rows = 0   # device rows that mirror the host arrays
        self._full_dirty = False  # growth / external mutation: re-upload all

    def __len__(self) -> int:
        return sum(self._alive[: self._count])

    @staticmethod
    def _normalize(v: np.ndarray) -> np.ndarray:
        norm = np.linalg.norm(v, axis=-1, keepdims=True)
        return v / np.maximum(norm, 1e-9)

    def _intern(self, user_id: str) -> int:
        code = self._user_interner.get(user_id)
        if code is None:
            code = len(self._user_interner)
            self._user_interner[user_id] = code
        return code

    def _grow(self, needed: int) -> None:
        new_cap = self._capacity
        while new_cap < needed:
            new_cap *= 2
        pad = new_cap - self._capacity
        self._host_vectors = np.concatenate([self._host_vectors, np.zeros((pad, self.dim), np.float32)])
        self._dates = np.concatenate([self._dates, np.zeros((pad,), np.float64)])
        self._alive = np.concatenate([self._alive, np.zeros((pad,), bool)])
        self._user_codes = np.concatenate(
            [self._user_codes, np.full((pad,), NO_MATCH_CODE, np.int32)]
        )
        self._capacity = new_cap
        self._full_dirty = True  # device arrays must be rebuilt at new shape

    def upsert(self, points: list[VectorPoint]) -> None:
        with self._lock:
            if self._count + len(points) > self._capacity:
                self._grow(self._count + len(points))
            for p in points:
                row = self._count
                self._host_vectors[row] = self._normalize(np.asarray(p.vector, np.float32))
                self._dates[row] = float(p.metadata.get("date", 0) or 0)
                self._alive[row] = True
                self._points.append(p)
                uid = str(p.metadata.get("user_id", ""))
                self._user_ids.append(uid)
                self._user_codes[row] = self._intern(uid)
                self._count += 1

    def _sync_device(self) -> None:
        """Bring the device arrays up to date with the host arrays. Full
        re-upload only on growth/external mutation; the steady-state ingest
        path splices just the rows added since the last sync."""
        if self._full_dirty:
            hi, lo = _split_f64(self._dates)
            self._device_vectors = jnp.asarray(self._host_vectors)
            self._device_alive = jnp.asarray(self._alive)
            self._device_user_codes = jnp.asarray(self._user_codes)
            self._device_dates_hi = jnp.asarray(hi)
            self._device_dates_lo = jnp.asarray(lo)
            self._synced_rows = self._count
            self._full_dirty = False
            return
        lo, hi = self._synced_rows, self._count
        if lo >= hi:
            return
        # pad the splice to a power-of-two row count (clamped to capacity)
        # so streaming ingest compiles at most log2(capacity) splice
        # variants; the padding rows carry host truth, so overwriting them
        # is idempotent
        padded_hi = min(lo + self._query_bucket(hi - lo), self._capacity)
        start = jnp.int32(lo)
        self._device_vectors = _splice_rows(
            self._device_vectors, jnp.asarray(self._host_vectors[lo:padded_hi]), start
        )
        self._device_alive = _splice_rows(
            self._device_alive, jnp.asarray(self._alive[lo:padded_hi]), start
        )
        self._device_user_codes = _splice_rows(
            self._device_user_codes, jnp.asarray(self._user_codes[lo:padded_hi]), start
        )
        d_hi, d_lo = _split_f64(self._dates[lo:padded_hi])
        self._device_dates_hi = _splice_rows(self._device_dates_hi, jnp.asarray(d_hi), start)
        self._device_dates_lo = _splice_rows(self._device_dates_lo, jnp.asarray(d_lo), start)
        self._synced_rows = hi

    def query_points(
        self,
        query_vector: np.ndarray,
        *,
        limit: int,
        user_id: str | None = None,
        date_gte: float | None = None,
    ) -> list[VectorPoint]:
        """Top-``limit`` cosine matches under the must-filters, best first.

        Serial host-mask path: the filter mask builds in numpy (from the
        incrementally-maintained code column, not the Python list), then
        one single-query scoring dispatch. The batched device-filter plane
        (``query_points_batch``) must stay golden-equivalent to this."""
        with self._lock:
            if self._count == 0:
                return []
            self._sync_device()
            mask = self._alive[: self._capacity].copy()
            mask[self._count :] = False
            if user_id is not None:
                code = self._user_interner.get(user_id, NO_MATCH_CODE)
                mask[: self._count] &= self._user_codes[: self._count] == code
            if date_gte is not None:
                mask[: self._count] &= self._dates[: self._count] >= date_gte
            if not mask.any():
                return []
            q = self._normalize(np.asarray(query_vector, np.float32))
            k = min(limit, self._capacity)
            scores, idx = _topk_scores(self._device_vectors, jnp.asarray(mask), jnp.asarray(q), k=k)
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            out: list[VectorPoint] = []
            for s, i in zip(scores, idx):
                if not np.isfinite(s):
                    break
                out.append(self._points[int(i)])
            return out

    @staticmethod
    def _query_bucket(n: int) -> int:
        b = 1
        while b < n:
            b *= 2
        return b

    def query_points_batch(self, queries: list[QuerySpec]) -> list[list[VectorPoint]]:
        """Top-k for B queries in ONE device dispatch (``V @ Q^T`` scoring,
        on-device must-filter masks). The query batch pads to a power of
        two so concurrent fan-in compiles at most log2 variants per
        (capacity, k) pair. Returns one best-first hit list per query,
        golden-equivalent to ``query_points`` run serially."""
        if not queries:
            return []
        with self._lock:
            if self._count == 0:
                return [[] for _ in queries]
            self._sync_device()
            B = self._query_bucket(len(queries))
            q = np.zeros((B, self.dim), np.float32)
            q_codes = np.full((B,), NO_MATCH_CODE, np.int32)  # padding matches nothing
            q_dates = np.full((B,), -np.inf, np.float64)
            limits = []
            for i, spec in enumerate(queries):
                q[i] = self._normalize(np.asarray(spec.vector, np.float32))
                if spec.user_id is None:
                    q_codes[i] = NO_FILTER_CODE
                else:
                    q_codes[i] = self._user_interner.get(spec.user_id, NO_MATCH_CODE)
                if spec.date_gte is not None:
                    q_dates[i] = spec.date_gte
                limits.append(min(int(spec.limit), self._capacity))
            k = max(limits)
            q_hi, q_lo = _split_f64(q_dates)
            scores, idx = _topk_scores_batch(
                self._device_vectors, self._device_alive,
                self._device_user_codes, self._device_dates_hi, self._device_dates_lo,
                jnp.asarray(q), jnp.asarray(q_codes),
                jnp.asarray(q_hi), jnp.asarray(q_lo),
                k=k,
            )
            scores = np.asarray(scores)
            idx = np.asarray(idx)
            results: list[list[VectorPoint]] = []
            for i in range(len(queries)):
                out: list[VectorPoint] = []
                for s, j in zip(scores[i, : limits[i]], idx[i, : limits[i]]):
                    if not np.isfinite(s):
                        break
                    out.append(self._points[int(j)])
                results.append(out)
            return results

    # --- durability (VERDICT r1 task 5) ---------------------------------
    # The reference's collection lives in an external, durable Qdrant
    # (qdrant_tool.py:24-37); the on-device index persists to a local
    # snapshot instead so retrieval is not empty-at-boot.

    def save(self, path: str) -> None:
        """Atomic snapshot: vectors as .npz, payloads as .jsonl sidecar.

        ``_lock`` is held only long enough to COPY the arrays and payload
        refs — compression and file IO run outside it, so a snapshot never
        stalls concurrent queries/upserts for the write's duration.
        ``_save_lock`` serializes overlapping save() calls (debounced
        ingest persist racing a forced shutdown persist), which would
        otherwise interleave writes to the same .tmp files."""
        with self._save_lock:
            with self._lock:
                n = self._count
                vectors = self._host_vectors[:n].copy()
                dates = self._dates[:n].copy()
                alive = self._alive[:n].copy()
                points = list(self._points)
            base = Path(path)
            base.parent.mkdir(parents=True, exist_ok=True)
            # np.savez appends ".npz" unless the name already ends with it
            tmp_vec = str(base) + ".tmp.npz"
            np.savez_compressed(tmp_vec, vectors=vectors, dates=dates, alive=alive)
            tmp_pay = str(base) + ".jsonl.tmp"
            with open(tmp_pay, "w") as f:
                for p in points:
                    f.write(json.dumps({"id": p.id, "payload": p.payload}) + "\n")
            os.replace(tmp_vec, str(base) + ".npz")
            os.replace(tmp_pay, str(base) + ".jsonl")
        logger.info("vector index saved: %d points -> %s.{npz,jsonl}", n, path)

    @classmethod
    def load(cls, path: str, dim: int) -> "DeviceVectorIndex":
        """Restore a snapshot; a missing snapshot yields an empty index."""
        base = Path(path)
        vec_file, pay_file = Path(str(base) + ".npz"), Path(str(base) + ".jsonl")
        index = cls(dim=dim)
        if not (vec_file.exists() and pay_file.exists()):
            logger.info("no vector snapshot at %s; starting empty", path)
            return index
        data = np.load(vec_file)
        vectors, dates, alive = data["vectors"], data["dates"], data["alive"]
        with open(pay_file) as f:  # finchat-lint: disable=event-loop-blocking -- startup snapshot load (build_app runs it before the loop serves); ingest-path saves already copy-then-write off-lock
            records = [json.loads(line) for line in f]
        if len(records) != len(vectors):
            # a crash between the two os.replace calls in save() can tear
            # the snapshot; fail with a clear message, not an IndexError
            raise ValueError(
                f"snapshot mismatch at {path}: {len(vectors)} vectors vs "
                f"{len(records)} payloads (torn snapshot?)"
            )
        points = [
            VectorPoint(id=rec["id"], vector=vectors[row], payload=rec["payload"])
            for row, rec in enumerate(records)
        ]
        index.upsert(points)
        # restore tombstones + original dates exactly; the device mirrors
        # are stale after this direct mutation — force a full re-upload
        index._alive[: len(points)] = alive
        index._dates[: len(points)] = dates
        index._full_dirty = True
        logger.info("vector index restored: %d points from %s", len(points), path)
        return index
