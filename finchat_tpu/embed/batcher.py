"""Cross-request embedding microbatcher.

Every chat message that needs retrieval pays one query-embed, and every
ingest pays one embed per row batch — before this plane existed, each ran
as its own batch-of-1 (or batch-of-ingest) ``encode_batch`` dispatch, so
concurrent traffic serialized N device dispatches where one would do
(ISSUE 3; the Conveyor/Kernel-Looping observation that the dispatch
boundary itself is the tax).

``EmbedMicrobatcher`` sits in front of :class:`EmbeddingEncoder` as an
async coalescing queue:

- a request enqueues its texts and awaits a future;
- the flusher wakes on the FIRST pending item, then waits up to
  ``window_ms`` for more arrivals (or until ``max_batch`` texts are
  pending) and dispatches ONE bucket-padded ``encode_batch`` for the
  whole bucket in a worker thread;
- results scatter back to the per-request futures.

Error isolation: a failed coalesced dispatch retries each REQUEST
individually, so one request's un-encodable text fails only its own
future, never its neighbors'. Backpressure: at ``max_pending`` queued
texts, submitters wait for the queue to drain before enqueueing (bounding
both memory and the tail latency an unbounded queue would hide).

Metrics: ``finchat_embed_batch_occupancy`` (gauge — texts in the last
dispatched bucket), ``finchat_embed_queue_depth`` (gauge),
``finchat_embed_batch_dispatches_total`` / ``finchat_embed_requests_total``
/ ``finchat_embed_texts_total`` (counters — dispatches/query is the
coalescing figure of merit), ``finchat_embed_wait_seconds`` (histogram —
time a request spends queued before its dispatch starts).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field

import numpy as np

from finchat_tpu.embed.encoder import EmbeddingEncoder
from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)


@dataclass
class _Pending:
    """One enqueued request: its texts and the future its rows resolve."""

    texts: list[str]
    future: asyncio.Future
    enqueued_at: float = field(default_factory=time.perf_counter)


class EmbedMicrobatcher:
    """Async coalescing queue in front of an EmbeddingEncoder.

    Lazily binds to the running event loop on first use (``embed`` /
    ``bind_loop``); ``embed_threadsafe`` lets worker threads (the ingest
    path runs under ``asyncio.to_thread``) ride the same coalescing
    window as event-loop queries.
    """

    def __init__(
        self,
        encoder: EmbeddingEncoder,
        *,
        window_ms: float = 3.0,
        max_batch: int = 32,
        max_pending: int | None = None,
    ):
        self.encoder = encoder
        self.window_s = max(0.0, window_ms) / 1000.0
        self.max_batch = max(1, max_batch)
        # backpressure bound: pending TEXTS (not requests) beyond which
        # submitters wait — 8 full buckets of headroom by default
        self.max_pending = max_pending if max_pending is not None else self.max_batch * 8
        self._queue: list[_Pending] = []
        self._pending_texts = 0
        self._loop: asyncio.AbstractEventLoop | None = None
        self._flusher: asyncio.Task | None = None
        self._arrival: asyncio.Event | None = None
        self._drained: asyncio.Event | None = None
        self._closed = False

    @property
    def dim(self) -> int:
        return self.encoder.dim

    # --- lifecycle ------------------------------------------------------
    def bind_loop(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        """Attach the flusher to ``loop`` (default: the running loop).
        Called at app startup; ``embed`` also self-binds on first use
        from a coroutine. A binding left over from a previous, now-dead
        loop (stop/start across asyncio.run — the scheduler supports the
        same restart shape) is replaced, so a restarted app embeds again
        instead of failing every retrieval."""
        target = loop or asyncio.get_running_loop()
        if self._loop is target and self._flusher is not None and not self._flusher.done():
            return
        if self._loop is not None and self._loop is not target and self._loop.is_running():
            raise RuntimeError("EmbedMicrobatcher is already bound to a live loop")
        self._loop = target
        self._arrival = asyncio.Event()
        self._drained = asyncio.Event()
        self._drained.set()
        self._closed = False
        self._flusher = self._loop.create_task(self._run())

    async def close(self) -> None:
        """Flush what's queued, then stop the flusher."""
        self._closed = True
        if self._flusher is not None:
            if self._arrival is not None:
                self._arrival.set()
            try:
                await self._flusher
            except asyncio.CancelledError:
                pass
            self._flusher = None
        if self._drained is not None:
            self._drained.set()  # wake backpressured submitters to fail fast

    # --- submission -----------------------------------------------------
    async def embed(self, texts: list[str]) -> np.ndarray:
        """Embed ``texts`` → [n, dim] fp32, coalesced with concurrent
        callers into shared ``encode_batch`` dispatches."""
        if not texts:
            return np.empty((0, self.encoder.dim), np.float32)
        if self._closed:
            raise RuntimeError("EmbedMicrobatcher is closed")
        # binds on first use; replaces a stale binding after a loop
        # restart; raises if another loop holds a LIVE binding (threads
        # must use embed_threadsafe)
        self.bind_loop()
        while self._pending_texts >= self.max_pending:  # backpressure
            self._drained.clear()
            await self._drained.wait()
            if self._closed:
                # close() drained the queue while this submitter was gated;
                # enqueueing now would strand a future no flusher will see
                raise RuntimeError("EmbedMicrobatcher closed while waiting")
        item = _Pending(list(texts), self._loop.create_future())
        self._queue.append(item)
        self._pending_texts += len(item.texts)
        METRICS.inc("finchat_embed_requests_total")
        METRICS.inc("finchat_embed_texts_total", len(item.texts))
        METRICS.set_gauge("finchat_embed_queue_depth", self._pending_texts)
        self._arrival.set()
        return await item.future

    async def embed_one(self, text: str) -> np.ndarray:
        return (await self.embed([text]))[0]

    def embed_threadsafe(self, texts: list[str], timeout: float | None = 120.0) -> np.ndarray:
        """Blocking submit from a worker thread (the ingest path), riding
        the same coalescing window as event-loop queries. Falls back to a
        direct encoder call when no loop is bound (tests, offline tools)
        or when called ON the loop's own thread (where blocking would
        deadlock the flusher)."""
        loop = self._loop
        if loop is None or self._closed or not loop.is_running():
            return self.encoder.embed_batch(texts)
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            return self.encoder.embed_batch(texts)
        fut = asyncio.run_coroutine_threadsafe(self.embed(texts), loop)
        return fut.result(timeout=timeout)

    # --- flusher --------------------------------------------------------
    async def _run(self) -> None:
        while True:
            if not self._queue:
                if self._closed:
                    return
                self._arrival.clear()
                try:
                    await asyncio.wait_for(self._arrival.wait(), timeout=0.5)
                except asyncio.TimeoutError:
                    pass
                continue  # re-check queue/closed at the top either way
            # wait-window: give concurrent callers up to window_s to land
            # in this bucket, unless a full bucket is already pending
            if self.window_s > 0 and not self._closed:
                deadline = self._queue[0].enqueued_at + self.window_s
                while self._pending_texts < self.max_batch:
                    now = time.perf_counter()
                    if now >= deadline:
                        break
                    self._arrival.clear()
                    try:
                        await asyncio.wait_for(
                            self._arrival.wait(), timeout=deadline - now
                        )
                    except asyncio.TimeoutError:
                        break
            # drain whole requests up to max_batch texts (never split one
            # request across buckets: scatter stays trivial and a request
            # is atomic for error isolation); always take at least one
            bucket: list[_Pending] = []
            n = 0
            while self._queue and (not bucket or n + len(self._queue[0].texts) <= self.max_batch):
                item = self._queue.pop(0)
                bucket.append(item)
                n += len(item.texts)
            self._pending_texts -= n
            METRICS.set_gauge("finchat_embed_queue_depth", self._pending_texts)
            if self._pending_texts < self.max_pending:
                self._drained.set()
            if bucket:
                await self._dispatch(bucket, n)

    async def _dispatch(self, bucket: list[_Pending], n: int) -> None:
        texts = [t for item in bucket for t in item.texts]
        now = time.perf_counter()
        for item in bucket:
            METRICS.observe("finchat_embed_wait_seconds", now - item.enqueued_at)
        METRICS.inc("finchat_embed_batch_dispatches_total")
        METRICS.set_gauge("finchat_embed_batch_occupancy", n)
        try:
            # armable fault site (ISSUE 5 satellite): a raised injection is
            # exactly a failed coalesced dispatch, driving the per-request
            # retry isolation below
            inject("embed.dispatch", n_texts=n)
            out = await asyncio.to_thread(self.encoder.embed_batch, texts)
        except Exception as batch_err:
            if len(bucket) == 1:
                self._fail(bucket[0], batch_err)
                return
            # error isolation: one request's bad text must not fail its
            # neighbors — retry each request as its own dispatch
            logger.warning(
                "coalesced embed batch of %d requests failed (%s); "
                "retrying per-request", len(bucket), batch_err,
            )
            METRICS.inc("finchat_embed_batch_retries_total")
            for item in bucket:
                try:
                    rows = await asyncio.to_thread(self.encoder.embed_batch, item.texts)
                except Exception as item_err:
                    self._fail(item, item_err)
                else:
                    self._resolve(item, rows)
            return
        lo = 0
        for item in bucket:
            self._resolve(item, out[lo : lo + len(item.texts)])
            lo += len(item.texts)

    @staticmethod
    def _resolve(item: _Pending, rows: np.ndarray) -> None:
        if not item.future.done():
            item.future.set_result(rows)

    @staticmethod
    def _fail(item: _Pending, err: Exception) -> None:
        METRICS.inc("finchat_embed_failures_total")
        if not item.future.done():
            item.future.set_exception(
                err if isinstance(err, Exception) else RuntimeError(str(err))
            )


__all__ = ["EmbedMicrobatcher"]
