"""HF BERT safetensors → embedding-encoder params.

The reference embeds with the OpenAI API (``tools/qdrant_tool.py:28,137``);
here the encoder is in-tree, and this loader brings in real weights
(bge-base-en-v1.5 and friends are plain HF ``BertModel`` checkpoints).
Wired to ``EmbedConfig.checkpoint_path`` in serve/app.py — without it
production retrieval would run on random embeddings (VERDICT r1 task 5).

Mapping to the layout of ``embed/encoder.py:init_bert_params``:

- per-layer q/k/v projections are fused into one ``qkv`` [D, 3D] matmul
  (and one bias) — a single MXU-friendly GEMM instead of three;
- every HF ``Linear`` weight is [out, in] and transposed to [in, out];
- the constant token-type-0 embedding row is folded into the position
  table (finetuned encoders are run with all-zero token types);
- the pooler head is dropped (bge pools CLS from the last hidden state).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax.numpy as jnp
import numpy as np

from finchat_tpu.embed.encoder import BertConfig
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def load_bert_params(checkpoint_dir: str, config: BertConfig) -> dict[str, Any]:
    from safetensors import safe_open

    path = Path(checkpoint_dir)
    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    tensors: dict[str, np.ndarray] = {}
    for file in files:
        with safe_open(str(file), framework="numpy") as shard:
            for name in shard.keys():
                # some exports prefix with "bert."
                tensors[name.removeprefix("bert.")] = shard.get_tensor(name)
    logger.info("read %d tensors from %s", len(tensors), path)

    cfg_file = path / "config.json"
    if cfg_file.exists():
        hf_cfg = json.loads(cfg_file.read_text())
        expected = {
            "hidden_size": config.dim,
            "num_hidden_layers": config.n_layers,
            "num_attention_heads": config.n_heads,
            "intermediate_size": config.hidden_dim,
            "vocab_size": config.vocab_size,
            "max_position_embeddings": config.max_position,
        }
        for hf_key, ours in expected.items():
            if hf_key in hf_cfg and hf_cfg[hf_key] != ours:
                raise ValueError(
                    f"checkpoint {hf_key}={hf_cfg[hf_key]} != config {ours}; wrong preset?"
                )

    dtype = config.dtype

    def put(array: np.ndarray) -> jnp.ndarray:
        return jnp.asarray(array, dtype=dtype)

    def stack(fmt: str, transpose: bool = True) -> np.ndarray:
        rows = []
        for i in range(config.n_layers):
            t = tensors[fmt.format(i=i)]
            rows.append(t.T if transpose else t)
        return np.stack(rows)

    def stack_qkv(bias: bool) -> np.ndarray:
        """Fuse q/k/v into [L, D, 3D] (weights) or [L, 3D] (biases)."""
        rows = []
        for i in range(config.n_layers):
            parts = [
                tensors[f"encoder.layer.{i}.attention.self.{name}.{'bias' if bias else 'weight'}"]
                for name in ("query", "key", "value")
            ]
            if bias:
                rows.append(np.concatenate(parts))
            else:
                rows.append(np.concatenate([p.T for p in parts], axis=1))
        return np.stack(rows)

    # token-type row 0 is added to every position (all-zero token types)
    pos = tensors["embeddings.position_embeddings.weight"].astype(np.float32)
    if "embeddings.token_type_embeddings.weight" in tensors:
        pos = pos + tensors["embeddings.token_type_embeddings.weight"][0].astype(np.float32)

    params: dict[str, Any] = {
        "tok_embed": put(tensors["embeddings.word_embeddings.weight"]),
        "pos_embed": put(pos),
        "embed_ln_scale": put(tensors["embeddings.LayerNorm.weight"]),
        "embed_ln_bias": put(tensors["embeddings.LayerNorm.bias"]),
        "layers": {
            "qkv": put(stack_qkv(bias=False)),
            "qkv_bias": put(stack_qkv(bias=True)),
            "attn_out": put(stack("encoder.layer.{i}.attention.output.dense.weight")),
            "attn_out_bias": put(stack("encoder.layer.{i}.attention.output.dense.bias", transpose=False)),
            "ln1_scale": put(stack("encoder.layer.{i}.attention.output.LayerNorm.weight", transpose=False)),
            "ln1_bias": put(stack("encoder.layer.{i}.attention.output.LayerNorm.bias", transpose=False)),
            "mlp_in": put(stack("encoder.layer.{i}.intermediate.dense.weight")),
            "mlp_in_bias": put(stack("encoder.layer.{i}.intermediate.dense.bias", transpose=False)),
            "mlp_out": put(stack("encoder.layer.{i}.output.dense.weight")),
            "mlp_out_bias": put(stack("encoder.layer.{i}.output.dense.bias", transpose=False)),
            "ln2_scale": put(stack("encoder.layer.{i}.output.LayerNorm.weight", transpose=False)),
            "ln2_bias": put(stack("encoder.layer.{i}.output.LayerNorm.bias", transpose=False)),
        },
    }
    logger.info("loaded bert params: %d layers, dim %d", config.n_layers, config.dim)
    return params
