"""HF safetensors → stacked jax params.

The reference has no model weights at all (SURVEY §5.4); this is the
checkpoint system for the in-tree engine. Reads a HuggingFace Llama-family
checkpoint directory (``*.safetensors`` shards) and produces the stacked
pytree layout of ``models/llama.py:init_params`` — every per-layer HF tensor
transposed to (in, out) and stacked on a leading layer axis.

Memory discipline: tensors are read lazily per shard and converted layer by
layer; with a sharding provided, each stacked leaf is ``jax.device_put``
directly to its target placement so an 8B/70B checkpoint never needs full
host residency twice.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from finchat_tpu.models.llama import LlamaConfig
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _open_shards(path: Path):
    """Yield (name → numpy) accessors over every safetensors shard."""
    from safetensors import safe_open

    files = sorted(path.glob("*.safetensors"))
    if not files:
        raise FileNotFoundError(f"no .safetensors files under {path}")
    for file in files:
        yield safe_open(str(file), framework="numpy")


def load_llama_params(
    checkpoint_dir: str,
    config: LlamaConfig,
    *,
    shardings: dict[str, Any] | None = None,
    quant: str = "",
    quant_group: int = 0,
) -> dict[str, Any]:
    """Load HF Llama weights into the stacked pytree layout.

    ``shardings``: optional map from our param path (e.g. ``layers/attn_q``)
    to a ``jax.sharding.Sharding`` for direct sharded placement.

    ``quant="int8"`` / ``"int4"``: quantize each matmul weight AT LOAD, one
    tensor at a time (models/quant.py) — the device never holds more than
    one bf16 leaf alongside the quantized tree, so llama3-8b (16 GB bf16)
    loads onto one 16 GB v5e chip. Same numerics as quantizing after a
    full-precision load. ``quant_group`` is the int4 scale group size
    along K (0 = per-output-channel).
    """
    from finchat_tpu.models.quant import validate_quant_mode

    validate_quant_mode(quant)
    path = Path(checkpoint_dir)
    tensors: dict[str, np.ndarray] = {}
    for shard in _open_shards(path):
        for name in shard.keys():
            tensors[name] = shard.get_tensor(name)
    logger.info("read %d tensors from %s", len(tensors), path)

    cfg_file = path / "config.json"
    if cfg_file.exists():
        hf_cfg = json.loads(cfg_file.read_text())
        mismatches = {
            "hidden_size": config.dim,
            "num_hidden_layers": config.n_layers,
            "num_attention_heads": config.n_heads,
            "num_key_value_heads": config.n_kv_heads,
            "intermediate_size": config.hidden_dim,
            "vocab_size": config.vocab_size,
            "num_local_experts": config.n_experts,  # Mixtral-family
            "num_experts_per_tok": config.top_k_experts,
        }
        for hf_key, ours in mismatches.items():
            if hf_key in hf_cfg and hf_cfg[hf_key] != ours:
                raise ValueError(
                    f"checkpoint {hf_key}={hf_cfg[hf_key]} != config {ours}; wrong preset?"
                )
        if config.n_experts and "num_local_experts" not in hf_cfg:
            raise ValueError(
                "config expects an MoE checkpoint (n_experts="
                f"{config.n_experts}) but config.json has no num_local_experts"
            )

    dtype = config.dtype

    def put(path_key: str, array: np.ndarray) -> Any:
        arr = jnp.asarray(array, dtype=dtype)
        if shardings and path_key in shardings:
            arr = jax.device_put(arr, shardings[path_key])
        if quant:
            from finchat_tpu.models.quant import quantize_stacked, should_quantize

            if should_quantize(path_key.rsplit("/", 1)[-1]):
                # per-slice for stacked leaves: whole-leaf quantize's fp32
                # upcast transient (7.5 GB on the 8B mlp stack) would OOM
                # next to the already-quantized leaves
                qt = quantize_stacked(arr, mode=quant, group_size=quant_group)
                # free the bf16 copy before the next tensor materializes
                jax.block_until_ready(qt.q)  # finchat-lint: disable=event-loop-blocking -- checkpoint-load memory backpressure by design (one quantized slice's transients at a time); startup path, runs before anything serves
                del arr
                return qt
        return arr

    def stack(fmt: str, transpose: bool = True) -> np.ndarray:
        layers = []
        for i in range(config.n_layers):
            t = tensors[fmt.format(i=i)]
            layers.append(t.T if transpose else t)
        return np.stack(layers)

    params: dict[str, Any] = {
        "embed": put("embed", tensors["model.embed_tokens.weight"]),
        "layers": {
            "attn_q": put("layers/attn_q", stack("model.layers.{i}.self_attn.q_proj.weight")),
            "attn_k": put("layers/attn_k", stack("model.layers.{i}.self_attn.k_proj.weight")),
            "attn_v": put("layers/attn_v", stack("model.layers.{i}.self_attn.v_proj.weight")),
            "attn_o": put("layers/attn_o", stack("model.layers.{i}.self_attn.o_proj.weight")),
            "ln_attn": put("layers/ln_attn", stack("model.layers.{i}.input_layernorm.weight", transpose=False)),
            "ln_mlp": put("layers/ln_mlp", stack("model.layers.{i}.post_attention_layernorm.weight", transpose=False)),
        },
        "norm": put("norm", tensors["model.norm.weight"]),
    }
    if config.n_experts:
        # Mixtral layout: block_sparse_moe.gate (router) + experts.{e}.w1/w3/w2
        # (gate/up/down) — stacked to [L, E, in, out]
        def stack_experts(w: str) -> np.ndarray:
            return np.stack([
                np.stack([
                    tensors[f"model.layers.{i}.block_sparse_moe.experts.{e}.{w}.weight"].T
                    for e in range(config.n_experts)
                ])
                for i in range(config.n_layers)
            ])

        # router stays fp32: routing decisions are precision-sensitive and
        # the tensor is tiny ([L, D, E])
        router = np.stack([
            tensors[f"model.layers.{i}.block_sparse_moe.gate.weight"].T
            for i in range(config.n_layers)
        ])
        params["layers"].update({
            "router": jnp.asarray(router, jnp.float32),
            "moe_gate": put("layers/moe_gate", stack_experts("w1")),
            "moe_up": put("layers/moe_up", stack_experts("w3")),
            "moe_down": put("layers/moe_down", stack_experts("w2")),
        })
    else:
        params["layers"].update({
            "mlp_gate": put("layers/mlp_gate", stack("model.layers.{i}.mlp.gate_proj.weight")),
            "mlp_up": put("layers/mlp_up", stack("model.layers.{i}.mlp.up_proj.weight")),
            "mlp_down": put("layers/mlp_down", stack("model.layers.{i}.mlp.down_proj.weight")),
        })
    if "lm_head.weight" in tensors:
        params["lm_head"] = put("lm_head", tensors["lm_head.weight"].T)
    else:
        # tied embeddings (TinyLlama & Llama-3.2 style)
        params["lm_head"] = put("lm_head", np.asarray(tensors["model.embed_tokens.weight"]).T)
    logger.info("loaded llama params: %d layers, dim %d", config.n_layers, config.dim)
    return params
