"""Native sharded checkpoints via Orbax/tensorstore.

SURVEY §5.4: the reference has no model weights, so checkpointing is new
framework surface. Two layers:

- ``hf_loader`` converts a HuggingFace safetensors directory once (one-way,
  CPU-heavy transposes + stacking);
- this module persists/loads the CONVERTED stacked pytree natively, with
  per-shard tensorstore streams — so a server boot restores an 8B/70B param
  tree directly onto its mesh placement (each host reads only its shards,
  resumable on failure), instead of re-converting HF every start.

Also covers training resume: ``TrainState`` (params + optimizer state +
step) round-trips the same way, preserving shardings.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_pytree(path: str | Path, tree: Any) -> None:
    """Persist a pytree of jax arrays (sharded or not) to ``path``.

    Each device's shards stream to tensorstore; the write is atomic (Orbax
    finalizes via rename) so a crashed save never leaves a half checkpoint
    that restore would accept.
    """
    path = Path(path).resolve()
    ckptr = _checkpointer()
    ckptr.save(path, tree, force=True)
    ckptr.wait_until_finished()
    logger.info("checkpoint saved to %s", path)


def restore_pytree(path: str | Path, like: Any) -> Any:
    """Restore a pytree saved by ``save_pytree``.

    ``like`` supplies structure/shape/dtype AND placement: pass a pytree of
    ``jax.ShapeDtypeStruct``s carrying ``sharding`` (or concrete arrays) and
    each process reads exactly its own shards from the store.
    """
    path = Path(path).resolve()
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=getattr(x, "sharding", None))
        if not isinstance(x, jax.ShapeDtypeStruct) else x,
        like,
    )
    ckptr = _checkpointer()
    restored = ckptr.restore(path, abstract)
    logger.info("checkpoint restored from %s", path)
    return restored


def save_train_state(path: str | Path, state: Any) -> None:
    """Persist a train/train_step.TrainState (params, opt_state, step)."""
    save_pytree(
        Path(path) / "train_state",
        {"params": state.params, "opt_state": state.opt_state, "step": state.step},
    )


def restore_train_state(path: str | Path, like_state: Any):
    """Restore into the structure of ``like_state`` (same optimizer config)."""
    from finchat_tpu.train.train_step import TrainState

    restored = restore_pytree(
        Path(path) / "train_state",
        {"params": like_state.params, "opt_state": like_state.opt_state, "step": like_state.step},
    )
    return TrainState(
        params=restored["params"], opt_state=restored["opt_state"], step=restored["step"]
    )
