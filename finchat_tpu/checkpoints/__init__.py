from finchat_tpu.checkpoints.hf_loader import load_llama_params

__all__ = ["load_llama_params"]
