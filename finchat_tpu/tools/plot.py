"""create_financial_plot — chart generation over transaction data.

The reference ships this tool as dead code (``tools/plot_tool.py``, never
imported — SURVEY §2.1); here it is implemented and wired into the agent.
Renders line/bar/pie/scatter/histogram charts from a JSON list of
transactions and returns a base64 PNG data-URI, matching the reference
tool's contract.

Implementation notes: pure stdlib + numpy + matplotlib(Agg) — deliberately
NO pandas: DataFrame construction off the main thread segfaults
intermittently (pyarrow string arrays are not thread-safe), and the chart
path must never be able to take down the singleton TPU worker. Rendering is
cheap (≤10k rows, Agg backend) and runs synchronously on the caller.
"""

from __future__ import annotations

import base64
import io
import json
import threading
from dataclasses import dataclass
from typing import Any

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# Canonical chart-type enum: the grammar (agent/constrained.py) and the
# validator (agent/toolcall.py) both import this, so the three layers
# cannot drift.
CHART_TYPES = ("line", "bar", "pie", "scatter", "histogram")

# matplotlib's pyplot state machine is not thread-safe; serialize renders
_RENDER_LOCK = threading.Lock()


@dataclass
class PlotConfig:
    """Parity with the reference's PlotConfig schema (plot_tool.py:9-14)."""

    chart_type: str = "bar"
    x_field: str = "date"
    y_field: str = "amount"
    title: str = "Financial Plot"


def _columns(rows: list[dict], fields: tuple[str, ...]) -> dict[str, list]:
    for field_name in fields:
        missing = [r for r in rows if field_name not in r]
        if missing:
            raise ValueError(f"field {field_name!r} missing from transactions")
    return {f: [r[f] for r in rows] for f in fields}


def create_financial_plot(transactions_json: str, config: PlotConfig | None = None) -> str:
    """Render a chart from transaction JSON → ``data:image/png;base64,...``.

    ``transactions_json``: JSON list of objects with at least the configured
    x/y fields. Raises ValueError on malformed input or unknown chart type.
    """
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt

    cfg = config or PlotConfig()
    if cfg.chart_type not in CHART_TYPES:
        raise ValueError(f"unknown chart_type {cfg.chart_type!r}; expected one of {CHART_TYPES}")

    rows: Any = json.loads(transactions_json)
    if not isinstance(rows, list) or not rows or not all(isinstance(r, dict) for r in rows):
        raise ValueError("transactions_json must be a non-empty JSON list of objects")
    fields = (cfg.y_field,) if cfg.chart_type == "histogram" else (cfg.x_field, cfg.y_field)
    cols = _columns(rows, fields)

    with _RENDER_LOCK:
        fig, ax = plt.subplots(figsize=(8, 5))
        try:
            if cfg.chart_type == "line":
                ax.plot(cols[cfg.x_field], cols[cfg.y_field])
            elif cfg.chart_type == "bar":
                ax.bar([str(x) for x in cols[cfg.x_field]], cols[cfg.y_field])
            elif cfg.chart_type == "scatter":
                ax.scatter(cols[cfg.x_field], cols[cfg.y_field])
            elif cfg.chart_type == "histogram":
                ax.hist(cols[cfg.y_field], bins=min(20, max(5, len(rows) // 2)))
            elif cfg.chart_type == "pie":
                totals: dict[str, float] = {}
                for x, y in zip(cols[cfg.x_field], cols[cfg.y_field]):
                    totals[str(x)] = totals.get(str(x), 0.0) + abs(float(y))
                ax.pie(list(totals.values()), labels=list(totals.keys()), autopct="%1.1f%%")
            if cfg.chart_type != "pie":
                ax.set_xlabel(cfg.x_field)
                ax.set_ylabel(cfg.y_field)
                fig.autofmt_xdate(rotation=30)
            ax.set_title(cfg.title)
            buf = io.BytesIO()
            fig.savefig(buf, format="png", dpi=100, bbox_inches="tight")
        finally:
            plt.close(fig)

    encoded = base64.b64encode(buf.getvalue()).decode("ascii")
    logger.info("rendered %s chart (%d rows, %d png bytes)", cfg.chart_type, len(rows), len(buf.getvalue()))
    return f"data:image/png;base64,{encoded}"
