"""create_financial_plot — chart generation over transaction data.

The reference ships this tool as dead code (``tools/plot_tool.py``, never
imported — SURVEY §2.1); here it is implemented and importable. Renders
line/bar/pie/scatter/histogram charts from a JSON list of transactions and
returns a base64 PNG data-URI, matching the reference tool's contract.
"""

from __future__ import annotations

import base64
import io
import json
from dataclasses import dataclass
from typing import Any

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CHART_TYPES = ("line", "bar", "pie", "scatter", "histogram")


@dataclass
class PlotConfig:
    """Parity with the reference's PlotConfig schema (plot_tool.py:9-14)."""

    chart_type: str = "bar"
    x_field: str = "date"
    y_field: str = "amount"
    title: str = "Financial Plot"


def create_financial_plot(transactions_json: str, config: PlotConfig | None = None) -> str:
    """Render a chart from transaction JSON → ``data:image/png;base64,...``.

    ``transactions_json``: JSON list of objects with at least the configured
    x/y fields. Raises ValueError on malformed input or unknown chart type.
    """
    import matplotlib

    matplotlib.use("Agg")  # headless
    import matplotlib.pyplot as plt
    import pandas as pd

    cfg = config or PlotConfig()
    if cfg.chart_type not in _CHART_TYPES:
        raise ValueError(f"unknown chart_type {cfg.chart_type!r}; expected one of {_CHART_TYPES}")

    rows: Any = json.loads(transactions_json)
    if not isinstance(rows, list) or not rows:
        raise ValueError("transactions_json must be a non-empty JSON list")
    frame = pd.DataFrame(rows)
    for column in (cfg.x_field, cfg.y_field) if cfg.chart_type != "histogram" else (cfg.y_field,):
        if column not in frame.columns:
            raise ValueError(f"field {column!r} missing from transactions")

    fig, ax = plt.subplots(figsize=(8, 5))
    try:
        if cfg.chart_type == "line":
            ax.plot(frame[cfg.x_field], frame[cfg.y_field])
        elif cfg.chart_type == "bar":
            ax.bar(frame[cfg.x_field].astype(str), frame[cfg.y_field])
        elif cfg.chart_type == "scatter":
            ax.scatter(frame[cfg.x_field], frame[cfg.y_field])
        elif cfg.chart_type == "histogram":
            ax.hist(frame[cfg.y_field], bins=min(20, max(5, len(frame) // 2)))
        elif cfg.chart_type == "pie":
            grouped = frame.groupby(cfg.x_field)[cfg.y_field].sum().abs()
            ax.pie(grouped.values, labels=[str(l) for l in grouped.index], autopct="%1.1f%%")
        if cfg.chart_type != "pie":
            ax.set_xlabel(cfg.x_field)
            ax.set_ylabel(cfg.y_field)
            fig.autofmt_xdate(rotation=30)
        ax.set_title(cfg.title)
        buf = io.BytesIO()
        fig.savefig(buf, format="png", dpi=100, bbox_inches="tight")
    finally:
        plt.close(fig)

    encoded = base64.b64encode(buf.getvalue()).decode("ascii")
    logger.info("rendered %s chart (%d rows, %d png bytes)", cfg.chart_type, len(frame), len(buf.getvalue()))
    return f"data:image/png;base64,{encoded}"
