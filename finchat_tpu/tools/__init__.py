from finchat_tpu.tools.retrieval import TransactionRetriever
from finchat_tpu.tools.plot import create_financial_plot

__all__ = ["TransactionRetriever", "create_financial_plot"]
