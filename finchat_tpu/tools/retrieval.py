"""retrieve_transactions — the RAG tool.

Behavior parity with the reference tool (``tools/qdrant_tool.py:75-177``),
with the embedding + search moved on-device:

- SECURITY: empty ``user_id`` → immediate ``[]`` (qdrant_tool.py:89-91);
  the index query carries a must-filter on ``metadata.user_id``
  (:105-112) AND every hit is re-checked post-hoc, skipped hits counted
  and logged (:159-170).
- ``num_transactions`` defaults to 10,000 when unset (:145);
  ``time_period_days`` becomes ``metadata.date >= now - N days`` (:116-126).
- Returns ``page_content`` strings only (:164); any exception → ``[]``
  with an error log (:175-177).
"""

from __future__ import annotations

import time
from typing import Any, Callable

from finchat_tpu.embed.batcher import EmbedMicrobatcher
from finchat_tpu.embed.encoder import EmbeddingEncoder
from finchat_tpu.embed.index import DeviceVectorIndex, QuerySpec
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS, Timer

logger = get_logger(__name__)

DEFAULT_LIMIT = 10_000
DEFAULT_QUERY = "recent transactions"


class TransactionRetriever:
    """Callable tool: validated args dict (``user_id`` already injected
    server-side by the agent) → list of transaction texts.

    With a ``batcher`` (EmbedMicrobatcher) wired, the query embed
    coalesces with concurrent requests' embeds into shared device
    dispatches and the index search rides the batched device-filter
    plane (``query_points_batch``); without one, the serial host path is
    used unchanged. Per-stage latency lands in the
    ``finchat_retrieval_embed_seconds`` / ``finchat_retrieval_search_seconds``
    histograms either way (the graft stage is timed at the generator's
    ``extend_prompt`` seam)."""

    def __init__(
        self,
        encoder: EmbeddingEncoder,
        index: DeviceVectorIndex,
        *,
        default_limit: int = DEFAULT_LIMIT,  # VectorConfig.default_limit
        now: Callable[[], float] = time.time,
        batcher: EmbedMicrobatcher | None = None,
    ):
        self.encoder = encoder
        self.index = index
        self.default_limit = default_limit
        self.now = now
        self.batcher = batcher

    async def __call__(self, args: dict[str, Any]) -> list[str]:
        return [row["page_content"] for row in await self.structured(args)]

    async def structured(self, args: dict[str, Any]) -> list[dict[str, Any]]:
        """Like ``__call__`` but returns full rows (page_content + metadata
        fields) — the data source for ``create_financial_plot``, which needs
        structured x/y fields, not rendered text.

        The embedding forward pass + index query run device matmuls and
        host syncs; they execute in a worker thread (like the ingestion
        path, serve/app.py) so in-flight token streams on the event loop
        never stall behind a retrieval (verdict r3 weak #3). The batched
        plane keeps that property: the microbatcher dispatches in its own
        worker thread and the index query threads off explicitly."""
        import asyncio

        if self.batcher is None or not hasattr(self.index, "query_points_batch"):
            return await asyncio.to_thread(self._structured_sync, args)
        try:
            parsed = self._parse_args(args)
            if parsed is None:
                return []
            search_query, limit, date_gte = parsed
            user_id = args["user_id"]

            with Timer(METRICS, "finchat_retrieval_embed_seconds"):
                query_vector = await self.batcher.embed_one(search_query)
            with Timer(METRICS, "finchat_retrieval_search_seconds"):
                hits = (await asyncio.to_thread(
                    self.index.query_points_batch,
                    [QuerySpec(query_vector, limit=limit,
                               user_id=user_id, date_gte=date_gte)],
                ))[0]
            rows = self._secure_rows(hits, user_id)
            METRICS.inc("finchat_retrievals_total")
            logger.info("Successfully processed %d transactions", len(rows))
            return rows
        except Exception as e:
            logger.error("Error retrieving transactions: %s", e, exc_info=True)
            return []

    def _parse_args(self, args: dict[str, Any]) -> tuple[str, int, float | None] | None:
        """Shared tool-argument parsing for both retrieval planes: the
        user_id security gate (qdrant_tool.py:89-91), the search-query and
        limit defaults (:145), and the ``time_period_days`` → ``date >=``
        window (:116-126). ONE implementation, so the defaulting rules can
        never drift between the serial fallback and the batched plane.
        Returns ``(search_query, limit, date_gte)`` or None (refuse)."""
        user_id = args.get("user_id", "")
        logger.info("Starting transaction retrieval for user_id: %s", user_id)
        if not user_id:
            logger.error("Security violation: user_id not provided")
            return None
        search_query = args.get("search_query") or DEFAULT_QUERY
        limit = int(args.get("num_transactions") or self.default_limit)
        date_gte = None
        days = args.get("time_period_days")
        if days:
            date_gte = self.now() - days * 86_400.0
        return search_query, limit, date_gte

    def _secure_rows(self, hits, user_id: str) -> list[dict[str, Any]]:
        """The post-hoc security re-check (parity with
        qdrant_tool.py:159-170) — ONE implementation shared by the serial
        and batched planes, so the golden-equivalence contract between
        them covers the must-filter backstop too."""
        rows: list[dict[str, Any]] = []
        skipped = 0
        for hit in hits:
            payload = hit.payload
            metadata = hit.metadata
            if payload and metadata.get("user_id") == user_id:
                rows.append({**metadata, "page_content": payload["page_content"]})
            else:
                skipped += 1
                logger.warning(
                    "Security check: Skipping transaction with mismatched user_id. "
                    "Expected: %s, Got: %s", user_id, metadata.get("user_id"),
                )
        if skipped:
            logger.warning("Skipped %d transactions due to user_id mismatch", skipped)
            METRICS.inc("finchat_retrieval_security_skips_total", skipped)
        return rows

    def _structured_sync(self, args: dict[str, Any]) -> list[dict[str, Any]]:
        try:
            parsed = self._parse_args(args)
            if parsed is None:
                return []
            search_query, limit, date_gte = parsed
            user_id = args["user_id"]

            with Timer(METRICS, "finchat_retrieval_embed_seconds"):
                query_vector = self.encoder.embed_query(search_query)
            with Timer(METRICS, "finchat_retrieval_search_seconds"):
                hits = self.index.query_points(
                    query_vector, limit=limit, user_id=user_id, date_gte=date_gte
                )
            rows = self._secure_rows(hits, user_id)
            METRICS.inc("finchat_retrievals_total")
            logger.info("Successfully processed %d transactions", len(rows))
            return rows
        except Exception as e:
            logger.error("Error retrieving transactions: %s", e, exc_info=True)
            return []

    # --- ingestion side (the reference's upsert path lives out-of-repo;
    # here it is first-class so the product is self-contained) ------------
    def upsert_transactions(
        self,
        user_id: str,
        texts: list[str],
        dates: list[float] | None = None,
        metadatas: list[dict[str, Any]] | None = None,
    ) -> None:
        """``metadatas`` (e.g. ``{"amount": -12.5, "category": "coffee"}``)
        merge into each point's metadata — the structured fields the plot
        tool charts."""
        from finchat_tpu.embed.index import VectorPoint

        if self.batcher is not None:
            # ingest embeds coalesce with in-flight query embeds (the
            # threadsafe path no-ops to a direct call when no loop runs)
            vectors = self.batcher.embed_threadsafe(texts)
        else:
            vectors = self.encoder.embed_batch(texts)
        dates = dates or [self.now()] * len(texts)
        points = [
            VectorPoint(
                id=f"{user_id}-{i}-{int(dates[i])}",
                vector=vectors[i],
                payload={
                    "page_content": texts[i],
                    "metadata": {
                        **(metadatas[i] if metadatas else {}),
                        "user_id": user_id,
                        "date": dates[i],
                    },
                },
            )
            for i in range(len(texts))
        ]
        self.index.upsert(points)
