"""External Qdrant retriever backend (VERDICT r4 missing #3).

Drop-in for deployments with an existing, already-populated Qdrant
cluster — the reference's actual vector backend (``tools/
qdrant_tool.py:24-37``, ``query_points`` :147-153). Implements the same
interface as the in-tree ``TransactionRetriever`` (``__call__`` /
``structured`` / ``upsert_transactions``), so the agent, plot tool, and
ingestion paths cannot tell the backends apart, and keeps every security
invariant:

- empty ``user_id`` → immediate ``[]``, no backend call
  (qdrant_tool.py:89-91);
- the search carries a server-side must-filter on ``metadata.user_id``
  (:105-112) and a ``metadata.date >= now - N days`` range when
  ``time_period_days`` is set (:116-126);
- every returned hit is re-checked post-hoc, mismatches skipped and
  counted (:159-170);
- any exception → ``[]`` with an error log (:175-177).

TPU-first split: the query/ingest EMBEDDINGS still run on-device
(``embed/encoder.py`` — the reference calls OpenAI for these); only the
ANN search itself is delegated to the external service. Filters and
points are built as plain dicts (the qdrant client parses them into its
pydantic models), which keeps this module importable — and fully
testable against a faked client — without ``qdrant-client`` installed;
the real client import is deferred to first construction without an
injected client. Selected by ``build_app`` when ``QDRANT_URL`` is set.
"""

from __future__ import annotations

import time
from typing import Any, Callable

from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

DEFAULT_LIMIT = 10_000  # qdrant_tool.py:145
DEFAULT_QUERY = "recent transactions"

# reference search tuning (qdrant_tool.py:98-101)
_SEARCH_PARAMS = {"hnsw_ef": 128, "exact": False}


class QdrantRetriever:
    """Callable RAG tool backed by an external Qdrant service.

    ``client`` is injectable (tests fake it); when omitted, a
    ``qdrant_client.QdrantClient(url=..., api_key=...)`` is constructed
    lazily so the dependency stays optional.
    """

    def __init__(
        self,
        encoder,
        *,
        url: str = "",
        api_key: str = "",
        collection: str = "transactions",
        default_limit: int = DEFAULT_LIMIT,
        now: Callable[[], float] = time.time,
        client: Any = None,
    ):
        if client is None:
            try:
                from qdrant_client import QdrantClient
            except ImportError as e:  # pragma: no cover - env without the pkg
                raise RuntimeError(
                    "QDRANT_URL is set but the 'qdrant-client' package is not "
                    "installed; install it or unset QDRANT_URL to use the "
                    "on-device vector index"
                ) from e
            client = QdrantClient(url=url, api_key=api_key or None)
            logger.info("qdrant retriever: connected to %s (collection=%s)",
                        url, collection)
        self.client = client
        self.encoder = encoder
        self.collection = collection
        self.default_limit = default_limit
        self.now = now

    async def __call__(self, args: dict[str, Any]) -> list[str]:
        return [row["page_content"] for row in await self.structured(args)]

    async def structured(self, args: dict[str, Any]) -> list[dict[str, Any]]:
        """Full rows (page_content + metadata) for the plot tool. The
        device embedding forward + the network round-trip both run in a
        worker thread so token streams on the event loop never stall
        behind a retrieval (same policy as tools/retrieval.py)."""
        import asyncio

        return await asyncio.to_thread(self._structured_sync, args)

    def _structured_sync(self, args: dict[str, Any]) -> list[dict[str, Any]]:
        try:
            user_id = args.get("user_id", "")
            logger.info("Starting transaction retrieval for user_id: %s", user_id)
            if not user_id:
                logger.error("Security violation: user_id not provided")
                return []

            search_query = args.get("search_query") or DEFAULT_QUERY
            limit = args.get("num_transactions") or self.default_limit
            must: list[dict[str, Any]] = [
                {"key": "metadata.user_id", "match": {"value": user_id}}
            ]
            days = args.get("time_period_days")
            if days:
                must.append({
                    "key": "metadata.date",
                    "range": {"gte": int(self.now() - days * 86_400.0)},
                })

            query_vector = self.encoder.embed_query(search_query)
            hits = self.client.query_points(
                collection_name=self.collection,
                query=[float(x) for x in query_vector],
                limit=int(limit),
                query_filter={"must": must},
                search_params=dict(_SEARCH_PARAMS),
                with_payload=True,
            ).points

            rows: list[dict[str, Any]] = []
            skipped = 0
            for hit in hits:
                payload = hit.payload
                metadata = (payload or {}).get("metadata", {})
                content = (payload or {}).get("page_content")
                # post-hoc security re-check, parity with qdrant_tool.py:159-170
                # (content is also .get-checked: one malformed point in an
                # externally-populated cluster skips, not empties, the result)
                if payload and content is not None and metadata.get("user_id") == user_id:
                    rows.append({**metadata, "page_content": content})
                else:
                    skipped += 1
                    logger.warning(
                        "Security check: Skipping transaction with mismatched "
                        "user_id. Expected: %s, Got: %s",
                        user_id, metadata.get("user_id"),
                    )
            if skipped:
                logger.warning("Skipped %d transactions due to user_id mismatch", skipped)
                METRICS.inc("finchat_retrieval_security_skips_total", skipped)

            METRICS.inc("finchat_retrievals_total")
            logger.info("Successfully processed %d transactions", len(rows))
            return rows
        except Exception as e:
            logger.error("Error retrieving transactions: %s", e, exc_info=True)
            return []

    # --- ingestion side (mirrors tools/retrieval.py upsert contract) -----
    def upsert_transactions(
        self,
        user_id: str,
        texts: list[str],
        dates: list[float] | None = None,
        metadatas: list[dict[str, Any]] | None = None,
    ) -> None:
        """Embed on-device, upsert into the external collection with the
        same payload shape the retrieval side (and the reference's
        out-of-band ingestion) expects."""
        vectors = self.encoder.embed_batch(texts)
        dates = dates or [self.now()] * len(texts)
        points = [
            {
                "id": _point_id(user_id, i, dates[i]),
                "vector": [float(x) for x in vectors[i]],
                "payload": {
                    "page_content": texts[i],
                    "metadata": {
                        **(metadatas[i] if metadatas else {}),
                        "user_id": user_id,
                        "date": dates[i],
                    },
                },
            }
            for i in range(len(texts))
        ]
        self.client.upsert(collection_name=self.collection, points=points)


def _point_id(user_id: str, i: int, date: float) -> str:
    """Qdrant point ids must be unsigned ints or UUIDs (unlike the
    in-tree index's free-form strings): derive a stable UUID from the
    same ``user_id/ordinal/date`` identity the device index keys on, so
    re-ingesting the same row overwrites instead of duplicating."""
    import uuid

    return str(uuid.uuid5(uuid.NAMESPACE_URL, f"{user_id}-{i}-{int(date)}"))
