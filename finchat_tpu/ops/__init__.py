from finchat_tpu.ops.dispatch import attention_backend, causal_attention, paged_attention
from finchat_tpu.ops.flash_attention import flash_attention
from finchat_tpu.ops.kv_append import paged_kv_append
from finchat_tpu.ops.paged_attention import paged_flash_attention
from finchat_tpu.ops.refs import gqa_repeat, mha_reference

__all__ = [
    "attention_backend",
    "causal_attention",
    "flash_attention",
    "gqa_repeat",
    "mha_reference",
    "paged_attention",
    "paged_flash_attention",
    "paged_kv_append",
]
