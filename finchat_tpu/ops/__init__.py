from finchat_tpu.ops.refs import mha_reference, gqa_repeat

__all__ = ["mha_reference", "gqa_repeat"]
