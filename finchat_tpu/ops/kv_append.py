"""Pallas in-place decode KV append — the write half of the decode hot path.

The XLA alternative (``engine/kv_cache.py scatter_kv_chunk``) lowers to a
scatter that rebuilds the destination buffer: ~22 ms/step measured for a
1.5 GB TinyLlama cache on v5e (benchmarks/probe_cache_styles.py), both as
scan xs→ys and as an in-carry scatter — XLA never does it in place. This
kernel does: ``input_output_aliases`` pins the output to the input buffer
and each program read-modify-writes exactly ONE page, so per-step traffic is
B pages instead of the whole cache (~0.5 ms at bench shapes).

Mosaic constraints that shaped the design (discovered on v5e hardware,
round 4 — see git history for the failed variants):
- DMA slices must be tile-aligned in the trailing two dims: a single-token
  ``(1, hd)`` copy is rejected, a full page ``(page_size, Hkv*hd)`` is
  legal. Hence RMW of the whole page with the token row inserted by a
  masked select, not a token-granular write.
- Dynamic (scalar-prefetch-dependent) OUTPUT BlockSpec index maps compile
  but fail at runtime; manual ``make_async_copy`` into an ``ANY``-space
  aliased output works.

Grid is ``(B,)`` — one program per sequence per layer; the layer is a
scalar-prefetch operand so the kernel indexes the full-depth cache that the
model's layer scan carries (no per-layer dynamic-slice copies).

Serves decode only (C = 1). Prefill chunks keep the XLA scatter: one
full-cache copy amortized over a whole batched chunk is noise next to the
prefill matmuls.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TRASH_PAGE = 0


def _append_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32
    page_table_ref,  # [B, max_pages] int32
    pos_ref,  # [B] int32 — absolute write position (the token's position)
    n_valid_ref,  # [B] int32 — 1 = live slot, 0 = inactive (trash redirect)
    # blocks
    kv_new_ref,  # [1, 1, 2*HD] VMEM — k row ++ v row
    k_any,  # [L, P, PS, HD] ANY (aliased to output 0)
    v_any,
    o_k,  # aliased outputs (same buffers as k_any / v_any)
    o_v,
    # scratch
    k_scr,  # [PS, HD] VMEM
    v_scr,
    sems,  # DMA semaphores (4,)
    *,
    page_size: int,
):
    b = pl.program_id(0)
    pos = pos_ref[b]
    off = pos % page_size
    layer = layer_ref[0]
    valid = n_valid_ref[b] > 0
    # the table read happens BEFORE the select, so an invalid lane's pos
    # (e.g. a trash-redirected verify-step position at the slot's length
    # limit) must not index past the table row — read column 0 instead
    logical = jnp.where(valid, pos // page_size, 0)
    phys = jnp.where(valid, page_table_ref[b, logical], TRASH_PAGE)
    hd = k_scr.shape[-1]

    kin = pltpu.make_async_copy(k_any.at[layer, phys], k_scr, sems.at[0])
    vin = pltpu.make_async_copy(v_any.at[layer, phys], v_scr, sems.at[1])
    kin.start()
    vin.start()
    kin.wait()
    vin.wait()

    row = jax.lax.broadcasted_iota(jnp.int32, (page_size, 1), 0)
    hit = row == off
    k_scr[:] = jnp.where(hit, kv_new_ref[0, :, 0:hd], k_scr[:])
    v_scr[:] = jnp.where(hit, kv_new_ref[0, :, hd:2 * hd], v_scr[:])

    kout = pltpu.make_async_copy(k_scr, o_k.at[layer, phys], sems.at[2])
    vout = pltpu.make_async_copy(v_scr, o_v.at[layer, phys], sems.at[3])
    kout.start()
    vout.start()
    kout.wait()
    vout.wait()


def _append_kernel_q8(
    # scalar prefetch
    layer_ref,  # [1] int32
    page_table_ref,  # [B, max_pages] int32
    pos_ref,  # [B] int32
    n_valid_ref,  # [B] int32
    # blocks
    kv_new_ref,  # [1, 1, 2*HD] VMEM float — k row ++ v row (unquantized)
    k_any,  # [L, P, PS, HD] int8 ANY (aliased to output 0)
    v_any,
    ks_any,  # [L, P, SPAD, PS] fp32 ANY (aliased to output 2)
    vs_any,
    o_k, o_v, o_ks, o_vs,  # aliased outputs
    # scratch
    k_scr,  # [PS, HD] int8
    v_scr,
    ks_scr,  # [SPAD, PS] fp32
    vs_scr,
    sems,  # DMA semaphores (8,)
    *,
    page_size: int,
    n_kv: int,
):
    """Quantizing decode append: RMW one data page AND its scale block per
    sequence. The new token's row is quantized per head (amax/127) INSIDE
    the kernel; existing rows are copied back bit-identical (per-token
    scales — no requantization, no drift)."""
    b = pl.program_id(0)
    pos = pos_ref[b]
    off = pos % page_size
    layer = layer_ref[0]
    valid = n_valid_ref[b] > 0
    logical = jnp.where(valid, pos // page_size, 0)  # OOB-safe for trash lanes
    phys = jnp.where(valid, page_table_ref[b, logical], TRASH_PAGE)
    hd_fused = k_scr.shape[-1]
    hd = hd_fused // n_kv

    copies_in = [
        pltpu.make_async_copy(k_any.at[layer, phys], k_scr, sems.at[0]),
        pltpu.make_async_copy(v_any.at[layer, phys], v_scr, sems.at[1]),
        pltpu.make_async_copy(ks_any.at[layer, phys], ks_scr, sems.at[2]),
        pltpu.make_async_copy(vs_any.at[layer, phys], vs_scr, sems.at[3]),
    ]
    for c in copies_in:
        c.start()
    for c in copies_in:
        c.wait()

    rows = jax.lax.broadcasted_iota(jnp.int32, (page_size, 1), 0)
    hit = rows == off  # [PS, 1]
    srows = jax.lax.broadcasted_iota(jnp.int32, ks_scr.shape, 0)
    scols = jax.lax.broadcasted_iota(jnp.int32, ks_scr.shape, 1)
    for h in range(n_kv):
        sl = slice(h * hd, (h + 1) * hd)
        for new_ref_off, scr, s_scr in ((0, k_scr, ks_scr), (hd_fused, v_scr, vs_scr)):
            row = kv_new_ref[0, :, new_ref_off + h * hd:new_ref_off + (h + 1) * hd]
            row32 = row.astype(jnp.float32)  # [1, hd]
            amax = jnp.max(jnp.abs(row32))
            scale = jnp.where(amax > 0, amax, 1.0) / 127.0
            q8 = jnp.clip(jnp.round(row32 / scale), -127, 127).astype(jnp.int8)
            scr[:, sl] = jnp.where(hit, q8, scr[:, sl])
            s_hit = jnp.logical_and(srows == h, scols == off)
            s_scr[:] = jnp.where(s_hit, scale, s_scr[:])

    copies_out = [
        pltpu.make_async_copy(k_scr, o_k.at[layer, phys], sems.at[4]),
        pltpu.make_async_copy(v_scr, o_v.at[layer, phys], sems.at[5]),
        pltpu.make_async_copy(ks_scr, o_ks.at[layer, phys], sems.at[6]),
        pltpu.make_async_copy(vs_scr, o_vs.at[layer, phys], sems.at[7]),
    ]
    for c in copies_out:
        c.start()
    for c in copies_out:
        c.wait()


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "n_kv", "interpret"),
    donate_argnums=(1, 2, 3, 4),
)
def paged_kv_append_q8(
    kv_new: Array,  # [B, 1, 2*Hkv*hd] float — fused k row ++ v row
    k_pages: Array,  # [L, P, page_size, Hkv*hd] int8
    v_pages: Array,
    k_scales: Array,  # [L, P, scale_rows, page_size] fp32
    v_scales: Array,
    page_table: Array,
    pos: Array,
    n_valid: Array,
    layer: Array,
    *,
    page_size: int,
    n_kv: int,
    interpret: bool | None = None,
) -> tuple[Array, Array, Array, Array]:
    """Quantizing in-place append for the int8 KV cache; returns the
    (aliased) data and scale arrays."""
    B = kv_new.shape[0]
    HD = k_pages.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 1, 2 * HD), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((page_size, HD), k_pages.dtype),
            pltpu.VMEM((page_size, HD), k_pages.dtype),
            pltpu.VMEM(k_scales.shape[2:], jnp.float32),
            pltpu.VMEM(v_scales.shape[2:], jnp.float32),
            pltpu.SemaphoreType.DMA((8,)),
        ],
    )
    kernel = functools.partial(_append_kernel_q8, page_size=page_size, n_kv=n_kv)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
            jax.ShapeDtypeStruct(k_scales.shape, jnp.float32),
            jax.ShapeDtypeStruct(v_scales.shape, jnp.float32),
        ],
        # flattened operands: 4 scalar-prefetch, kv_new, then the 4 aliased
        input_output_aliases={5: 0, 6: 1, 7: 2, 8: 3},
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32), page_table, pos, n_valid, kv_new,
      k_pages, v_pages, k_scales, v_scales)


@functools.partial(
    jax.jit, static_argnames=("page_size", "interpret"), donate_argnums=(1, 2)
)
def paged_kv_append(
    kv_new: Array,  # [B, 1, 2*Hkv*hd] — fused k row ++ v row per sequence
    k_pages: Array,  # [L, P, page_size, Hkv*hd]
    v_pages: Array,
    page_table: Array,  # [B, max_pages] int32
    pos: Array,  # [B] int32 absolute write positions
    n_valid: Array,  # [B] int32 (0 redirects the write to the trash page)
    layer: Array,  # [1] int32
    *,
    page_size: int,
    interpret: bool | None = None,
) -> tuple[Array, Array]:
    """Append one token's K/V per sequence into layer ``layer``'s pages,
    in place. Returns the (aliased) cache pair."""
    B = kv_new.shape[0]
    HD = k_pages.shape[-1]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, 1, 2 * HD), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        scratch_shapes=[
            pltpu.VMEM((page_size, HD), k_pages.dtype),
            pltpu.VMEM((page_size, HD), k_pages.dtype),
            pltpu.SemaphoreType.DMA((4,)),
        ],
    )
    kernel = functools.partial(_append_kernel, page_size=page_size)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        # flattened operand order: 4 scalar-prefetch, kv_new, k_pages, v_pages
        input_output_aliases={5: 0, 6: 1},
        interpret=interpret,
    )(jnp.asarray(layer, jnp.int32), page_table, pos, n_valid, kv_new, k_pages, v_pages)
