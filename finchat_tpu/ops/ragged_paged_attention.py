"""Pallas ragged paged attention for TPU — one dispatch shape for every row.

ISSUE 10 / ROADMAP item 1, following "Ragged Paged Attention: A
High-Performance and Flexible LLM Inference Kernel for TPU" (PAPERS.md).
PR 4's ``mixed_step`` unified prefill and decode into one dispatch, but as a
PADDED ``[rows, chunk]`` buffer: every decode row paid dense compute for the
whole padded column width (two chunk buckets bounded the waste, at the price
of a row-bucket × chunk-bucket warmup matrix), and anything that was not
exactly "a chunk or a single token" — spec-decode verify blocks, decode-loop
rows, grammar-constrained picks — was demoted to the serialized split path.

Here the batch is a PACKED token buffer: each row owns a contiguous span of
``q`` tokens and carries its own descriptors —

- ``tok_row [T]``: which row each packed token belongs to (``R`` marks
  buffer padding). Rows must be packed in ascending, contiguous order.
- ``tok_pos [T]``: the token's absolute position in its sequence.
- ``page_table [R, max_pages]``: per-row physical page list (0 = trash).
- ``kv_len [R]``: valid KV length per row INCLUDING this dispatch's tokens.

A 512-token prefill chunk, a 1-token decode row, and a (1+Kd)-token spec
verify block are all just rows of different lengths in the same buffer, so
ONE compiled variant per packed-token bucket serves every feature mix — no
per-mode variants, no dense decode-row compute per padded column.

Kernel design (the Pallas path; the ``jax.lax`` reference below is the
CPU/tier-1 oracle and the serving path on non-TPU backends):

- rows are aligned to ``block_q`` (default 8, the fp32 sublane tile) inside
  the kernel wrapper — a gather/scatter of ``q``/``o`` only, O(T·H·D). On
  the MXU an 8-row tile is the minimum issue width, so a 1-token decode row
  padded to 8 sublanes costs the same MXU cycles as 1 row would: alignment
  padding is free compute, unlike the old chunk-width padding.
- grid ``(n_q_blocks, max_pages)`` with the page axis innermost; each
  q block belongs to exactly ONE row (alignment guarantees it), resolved at
  DMA time from the scalar-prefetched ``blk_row`` map, so the online-softmax
  scratch carries across the row's pages exactly like ops/paged_attention.py.
- K/V pages resolve through the per-row page table at DMA time
  (PrefetchScalarGridSpec); pages past ``kv_len`` or entirely in the causal
  future of the block redirect to the trash page and are skipped by the
  pipeline (consecutive identical block indices are not re-fetched).
- GQA: all KV heads in one program (static unroll), same as the paged
  kernel — a per-head grid axis multiplied the ~1 µs/iteration grid cost.
- int8-KV variant dequantizes per-token-per-head scale rows in VMEM, so the
  ragged kernel slots into the existing on-chip parity matrix (PARITY.md).

Cache layout and the full-depth ``layer`` scalar-prefetch contract are
identical to ops/paged_attention.py (the cache rides the model's layer scan
as a carry).

Bounded-KV serving (ISSUE 15, SnapStream-style sink+window): the per-row
page indirection is exactly what makes page-granular eviction free — an
evicted page just leaves the row's page list and the survivors pack the
front. The wrappers accept a per-row ``kv_gap`` (evicted-token count, the
``kv_window_start`` offset) and shift masking into compacted coordinates
(:func:`_compact_window`) while positions/rotary stay absolute upstream;
the kernel bodies are gap-oblivious.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from finchat_tpu.ops.flash_attention import NEG_INF, _online_softmax_update, _round_up

TRASH_PAGE = 0


def _compact_window(tok_row, tok_pos, kv_len, kv_gap, R: int):
    """Bounded-KV coordinate shift (SnapStream sink+window serving —
    ISSUE 15): ``kv_gap[r]`` tokens of row ``r`` were evicted between the
    pinned sink pages and the surviving window, and the row's page table
    already walks only the SURVIVORS (an evicted page just left the list).
    Masking and page-bound math therefore run in COMPACTED coordinates —
    query positions and kv lengths shift down by the row's gap — while the
    caller's rotary positions stay absolute (keys keep their original RoPE,
    so relative distances to surviving tokens are exact).

    Compacted-coordinate causality is exact for the surviving set: every
    live query sits past the whole evicted region, so ``c_kv <= c_q`` iff
    ``abs_kv <= abs_q`` for sink tokens (unshifted, below the gap) and
    window tokens (shifted by the same gap) alike. ``kv_gap=None`` (or all
    zeros) is the identity — the unbounded paths are bit-unchanged."""
    if kv_gap is None:
        return tok_pos, kv_len
    gap = jnp.asarray(kv_gap, jnp.int32)
    safe = jnp.minimum(jnp.asarray(tok_row, jnp.int32), R - 1)
    # the clamp guards padding tokens (tok_pos 0); real tokens of a gapped
    # row always sit past the evicted region (the scheduler's invariant)
    tok_pos = jnp.maximum(jnp.asarray(tok_pos, jnp.int32) - gap[safe], 0)
    kv_len = jnp.maximum(jnp.asarray(kv_len, jnp.int32) - gap, 0)
    return tok_pos, kv_len


def ragged_paged_attention_ref(
    q: Array,  # [T, H, D] packed query tokens
    k_pages: Array,  # [L, P, page_size, Hkv*D] full-depth cache (or int8)
    v_pages: Array,
    page_table: Array,  # [R, max_pages] int32 per-row physical pages
    tok_row: Array,  # [T] int32 — owning row per packed token (R = padding)
    tok_pos: Array,  # [T] int32 — absolute position per packed token
    kv_len: Array,  # [R] int32 — valid KV per row incl. this dispatch's tokens
    layer: Array,  # [1] int32
    *,
    page_size: int,
    n_kv: int,
    scale: float | None = None,
    k_scales: Array | None = None,  # int8 cache: [L, P, SPAD, page_size] fp32
    v_scales: Array | None = None,
    kv_gap: Array | None = None,  # [R] int32 — bounded-KV window offset
) -> Array:
    """``jax.lax`` reference for the ragged kernel — the correctness oracle
    AND the CPU/tier-1 serving path (ops/dispatch.py backend "ref").

    Deliberately computed as per-token calls into the SAME ``gather_kv`` +
    ``mha_reference`` math the split-path reference backend uses (each
    packed token is one batch element with ``Sq = 1``): at fp32 a ragged
    dispatch is bitwise the split path's math per token, which is what the
    mixed-vs-split byte-identity gate (bench --ragged-sweep) leans on.
    Padding tokens (``tok_row == R``) read the trash row with ``kv_len 0``
    and produce zeros, exactly like an inactive decode slot.

    ``kv_gap`` (bounded KV, ISSUE 15 — see :func:`_compact_window`) is the
    per-row count of evicted tokens: the gather below already walks only
    the surviving pages (eviction compacted the page list), so the only
    change is the coordinate shift; None/zeros is bit-identical to the
    unbounded path.
    """
    from finchat_tpu.engine.kv_cache import gather_kv_any
    from finchat_tpu.ops.refs import mha_reference

    T = q.shape[0]
    R, MP = page_table.shape
    tok_pos, kv_len = _compact_window(tok_row, tok_pos, kv_len, kv_gap, R)
    lay = jnp.asarray(layer, jnp.int32).reshape(())
    # row R = an all-trash row with kv_len 0 (the padding-token row)
    pt_pad = jnp.concatenate(
        [jnp.asarray(page_table, jnp.int32), jnp.zeros((1, MP), jnp.int32)]
    )
    kv_pad = jnp.concatenate(
        [jnp.asarray(kv_len, jnp.int32), jnp.zeros((1,), jnp.int32)]
    )
    row = jnp.minimum(jnp.asarray(tok_row, jnp.int32), R)
    pt_tok = pt_pad[row]  # [T, MP] — per-token page row
    kv_tok = kv_pad[row]  # [T]
    k_all, v_all = gather_kv_any(
        k_pages, v_pages, k_scales, v_scales, pt_tok, page_size, lay, n_kv,
        dtype=q.dtype,
    )  # [T, MP*page_size, Hkv, hd]
    out = mha_reference(
        q[:, None], k_all, v_all, causal=True,
        q_offset=jnp.asarray(tok_pos, jnp.int32), kv_len=kv_tok, scale=scale,
    )  # [T, 1, H, D]
    return out[:, 0]


def _ragged_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32
    page_table_ref,  # [R+1, max_pages] int32 in SMEM (row R = trash)
    blk_row_ref,  # [NB] int32 — owning row per aligned q block (R = padding)
    aln_start_ref,  # [R+1] int32 — row's first aligned token index
    pos0_ref,  # [R+1] int32 — absolute position of the row's first q token
    qlen_ref,  # [R+1] int32 — real q tokens in the row
    kvlen_ref,  # [R+1] int32
    # blocks
    q_ref,  # [H, Bq, D]
    k_ref,  # [1, 1, page_size, Hkv*D] — one physical page
    v_ref,
    o_ref,  # [H, Bq, D]
    # scratch
    m_scr,  # [Rpad, 128] fp32
    l_scr,
    acc_scr,  # [Rpad, D] fp32
    *,
    block_q: int,
    page_size: int,
    n_kv: int,
    group: int,
    scale: float,
):
    j = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    Bq = block_q
    D = q_ref.shape[-1]
    Rh = group * Bq  # scratch rows per kv head
    r = blk_row_ref[j]
    pos0 = pos0_ref[r]
    a0 = aln_start_ref[r]
    q_len = qlen_ref[r]
    kv_len = kvlen_ref[r]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    page_start = p * page_size
    # last VALID q position is pos0 + q_len - 1; the block-level bound uses
    # the unclamped block end (an over-fetch of at most one page for the
    # alignment-padding rows — masked in compute, never wrong)
    q_max = pos0 + (j * Bq + Bq - 1 - a0)
    needed = jnp.logical_and(page_start < kv_len, page_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        rows = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 1)
        qi = j * Bq - a0 + rows % Bq  # token index WITHIN the row
        q_pos = pos0 + qi
        kv_pos = page_start + cols
        invalid = (kv_pos >= kv_len) | (kv_pos > q_pos) | (qi >= q_len)

        for h in range(n_kv):  # static unroll over kv heads
            q_blk = q_ref[h * group:(h + 1) * group].reshape(Rh, D)
            k_blk = k_ref[0, 0, :, h * D:(h + 1) * D]  # [PS, D] value slice
            v_blk = v_ref[0, 0, :, h * D:(h + 1) * D]
            r0 = h * Rh

            m_new, l_new, acc_new = _online_softmax_update(
                q_blk, k_blk, v_blk, invalid,
                m_scr[r0:r0 + Rh, :1], l_scr[r0:r0 + Rh, :1],
                acc_scr[r0:r0 + Rh], scale,
            )
            m_scr[r0:r0 + Rh, :1] = m_new
            l_scr[r0:r0 + Rh, :1] = l_new
            acc_scr[r0:r0 + Rh] = acc_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        R = n_kv * Rh
        # fully-masked rows (alignment padding, padding blocks) have l = 0
        # and finalize to exact zeros — discarded by the wrapper's gather
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[...] = out.reshape(n_kv * group, Bq, D).astype(o_ref.dtype)


def _ragged_kernel_q8(
    # scalar prefetch
    layer_ref,
    page_table_ref,
    blk_row_ref,
    aln_start_ref,
    pos0_ref,
    qlen_ref,
    kvlen_ref,
    # blocks
    q_ref,  # [H, Bq, D]
    k_ref,  # [1, 1, page_size, Hkv*D] int8 — one physical page
    v_ref,
    ks_ref,  # [1, 1, SPAD, page_size] fp32 — per-token-per-head scales
    vs_ref,
    o_ref,
    # scratch
    m_scr,
    l_scr,
    acc_scr,
    *,
    block_q: int,
    page_size: int,
    n_kv: int,
    group: int,
    scale: float,
):
    """Int8-KV variant: identical control flow; K/V tiles dequantize in
    VMEM (int8 page * per-token scale row) before the same online-softmax
    update — the ragged kernel joins the on-chip parity matrix (PARITY.md)
    at both cache dtypes."""
    j = pl.program_id(0)
    p = pl.program_id(1)
    n_pages = pl.num_programs(1)

    Bq = block_q
    D = q_ref.shape[-1]
    Rh = group * Bq
    r = blk_row_ref[j]
    pos0 = pos0_ref[r]
    a0 = aln_start_ref[r]
    q_len = qlen_ref[r]
    kv_len = kvlen_ref[r]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    page_start = p * page_size
    q_max = pos0 + (j * Bq + Bq - 1 - a0)
    needed = jnp.logical_and(page_start < kv_len, page_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        rows = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 1)
        qi = j * Bq - a0 + rows % Bq
        q_pos = pos0 + qi
        kv_pos = page_start + cols
        invalid = (kv_pos >= kv_len) | (kv_pos > q_pos) | (qi >= q_len)

        for h in range(n_kv):  # static unroll over kv heads
            q_blk = q_ref[h * group:(h + 1) * group].reshape(Rh, D)
            ks = ks_ref[0, 0, h, :][:, None]  # [PS, 1] per-token scale
            vs = vs_ref[0, 0, h, :][:, None]
            k_blk = (k_ref[0, 0, :, h * D:(h + 1) * D].astype(jnp.float32) * ks
                     ).astype(q_blk.dtype)
            v_blk = (v_ref[0, 0, :, h * D:(h + 1) * D].astype(jnp.float32) * vs
                     ).astype(q_blk.dtype)
            r0 = h * Rh

            m_new, l_new, acc_new = _online_softmax_update(
                q_blk, k_blk, v_blk, invalid,
                m_scr[r0:r0 + Rh, :1], l_scr[r0:r0 + Rh, :1],
                acc_scr[r0:r0 + Rh], scale,
            )
            m_scr[r0:r0 + Rh, :1] = m_new
            l_scr[r0:r0 + Rh, :1] = l_new
            acc_scr[r0:r0 + Rh] = acc_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        R = n_kv * Rh
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[...] = out.reshape(n_kv * group, Bq, D).astype(o_ref.dtype)


def _aligned_layout(tok_row, tok_pos, T: int, R: int, block_q: int):
    """Device-side packed→aligned layout: per-row lengths from the token→row
    map, rows padded up to ``block_q`` alignment (so every aligned block
    belongs to exactly one row), and the token scatter/gather index.

    Returns ``(dest [T], blk_row [NB], aln_start [R+1], pos0 [R+1],
    q_len [R+1], NB, TALN)`` — all int32; rows ``R`` entries are the
    padding row (0 tokens). Requires packed tokens sorted by row
    (contiguous spans, ascending) — the engine packs them that way.
    """
    tok_row = jnp.asarray(tok_row, jnp.int32)
    tok_pos = jnp.asarray(tok_pos, jnp.int32)
    TALN = _round_up(T + R * (block_q - 1), block_q)
    NB = TALN // block_q
    valid = tok_row < R
    seg = jnp.where(valid, tok_row, R)
    q_len = jax.ops.segment_sum(
        valid.astype(jnp.int32), seg, num_segments=R + 1
    ).astype(jnp.int32)
    q_len = q_len.at[R].set(0)
    q_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(q_len[:R], dtype=jnp.int32)]
    )  # [R+1] exclusive
    aln_len = -(-q_len // block_q) * block_q
    aln_start = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(aln_len[:R], dtype=jnp.int32)]
    )  # [R+1]
    tok_idx = jnp.arange(T, dtype=jnp.int32)
    dest = jnp.where(
        valid, aln_start[seg] + (tok_idx - q_start[seg]), TALN
    )  # TALN = dropped by mode="drop"
    blk_row = jnp.full((NB,), R, jnp.int32).at[dest // block_q].set(
        seg, mode="drop"
    )
    # absolute position of each row's first q token (0 for empty rows —
    # their kv_len/q_len of 0 masks everything anyway)
    is_first = (tok_idx == q_start[seg]) & valid
    pos0 = jnp.zeros((R + 1,), jnp.int32).at[
        jnp.where(is_first, seg, R + 1)
    ].set(tok_pos, mode="drop")
    return dest, blk_row, aln_start, pos0, q_len, NB, TALN


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "n_kv", "scale", "block_q", "interpret"),
)
def ragged_flash_attention(  # finchat-lint: hot
    q: Array,  # [T, H, D] packed
    k_pages: Array,  # [L, P, page_size, Hkv*D]
    v_pages: Array,
    page_table: Array,  # [R, max_pages]
    tok_row: Array,  # [T]
    tok_pos: Array,  # [T]
    kv_len: Array,  # [R]
    layer: Array,  # [1]
    *,
    page_size: int,
    n_kv: int,
    scale: float | None = None,
    block_q: int = 8,
    interpret: bool | None = None,
    kv_gap: Array | None = None,  # [R] int32 — bounded-KV window offset
) -> Array:
    """Ragged paged attention over the native-dtype cache; returns
    [T, H, D]. Same descriptor contract as ``ragged_paged_attention_ref``
    (the oracle tests pin them against each other). ``kv_gap`` shifts a
    bounded row into compacted coordinates at the wrapper level
    (:func:`_compact_window`) — the kernel body is gap-oblivious: its
    page-bound and causal masks simply run on the compacted inputs."""
    T, H, D = q.shape
    R, max_pages = page_table.shape
    assert H % n_kv == 0, (H, n_kv)
    assert k_pages.shape[2] == page_size, (k_pages.shape, page_size)
    assert k_pages.shape[3] == n_kv * D, (k_pages.shape, n_kv, D)
    group = H // n_kv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tok_pos, kv_len = _compact_window(tok_row, tok_pos, kv_len, kv_gap, R)

    layer = jnp.asarray(layer, jnp.int32)
    pt_pad = jnp.concatenate(
        [jnp.asarray(page_table, jnp.int32),
         jnp.zeros((1, max_pages), jnp.int32)]
    )
    kv_pad = jnp.concatenate(
        [jnp.asarray(kv_len, jnp.int32), jnp.zeros((1,), jnp.int32)]
    )
    dest, blk_row, aln_start, pos0, q_len, NB, TALN = _aligned_layout(
        tok_row, tok_pos, T, R, block_q
    )
    q_aln = jnp.zeros((TALN, H, D), q.dtype).at[dest].set(q, mode="drop")
    q_t = q_aln.transpose(1, 0, 2)  # [H, TALN, D] — head-major blocks

    r_pad = _round_up(max(H * block_q, 8), 8)

    def kv_index(j, p, layer_ref, pt_ref, blk_row_ref, aln_start_ref,
                 pos0_ref, qlen_ref, kvlen_ref):
        r = blk_row_ref[j]
        page_start = p * page_size
        q_max = pos0_ref[r] + (j + 1) * block_q - 1 - aln_start_ref[r]
        needed = jnp.logical_and(page_start < kvlen_ref[r],
                                 page_start <= q_max)
        phys = jnp.where(needed, pt_ref[r, p], TRASH_PAGE)
        return (layer_ref[0], phys, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(NB, max_pages),
        in_specs=[
            pl.BlockSpec((H, block_q, D), lambda j, p, *_: (0, j, 0)),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
        ],
        out_specs=pl.BlockSpec((H, block_q, D), lambda j, p, *_: (0, j, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        block_q=block_q, page_size=page_size, n_kv=n_kv, group=group,
        scale=scale,
    )
    o_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((H, TALN, D), q.dtype),
        interpret=interpret,
    )(layer, pt_pad, blk_row, aln_start, pos0, q_len, kv_pad, q_t,
      k_pages, v_pages)
    o_aln = o_t.transpose(1, 0, 2)  # [TALN, H, D]
    return jnp.take(o_aln, jnp.minimum(dest, TALN - 1), axis=0)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "n_kv", "scale", "block_q", "interpret"),
)
def ragged_flash_attention_q8(  # finchat-lint: hot
    q: Array,  # [T, H, D] packed
    k_pages: Array,  # [L, P, page_size, Hkv*D] int8
    v_pages: Array,
    k_scales: Array,  # [L, P, SPAD, page_size] fp32
    v_scales: Array,
    page_table: Array,
    tok_row: Array,
    tok_pos: Array,
    kv_len: Array,
    layer: Array,
    *,
    page_size: int,
    n_kv: int,
    scale: float | None = None,
    block_q: int = 8,
    interpret: bool | None = None,
    kv_gap: Array | None = None,  # [R] int32 — bounded-KV window offset
) -> Array:
    """Int8-KV ragged paged attention; same contract as
    ``ragged_flash_attention`` with the scale arrays riding the same
    scalar-prefetched page indirection (and the same wrapper-level
    bounded-KV coordinate shift)."""
    T, H, D = q.shape
    R, max_pages = page_table.shape
    assert H % n_kv == 0, (H, n_kv)
    assert k_pages.shape[2] == page_size, (k_pages.shape, page_size)
    assert k_pages.shape[3] == n_kv * D, (k_pages.shape, n_kv, D)
    assert k_scales.shape[3] == page_size, (k_scales.shape, page_size)
    group = H // n_kv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    tok_pos, kv_len = _compact_window(tok_row, tok_pos, kv_len, kv_gap, R)
    spad = k_scales.shape[2]

    layer = jnp.asarray(layer, jnp.int32)
    pt_pad = jnp.concatenate(
        [jnp.asarray(page_table, jnp.int32),
         jnp.zeros((1, max_pages), jnp.int32)]
    )
    kv_pad = jnp.concatenate(
        [jnp.asarray(kv_len, jnp.int32), jnp.zeros((1,), jnp.int32)]
    )
    dest, blk_row, aln_start, pos0, q_len, NB, TALN = _aligned_layout(
        tok_row, tok_pos, T, R, block_q
    )
    q_aln = jnp.zeros((TALN, H, D), q.dtype).at[dest].set(q, mode="drop")
    q_t = q_aln.transpose(1, 0, 2)

    r_pad = _round_up(max(H * block_q, 8), 8)

    def kv_index(j, p, layer_ref, pt_ref, blk_row_ref, aln_start_ref,
                 pos0_ref, qlen_ref, kvlen_ref):
        r = blk_row_ref[j]
        page_start = p * page_size
        q_max = pos0_ref[r] + (j + 1) * block_q - 1 - aln_start_ref[r]
        needed = jnp.logical_and(page_start < kvlen_ref[r],
                                 page_start <= q_max)
        phys = jnp.where(needed, pt_ref[r, p], TRASH_PAGE)
        return (layer_ref[0], phys, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(NB, max_pages),
        in_specs=[
            pl.BlockSpec((H, block_q, D), lambda j, p, *_: (0, j, 0)),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, spad, page_size), kv_index),
            pl.BlockSpec((1, 1, spad, page_size), kv_index),
        ],
        out_specs=pl.BlockSpec((H, block_q, D), lambda j, p, *_: (0, j, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel_q8,
        block_q=block_q, page_size=page_size, n_kv=n_kv, group=group,
        scale=scale,
    )
    o_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((H, TALN, D), q.dtype),
        interpret=interpret,
    )(layer, pt_pad, blk_row, aln_start, pos0, q_len, kv_pad, q_t,
      k_pages, v_pages, k_scales, v_scales)
    o_aln = o_t.transpose(1, 0, 2)
    return jnp.take(o_aln, jnp.minimum(dest, TALN - 1), axis=0)
