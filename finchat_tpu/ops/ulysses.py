"""Ulysses-style sequence parallelism: all-to-all head scatter (SURVEY §5.7d).

The alternative SP mode to ring attention (ops/ring_attention.py) for long
sequences, after DeepSpeed-Ulysses: instead of rotating K/V blocks around
the ring, ONE all-to-all redistributes the sharding from sequence-sharded
``[B, S/n, H, D]`` to head-sharded ``[B, S, H/n, D]``, each device runs
ordinary FULL-sequence attention over its head group, and a second
all-to-all restores sequence sharding. Two collectives total (vs n-1 ring
hops), at the cost of requiring ``heads % n == 0`` and a full-sequence
attention footprint per device — the right trade when heads are plentiful
and S fits once per chip; ring attention remains the mode for S beyond one
chip's HBM.

GQA note: K/V heads are scattered over the same axis, so ``n`` must divide
``n_kv_heads`` too (else fall back to ring). Head groups stay aligned with
GQA groups because the head axis is sharded in contiguous blocks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from finchat_tpu.parallel.mesh import pcast, shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from finchat_tpu.ops.refs import mha_reference


def _ulysses_prefix_body(q, k, v, kp, vp, prefix_len, *, axis: str, n: int,
                         varying: tuple, causal: bool, seg_block: int = 1024):
    """Per-device Ulysses attention for ONE SEGMENT of a longer sequence:
    head-scatter the segment as usual, then fold the CACHED prefix K/V
    (this device's head group of it) into the online-softmax carry before
    the segment's own causal attention — the same flash-decoding-style
    merge the chunked ring prefill uses (ops/ring_attention.py), in the
    Ulysses layout.

    In: q [B, S/n, H, D], k/v [B, S/n, Hkv, D] (seq shards);
    kp/vp [B, P, Hkv, D] (FULL prefix, replicated over the seq axis,
    padded past ``prefix_len``). Out: [B, S/n, H, D].
    """
    from finchat_tpu.ops.ring_attention import fold_prefix_blocks, online_fold

    def seq_to_heads(x):
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    q_h = seq_to_heads(q)  # [B, S, H/n, D] — full segment, my head group
    k_h = seq_to_heads(k)
    v_h = seq_to_heads(v)
    B, S, Hg, D = q_h.shape
    idx = lax.axis_index(axis)
    # my head group's slice of the prefix (contiguous blocks keep GQA
    # groups aligned, same invariant as the scatter itself)
    hkv_g = kp.shape[2] // n
    kp_g = lax.dynamic_slice_in_dim(kp, idx * hkv_g, hkv_g, axis=2)
    vp_g = lax.dynamic_slice_in_dim(vp, idx * hkv_g, hkv_g, axis=2)

    q32 = q_h.astype(jnp.float32)
    scale = D ** -0.5
    # fresh accumulators must be born device-varying to match the
    # seq-varying values folded into them (same pattern as _ring_body)
    m = pcast(jnp.full((B, Hg, S), -1e30, jnp.float32), varying, to="varying")
    l = pcast(jnp.zeros((B, Hg, S), jnp.float32), varying, to="varying")
    acc = pcast(jnp.zeros((B, Hg, S, D), jnp.float32), varying, to="varying")
    m, l, acc = fold_prefix_blocks(
        q32, kp_g, vp_g, prefix_len, m, l, acc, scale=scale, H=Hg,
    )
    # the segment itself: blockwise causal fold (index-causal — a constant
    # position offset does not change intra-segment causality)
    SB = min(seg_block, S)
    while S % SB:
        SB -= 1

    def fold_seg_block(b, carry):
        m, l, acc = carry
        k_blk = lax.dynamic_slice_in_dim(k_h, b * SB, SB, axis=1)
        v_blk = lax.dynamic_slice_in_dim(v_h, b * SB, SB, axis=1)
        kv_pos = b * SB + jnp.arange(SB)
        if causal:
            invalid = kv_pos[None, None, None, :] > jnp.arange(S)[None, None, :, None]
        else:
            invalid = jnp.zeros((1, 1, 1, SB), bool)
        return online_fold(q32, k_blk, v_blk, m, l, acc,
                           scale=scale, H=Hg, invalid=invalid)

    m, l, acc = lax.fori_loop(0, S // SB, fold_seg_block, (m, l, acc))
    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B, Hg, S, D]
    out = out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B, S, Hg, D]
    return heads_to_seq(out)


def _ulysses_body(q, k, v, *, axis: str, causal: bool):
    """Per-device function under shard_map.

    In: q [B, S/n, H, D], k/v [B, S/n, Hkv, D] (local shards).
    Out: [B, S/n, H, D].
    """
    # seq-sharded -> head-sharded: split the local head axis into n groups,
    # all-to-all exchanges (my seq block of your head group) so every device
    # ends with the FULL sequence of its own head group.
    def seq_to_heads(x):
        # [B, S/n, h, D] -> [B, S, h/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # [B, S, h/n, D] -> [B, S/n, h, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    q_h = seq_to_heads(q)
    k_h = seq_to_heads(k)
    v_h = seq_to_heads(v)
    out_h = mha_reference(q_h, k_h, v_h, causal=causal)
    return heads_to_seq(out_h)


def ulysses_supported(
    n_heads: int, n_kv_heads: int, mesh: Mesh,
    axis: str = "seq", head_axis: str | None = None,
) -> bool:
    """THE divisibility predicate for the head scatter — shared by
    ``ulysses_attention``'s own check and the engine's sp_mode resolution
    (engine/engine.py) so the two can never drift: per-TP-shard head
    counts (query AND kv) must divide by the seq-axis extent."""
    n = mesh.shape.get(axis, 1)
    tp = mesh.shape.get(head_axis, 1) if head_axis else 1
    return not (
        n_heads % tp or n_kv_heads % tp
        or (n_heads // tp) % n or (n_kv_heads // tp) % n
    )


@partial(jax.jit, static_argnames=("mesh", "axis", "batch_axis", "head_axis", "causal"))
def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] sharded on S over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: str | None = None,
    head_axis: str | None = None,
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention via head scatter; result sharded like q.
    ``batch_axis`` (DP) and ``head_axis`` (TP over heads) compose with the
    seq scatter — the all-to-all then redistributes each TP shard's heads.

    Requires the (per-TP-shard) head counts divisible by
    ``n = mesh.shape[axis]`` (checked); callers fall back to ring attention
    otherwise.
    """
    H, Hkv = q.shape[2], k.shape[2]
    if not ulysses_supported(H, Hkv, mesh, axis=axis, head_axis=head_axis):
        raise ValueError(
            f"ulysses needs per-shard heads divisible by the seq axis: "
            f"H={H}, Hkv={Hkv}, mesh={dict(mesh.shape)} — use ring attention instead"
        )
    spec = P(batch_axis, axis, head_axis, None)
    fn = shard_map(
        partial(_ulysses_body, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


@partial(jax.jit, static_argnames=("mesh", "axis", "batch_axis", "head_axis", "causal"))
def ulysses_attention_with_prefix(
    q: jax.Array,  # [B, S, H, D] sharded on S over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    k_prefix: jax.Array,  # [B, P, Hkv, D] cached earlier tokens (replicated
    v_prefix: jax.Array,  # over `axis`; may be padded past prefix_len)
    prefix_len: jax.Array,  # scalar int32 — valid prefix positions
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: str | None = None,
    head_axis: str | None = None,
    causal: bool = True,
) -> jax.Array:
    """Ulysses attention for ONE SEGMENT of a longer sequence (see
    ``_ulysses_prefix_body``) — what makes the chunked serving prefill
    available under ``sp_mode='ulysses'`` too, not just ring."""
    H, Hkv = q.shape[2], k.shape[2]
    if not ulysses_supported(H, Hkv, mesh, axis=axis, head_axis=head_axis):
        raise ValueError(
            f"ulysses needs per-shard heads divisible by the seq axis: "
            f"H={H}, Hkv={Hkv}, mesh={dict(mesh.shape)} — use ring attention instead"
        )
    n = mesh.shape[axis]
    varying = tuple(a for a in (batch_axis, axis, head_axis) if a)
    spec = P(batch_axis, axis, head_axis, None)
    pspec = P(batch_axis, None, head_axis, None)
    fn = shard_map(
        partial(_ulysses_prefix_body, axis=axis, n=n, varying=varying, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec, pspec, pspec, P()),
        out_specs=spec,
    )
    return fn(q, k, v, k_prefix, v_prefix, jnp.asarray(prefix_len, jnp.int32))
