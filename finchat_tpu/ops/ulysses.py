"""Ulysses-style sequence parallelism: all-to-all head scatter (SURVEY §5.7d).

The alternative SP mode to ring attention (ops/ring_attention.py) for long
sequences, after DeepSpeed-Ulysses: instead of rotating K/V blocks around
the ring, ONE all-to-all redistributes the sharding from sequence-sharded
``[B, S/n, H, D]`` to head-sharded ``[B, S, H/n, D]``, each device runs
ordinary FULL-sequence attention over its head group, and a second
all-to-all restores sequence sharding. Two collectives total (vs n-1 ring
hops), at the cost of requiring ``heads % n == 0`` and a full-sequence
attention footprint per device — the right trade when heads are plentiful
and S fits once per chip; ring attention remains the mode for S beyond one
chip's HBM.

GQA note: K/V heads are scattered over the same axis, so ``n`` must divide
``n_kv_heads`` too (else fall back to ring). Head groups stay aligned with
GQA groups because the head axis is sharded in contiguous blocks.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from finchat_tpu.ops.refs import mha_reference


def _ulysses_body(q, k, v, *, axis: str, causal: bool):
    """Per-device function under shard_map.

    In: q [B, S/n, H, D], k/v [B, S/n, Hkv, D] (local shards).
    Out: [B, S/n, H, D].
    """
    # seq-sharded -> head-sharded: split the local head axis into n groups,
    # all-to-all exchanges (my seq block of your head group) so every device
    # ends with the FULL sequence of its own head group.
    def seq_to_heads(x):
        # [B, S/n, h, D] -> [B, S, h/n, D]
        return lax.all_to_all(x, axis, split_axis=2, concat_axis=1, tiled=True)

    def heads_to_seq(x):
        # [B, S, h/n, D] -> [B, S/n, h, D]
        return lax.all_to_all(x, axis, split_axis=1, concat_axis=2, tiled=True)

    q_h = seq_to_heads(q)
    k_h = seq_to_heads(k)
    v_h = seq_to_heads(v)
    out_h = mha_reference(q_h, k_h, v_h, causal=causal)
    return heads_to_seq(out_h)


def ulysses_supported(
    n_heads: int, n_kv_heads: int, mesh: Mesh,
    axis: str = "seq", head_axis: str | None = None,
) -> bool:
    """THE divisibility predicate for the head scatter — shared by
    ``ulysses_attention``'s own check and the engine's sp_mode resolution
    (engine/engine.py) so the two can never drift: per-TP-shard head
    counts (query AND kv) must divide by the seq-axis extent."""
    n = mesh.shape.get(axis, 1)
    tp = mesh.shape.get(head_axis, 1) if head_axis else 1
    return not (
        n_heads % tp or n_kv_heads % tp
        or (n_heads // tp) % n or (n_kv_heads // tp) % n
    )


@partial(jax.jit, static_argnames=("mesh", "axis", "batch_axis", "head_axis", "causal"))
def ulysses_attention(
    q: jax.Array,  # [B, S, H, D] sharded on S over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: str | None = None,
    head_axis: str | None = None,
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention via head scatter; result sharded like q.
    ``batch_axis`` (DP) and ``head_axis`` (TP over heads) compose with the
    seq scatter — the all-to-all then redistributes each TP shard's heads.

    Requires the (per-TP-shard) head counts divisible by
    ``n = mesh.shape[axis]`` (checked); callers fall back to ring attention
    otherwise.
    """
    H, Hkv = q.shape[2], k.shape[2]
    if not ulysses_supported(H, Hkv, mesh, axis=axis, head_axis=head_axis):
        raise ValueError(
            f"ulysses needs per-shard heads divisible by the seq axis: "
            f"H={H}, Hkv={Hkv}, mesh={dict(mesh.shape)} — use ring attention instead"
        )
    spec = P(batch_axis, axis, head_axis, None)
    fn = jax.shard_map(
        partial(_ulysses_body, axis=axis, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
