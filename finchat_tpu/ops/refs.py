"""jnp reference implementations for every kernel in ``ops/``.

These are the correctness oracles (SURVEY §4.2): Pallas kernels are validated
against them in CPU interpret mode and on TPU. They are also the fallback
attention path on CPU, where Mosaic kernels don't run.

Numerics policy: bf16 inputs, fp32 softmax (logits and normalizer), bf16
output — the same policy the Pallas kernels implement.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

NEG_INF = -1e30  # large-negative mask value; avoids NaN from (-inf) - (-inf)


def gqa_repeat(kv: Array, n_heads: int) -> Array:
    """Broadcast KV heads up to the query head count for grouped-query
    attention. kv: [..., n_kv_heads, head_dim] -> [..., n_heads, head_dim]."""
    n_kv = kv.shape[-2]
    if n_kv == n_heads:
        return kv
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    reps = n_heads // n_kv
    return jnp.repeat(kv, reps, axis=-2)


def mha_reference(
    q: Array,  # [B, Sq, H, D]
    k: Array,  # [B, Sk, Hkv, D]
    v: Array,  # [B, Sk, Hkv, D]
    *,
    causal: bool = True,
    q_offset: Array | int = 0,  # absolute position of q[0] within the kv axis
    kv_len: Array | None = None,  # [B] valid kv length (rest is padding)
    scale: float | None = None,
) -> Array:
    """Masked multi-head attention with GQA, fp32 softmax.

    ``q_offset`` supports chunked prefill / decode: query row i has absolute
    position ``q_offset + i`` and may attend to kv positions ≤ its own.
    ``kv_len`` masks right-padding in the kv axis (per batch element).
    """
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = scale if scale is not None else D ** -0.5

    k = gqa_repeat(k, H)
    v = gqa_repeat(v, H)

    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale

    kv_pos = jnp.arange(Sk)[None, None, None, :]  # [1,1,1,Sk]
    mask = jnp.zeros((B, 1, Sq, Sk), dtype=bool)
    if causal:
        if jnp.ndim(q_offset) == 0:
            q_pos = jnp.broadcast_to(q_offset + jnp.arange(Sq), (B, Sq))
        else:
            q_pos = q_offset[:, None] + jnp.arange(Sq)[None, :]
        mask = mask | (kv_pos > q_pos[:, None, :, None])
    if kv_len is not None:
        mask = mask | (kv_pos >= kv_len[:, None, None, None])

    logits = jnp.where(mask, NEG_INF, logits)
    weights = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights.astype(v.dtype), v)
    return out.astype(q.dtype)
