"""Pallas ragged paged attention for TPU — the decode-side hot kernel.

SURVEY §7.3 hard part #1: this kernel gates the decode-throughput target.
The jnp reference path (engine/kv_cache.py ``gather_kv`` + ``mha_reference``)
materializes every sequence's pages into a dense ``[B, max_pages*page_size]``
KV copy per layer per step — reading AND writing the whole allocation-shaped
cache through HBM each token. This kernel instead reads K/V pages **in
place** via a scalar-prefetched page table, so per-step HBM traffic is
exactly the live KV bytes (ragged per sequence), with Pallas double-buffering
the page DMAs behind the MXU work.

Cache layout: ``[n_layers, P, page_size, Hkv*hd]`` — token-major pages,
heads fused into the minor dim (see engine/kv_cache.py for why). The kernel
takes the FULL-depth cache plus a scalar-prefetched layer index, because the
cache rides the model's layer scan as a carry; slicing one layer out with
XLA would copy it.

Design:
- grid ``(B, nq, max_pages)`` — page axis innermost; online-softmax state
  (m, l, acc) carries across a sequence's pages in VMEM scratch. All KV
  heads are processed in ONE program (a static inner unroll): TPU grid
  iterations cost ~1 µs each, and a per-(kv-head) grid axis multiplied the
  count by Hkv — ~30 ms/step of pure grid overhead at TinyLlama bench
  shapes (measured round 4, benchmarks/profile_decode.py).
- per-head K/V tiles are VALUE slices ``k_blk[:, h*hd:(h+1)*hd]`` of the
  loaded ``(page_size, Hkv*hd)`` block — in-kernel value slicing is exempt
  from Mosaic's DMA tile-alignment rules.
- the K/V BlockSpec index map resolves ``page_table[b, p]`` at DMA time
  (PrefetchScalarGridSpec); pages that are causally skippable or past
  ``kv_len[b]`` are redirected to the trash page (physical page 0, the same
  page the writers park padding in), and consecutive identical block
  indices are not re-fetched by the pipeline.
- GQA: each kv head's ``group = H // Hkv`` query heads ride in the same
  q block, so each page is fetched once per (b, q-block).

Serves both decode (C = 1) and paged chunked prefill (C = chunk) — the same
causal/ragged masking as ``ops.refs.mha_reference`` with ``q_offset``/
``kv_len`` semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from finchat_tpu.ops.flash_attention import (
    NEG_INF,
    _online_softmax_update,
    _pick_block,
    _round_up,
)

TRASH_PAGE = 0


def _paged_kernel(
    # scalar prefetch
    layer_ref,  # [1] int32
    page_table_ref,  # [B, max_pages] int32 in SMEM
    q_offset_ref,  # [B] int32
    kv_len_ref,  # [B] int32
    # blocks
    q_ref,  # [1, H, Bq, D]
    k_ref,  # [1, 1, page_size, Hkv*D] — one physical page
    v_ref,
    o_ref,  # [1, H, Bq, D]
    # scratch
    m_scr,  # [Rpad, 128] fp32
    l_scr,
    acc_scr,  # [Rpad, D] fp32
    *,
    block_q: int,
    page_size: int,
    n_kv: int,
    group: int,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    Bq = block_q
    D = q_ref.shape[-1]
    Rh = group * Bq  # scratch rows per kv head
    q_off = q_offset_ref[b]
    kv_len = kv_len_ref[b]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    page_start = p * page_size
    q_max = q_off + (qi + 1) * Bq - 1
    needed = jnp.logical_and(page_start < kv_len, page_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        rows = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 1)
        q_pos = q_off + qi * Bq + rows % Bq
        kv_pos = page_start + cols
        invalid = jnp.logical_or(kv_pos >= kv_len, kv_pos > q_pos)

        for h in range(n_kv):  # static unroll over kv heads
            # row r = (query head h*group + r // Bq), position r % Bq
            q_blk = q_ref[0, h * group:(h + 1) * group].reshape(Rh, D)
            k_blk = k_ref[0, 0, :, h * D:(h + 1) * D]  # [PS, D] value slice
            v_blk = v_ref[0, 0, :, h * D:(h + 1) * D]
            r0 = h * Rh

            m_new, l_new, acc_new = _online_softmax_update(
                q_blk, k_blk, v_blk, invalid,
                m_scr[r0:r0 + Rh, :1], l_scr[r0:r0 + Rh, :1],
                acc_scr[r0:r0 + Rh], scale,
            )
            m_scr[r0:r0 + Rh, :1] = m_new
            l_scr[r0:r0 + Rh, :1] = l_new
            acc_scr[r0:r0 + Rh] = acc_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        R = n_kv * Rh
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[0] = out.reshape(n_kv * group, Bq, D).astype(o_ref.dtype)


def _paged_kernel_q8(
    # scalar prefetch
    layer_ref,
    page_table_ref,
    q_offset_ref,
    kv_len_ref,
    # blocks
    q_ref,  # [1, H, Bq, D]
    k_ref,  # [1, 1, page_size, Hkv*D] int8 — one physical page
    v_ref,
    ks_ref,  # [1, 1, SPAD, page_size] fp32 — per-token-per-head scales
    vs_ref,
    o_ref,
    # scratch
    m_scr,
    l_scr,
    acc_scr,
    *,
    block_q: int,
    page_size: int,
    n_kv: int,
    group: int,
    scale: float,
):
    """Int8-KV variant of ``_paged_kernel``: identical control flow; K/V
    tiles dequantize in VMEM (int8 page * per-token scale row) before the
    same online-softmax update, so HBM streams half the KV bytes."""
    b = pl.program_id(0)
    qi = pl.program_id(1)
    p = pl.program_id(2)
    n_pages = pl.num_programs(2)

    Bq = block_q
    D = q_ref.shape[-1]
    Rh = group * Bq
    q_off = q_offset_ref[b]
    kv_len = kv_len_ref[b]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    page_start = p * page_size
    q_max = q_off + (qi + 1) * Bq - 1
    needed = jnp.logical_and(page_start < kv_len, page_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        rows = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Rh, page_size), 1)
        q_pos = q_off + qi * Bq + rows % Bq
        kv_pos = page_start + cols
        invalid = jnp.logical_or(kv_pos >= kv_len, kv_pos > q_pos)

        for h in range(n_kv):  # static unroll over kv heads
            q_blk = q_ref[0, h * group:(h + 1) * group].reshape(Rh, D)
            ks = ks_ref[0, 0, h, :][:, None]  # [PS, 1] per-token scale
            vs = vs_ref[0, 0, h, :][:, None]
            k_blk = (k_ref[0, 0, :, h * D:(h + 1) * D].astype(jnp.float32) * ks
                     ).astype(q_blk.dtype)
            v_blk = (v_ref[0, 0, :, h * D:(h + 1) * D].astype(jnp.float32) * vs
                     ).astype(q_blk.dtype)
            r0 = h * Rh

            m_new, l_new, acc_new = _online_softmax_update(
                q_blk, k_blk, v_blk, invalid,
                m_scr[r0:r0 + Rh, :1], l_scr[r0:r0 + Rh, :1],
                acc_scr[r0:r0 + Rh], scale,
            )
            m_scr[r0:r0 + Rh, :1] = m_new
            l_scr[r0:r0 + Rh, :1] = l_new
            acc_scr[r0:r0 + Rh] = acc_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        R = n_kv * Rh
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[0] = out.reshape(n_kv * group, Bq, D).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "n_kv", "scale", "block_q", "interpret"),
)
def paged_flash_attention_q8(
    q: Array,  # [B, C, H, D]
    k_pages: Array,  # [L, P, page_size, Hkv*D] int8
    v_pages: Array,
    k_scales: Array,  # [L, P, SPAD, page_size] fp32
    v_scales: Array,
    page_table: Array,
    q_offset: Array,
    kv_len: Array,
    layer: Array,
    *,
    page_size: int,
    n_kv: int,
    scale: float | None = None,
    block_q: int = 128,
    interpret: bool | None = None,
) -> Array:
    """Attention over the int8 paged KV cache; same contract as
    ``paged_flash_attention`` with the scale arrays riding the same
    scalar-prefetched page indirection."""
    B, C, H, D = q.shape
    max_pages = page_table.shape[1]
    assert H % n_kv == 0, (H, n_kv)
    assert k_pages.shape[2] == page_size, (k_pages.shape, page_size)
    assert k_pages.shape[3] == n_kv * D, (k_pages.shape, n_kv, D)
    assert k_scales.shape[3] == page_size, (k_scales.shape, page_size)
    group = H // n_kv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    q_offset = jnp.asarray(q_offset, jnp.int32)
    kv_len = jnp.asarray(kv_len, jnp.int32)
    page_table = jnp.asarray(page_table, jnp.int32)
    layer = jnp.asarray(layer, jnp.int32)

    bq = _pick_block(C, block_q)
    nq = C // bq
    r_pad = _round_up(max(H * bq, 8), 8)
    spad = k_scales.shape[2]

    q_t = q.transpose(0, 2, 1, 3)  # [B, H, C, D]

    def kv_index(b, qi, p, layer_ref, page_table_ref, q_offset_ref, kv_len_ref):
        page_start = p * page_size
        q_max = q_offset_ref[b] + (qi + 1) * bq - 1
        needed = jnp.logical_and(page_start < kv_len_ref[b], page_start <= q_max)
        phys = jnp.where(needed, page_table_ref[b, p], TRASH_PAGE)
        return (layer_ref[0], phys, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, nq, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, bq, D), lambda b, qi, p, *_: (b, 0, qi, 0)),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, spad, page_size), kv_index),
            pl.BlockSpec((1, 1, spad, page_size), kv_index),
        ],
        out_specs=pl.BlockSpec((1, H, bq, D), lambda b, qi, p, *_: (b, 0, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel_q8,
        block_q=bq, page_size=page_size, n_kv=n_kv, group=group, scale=scale,
    )
    out_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C, D), q.dtype),
        interpret=interpret,
    )(layer, page_table, q_offset, kv_len, q_t, k_pages, v_pages, k_scales, v_scales)
    return out_t.transpose(0, 2, 1, 3)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "n_kv", "scale", "block_q", "interpret"),
)
def paged_flash_attention(
    q: Array,  # [B, C, H, D] — C = 1 for decode, chunk size for prefill
    k_pages: Array,  # [L, P, page_size, Hkv*D] — full-depth cache, in place
    v_pages: Array,
    page_table: Array,  # [B, max_pages] int32 physical page ids (0 = trash)
    q_offset: Array,  # [B] int32 — absolute position of q[:, 0]
    kv_len: Array,  # [B] int32 — valid KV length incl. this chunk's tokens
    layer: Array,  # [1] int32 — which layer's pages to read
    *,
    page_size: int,
    n_kv: int,
    scale: float | None = None,
    block_q: int = 128,
    interpret: bool | None = None,
) -> Array:
    """Attention over the paged KV cache; returns [B, C, H, D].

    Causal with absolute positions (query row i of batch b is at
    ``q_offset[b] + i``); sequences with ``kv_len == 0`` produce zeros.
    The current chunk's K/V must already be in the pages (the decode append
    kernel or the prefill scatter runs first).
    """
    B, C, H, D = q.shape
    max_pages = page_table.shape[1]
    assert H % n_kv == 0, (H, n_kv)
    assert k_pages.shape[2] == page_size, (k_pages.shape, page_size)
    assert k_pages.shape[3] == n_kv * D, (k_pages.shape, n_kv, D)
    group = H // n_kv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    q_offset = jnp.asarray(q_offset, jnp.int32)
    kv_len = jnp.asarray(kv_len, jnp.int32)
    page_table = jnp.asarray(page_table, jnp.int32)
    layer = jnp.asarray(layer, jnp.int32)

    bq = _pick_block(C, block_q)
    nq = C // bq
    r_pad = _round_up(max(H * bq, 8), 8)

    q_t = q.transpose(0, 2, 1, 3)  # [B, H, C, D]

    def kv_index(b, qi, p, layer_ref, page_table_ref, q_offset_ref, kv_len_ref):
        # resolve logical page -> physical page at DMA time; redirect pages
        # that contribute nothing to the trash page (repeat fetches of the
        # same block index are skipped by the pipeline)
        page_start = p * page_size
        q_max = q_offset_ref[b] + (qi + 1) * bq - 1
        needed = jnp.logical_and(page_start < kv_len_ref[b], page_start <= q_max)
        phys = jnp.where(needed, page_table_ref[b, p], TRASH_PAGE)
        return (layer_ref[0], phys, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, nq, max_pages),
        in_specs=[
            pl.BlockSpec((1, H, bq, D), lambda b, qi, p, *_: (b, 0, qi, 0)),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
            pl.BlockSpec((1, 1, page_size, n_kv * D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, H, bq, D), lambda b, qi, p, *_: (b, 0, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        block_q=bq, page_size=page_size, n_kv=n_kv, group=group, scale=scale,
    )
    out_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C, D), q.dtype),
        interpret=interpret,
    )(layer, page_table, q_offset, kv_len, q_t, k_pages, v_pages)
    return out_t.transpose(0, 2, 1, 3)
