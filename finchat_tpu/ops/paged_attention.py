"""Pallas ragged paged attention for TPU — the decode-side hot kernel.

SURVEY §7.3 hard part #1: this kernel gates the decode-throughput target.
The jnp reference path (engine/kv_cache.py ``gather_kv`` + ``mha_reference``)
materializes every sequence's pages into a dense ``[B, max_pages*page_size]``
KV copy per layer per step — reading AND writing the whole allocation-shaped
cache through HBM each token. This kernel instead reads K/V pages **in
place** via a scalar-prefetched page table, so per-step HBM traffic is
exactly the live KV bytes (ragged per sequence), with Pallas double-buffering
the page DMAs behind the MXU work.

Design:
- grid ``(B, Hkv, nq, max_pages)`` — page axis innermost; online-softmax
  state (m, l, acc) carries across a sequence's pages in VMEM scratch.
- the K/V BlockSpec index map resolves ``page_table[b, p]`` at DMA time
  (PrefetchScalarGridSpec); pages that are causally skippable or past
  ``kv_len[b]`` are redirected to the trash page (physical page 0, the same
  page the cache scatter parks padding writes in — engine/kv_cache.py), and
  consecutive identical block indices are not re-fetched by the pipeline.
- pages are head-major ``[P, Hkv, page_size, head_dim]`` so one (page,
  kv-head) DMA is a contiguous Mosaic-tileable (page_size, head_dim) tile.
- GQA: one program per KV head; its ``group = H // Hkv`` query heads ride in
  the same block, so each page's K/V slice is fetched once total.

Serves both decode (C = 1) and paged chunked prefill (C = chunk) — the same
causal/ragged masking as ``ops.refs.mha_reference`` with ``q_offset``/
``kv_len`` semantics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from finchat_tpu.ops.flash_attention import (
    NEG_INF,
    _online_softmax_update,
    _pick_block,
    _round_up,
)

TRASH_PAGE = 0


def _paged_kernel(
    # scalar prefetch
    page_table_ref,  # [B, max_pages] int32 in SMEM
    q_offset_ref,  # [B] int32
    kv_len_ref,  # [B] int32
    # blocks (head-major)
    q_ref,  # [1, G, Bq, D]
    k_ref,  # [1, 1, page_size, D] — one physical page, one KV head
    v_ref,
    o_ref,  # [1, G, Bq, D]
    # scratch
    m_scr,  # [Rpad, 128] fp32
    l_scr,
    acc_scr,  # [Rpad, D] fp32
    *,
    block_q: int,
    page_size: int,
    group: int,
    scale: float,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    p = pl.program_id(3)
    n_pages = pl.num_programs(3)

    Bq = block_q
    R = group * Bq
    q_off = q_offset_ref[b]
    kv_len = kv_len_ref[b]

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    page_start = p * page_size
    q_max = q_off + (qi + 1) * Bq - 1
    needed = jnp.logical_and(page_start < kv_len, page_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        q_blk = q_ref[0].reshape(R, q_ref.shape[3])  # row r = head r//Bq, pos r%Bq
        k_blk = k_ref[0, 0]  # [page_size, D]
        v_blk = v_ref[0, 0]

        rows = jax.lax.broadcasted_iota(jnp.int32, (R, page_size), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, page_size), 1)
        q_pos = q_off + qi * Bq + rows % Bq
        kv_pos = page_start + cols
        invalid = jnp.logical_or(kv_pos >= kv_len, kv_pos > q_pos)

        m_new, l_new, acc_new = _online_softmax_update(
            q_blk, k_blk, v_blk, invalid,
            m_scr[:R, :1], l_scr[:R, :1], acc_scr[:R], scale,
        )
        m_scr[:R, :1] = m_new
        l_scr[:R, :1] = l_new
        acc_scr[:R] = acc_new

    @pl.when(p == n_pages - 1)
    def _finalize():
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[0] = out.reshape(group, Bq, -1).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("page_size", "scale", "block_q", "interpret"),
)
def paged_flash_attention(
    q: Array,  # [B, C, H, D] — C = 1 for decode, chunk size for prefill
    k_pages: Array,  # [P, Hkv, page_size, D] — one layer's pages, in place
    v_pages: Array,
    page_table: Array,  # [B, max_pages] int32 physical page ids (0 = trash)
    q_offset: Array,  # [B] int32 — absolute position of q[:, 0]
    kv_len: Array,  # [B] int32 — valid KV length incl. this chunk's tokens
    *,
    page_size: int,
    scale: float | None = None,
    block_q: int = 128,
    interpret: bool | None = None,
) -> Array:
    """Attention over the paged KV cache; returns [B, C, H, D].

    Causal with absolute positions (query row i of batch b is at
    ``q_offset[b] + i``); sequences with ``kv_len == 0`` produce zeros.
    The current chunk's K/V must already be scattered into the pages
    (engine/kv_cache.py ``scatter_kv_chunk`` runs first).
    """
    B, C, H, D = q.shape
    Hkv = k_pages.shape[1]
    max_pages = page_table.shape[1]
    assert H % Hkv == 0, (H, Hkv)
    assert k_pages.shape[2] == page_size, (k_pages.shape, page_size)
    group = H // Hkv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    q_offset = jnp.asarray(q_offset, jnp.int32)
    kv_len = jnp.asarray(kv_len, jnp.int32)
    page_table = jnp.asarray(page_table, jnp.int32)

    bq = _pick_block(C, block_q)
    nq = C // bq
    r_pad = _round_up(max(group * bq, 8), 8)

    q_t = q.transpose(0, 2, 1, 3)  # [B, H, C, D]

    def kv_index(b, h, qi, p, page_table_ref, q_offset_ref, kv_len_ref):
        # resolve logical page -> physical page at DMA time; redirect pages
        # that contribute nothing to the trash page (repeat fetches of the
        # same block index are skipped by the pipeline)
        page_start = p * page_size
        q_max = q_offset_ref[b] + (qi + 1) * bq - 1
        needed = jnp.logical_and(page_start < kv_len_ref[b], page_start <= q_max)
        phys = jnp.where(needed, page_table_ref[b, p], TRASH_PAGE)
        return (phys, h, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, Hkv, nq, max_pages),
        in_specs=[
            pl.BlockSpec((1, group, bq, D), lambda b, h, qi, p, *_: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, page_size, D), kv_index),
            pl.BlockSpec((1, 1, page_size, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, group, bq, D), lambda b, h, qi, p, *_: (b, h, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        block_q=bq, page_size=page_size, group=group, scale=scale,
    )
    out_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, C, D), q.dtype),
        interpret=interpret,
    )(page_table, q_offset, kv_len, q_t, k_pages, v_pages)
    return out_t.transpose(0, 2, 1, 3)
