"""Ring attention over the ``seq`` mesh axis (SURVEY §5.7c).

Long-prefill RAG prompts (unbounded history + up to 10k retrieved
transactions, reference qdrant_tool.py:145 / llm_agent.py:234-236) are the
scaling axis this product actually has. Ring attention shards the sequence
across devices: each device keeps its Q block resident and the K/V blocks
rotate around the ICI ring via ``ppermute``, with a blockwise online-softmax
accumulation — peak memory O(S/n) per device, comms overlapped with compute
by XLA's collective scheduler.

Math: the standard streaming-softmax recurrence. Fully-masked blocks are
handled by zeroing probabilities under the mask (never exp'ing a -inf
difference), so intermediate ring steps that a causal Q block cannot see
contribute exactly nothing.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from finchat_tpu.parallel.mesh import pcast, shard_map
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from finchat_tpu.ops.refs import gqa_repeat

_NEG = -1e30


def online_fold(q32, k_blk, v_blk, m, l, acc, *, scale: float, H: int, invalid):
    """One streaming-softmax accumulation step shared by every attention
    body that merges multiple K/V sources (ring hops, cached-prefix
    blocks, causal segment blocks): fold ``k_blk``/``v_blk`` [B, K, Hkv, D]
    into the carry (m, l, acc) for queries ``q32`` [B, Sq, H, D] fp32.
    ``invalid`` broadcasts against the [B, H, Sq, K] logits; masked
    probabilities are zeroed explicitly so fully-masked blocks contribute
    exactly nothing (never exp'ing a -inf difference)."""
    k_rep = gqa_repeat(k_blk, H)
    v_rep = gqa_repeat(v_blk, H)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q32, k_rep.astype(jnp.float32)) * scale
    logits = jnp.where(invalid, _NEG, logits)
    m_new = jnp.maximum(m, logits.max(axis=-1))
    p = jnp.where(invalid, 0.0, jnp.exp(logits - m_new[..., None]))
    corr = jnp.exp(m - m_new)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_rep.astype(jnp.float32)
    )
    return m_new, l_new, acc_new


def fold_prefix_blocks(q32, kp, vp, prefix_len, m, l, acc, *,
                       scale: float, H: int, prefix_block: int = 1024):
    """Fold a cached, possibly-padded K/V prefix [B, P, Hkv, D] into the
    online-softmax carry, blockwise so [Sq, P] logits never materialize
    at full prefix length. Every prefix position precedes every query by
    construction; only the ``pos >= prefix_len`` padding tail masks."""
    P = kp.shape[1]
    PB = min(prefix_block, P)
    while P % PB:  # static: blocks must tile the prefix exactly, or
        PB -= 1    # the clamped last dynamic_slice would misposition

    def fold_block(b, carry):
        m, l, acc = carry
        k_blk = lax.dynamic_slice_in_dim(kp, b * PB, PB, axis=1)
        v_blk = lax.dynamic_slice_in_dim(vp, b * PB, PB, axis=1)
        pos = b * PB + jnp.arange(PB)
        invalid = (pos >= prefix_len)[None, None, None, :]
        return online_fold(q32, k_blk, v_blk, m, l, acc,
                           scale=scale, H=H, invalid=invalid)

    return lax.fori_loop(0, P // PB, fold_block, (m, l, acc))


def _ring_body(q, k0, v0, *, axis: str, varying: tuple, n_blocks: int, causal: bool, scale: float,
               prefix=None, prefix_block: int = 1024):
    """Per-device function under shard_map. q/k0/v0: [B, Sblk, H(kv), D].

    ``prefix`` (segmented serving prefill): an optional
    ``(k_prefix, v_prefix, prefix_len)`` of ALREADY-CACHED earlier
    tokens, replicated over the seq axis. Every prefix position precedes
    every Q row by construction, so the fold is unmasked except for the
    ``pos >= prefix_len`` tail (page-table padding). It seeds the online-
    softmax carry BEFORE the ring steps — the flash-decoding-style merge
    that lets a long prefill run as segments without losing cross-segment
    attention. Folded blockwise (``prefix_block``) so the [Sq, P] logits
    never materialize at full prefix length."""
    B, Sq, H, D = q.shape
    idx = lax.axis_index(axis)
    q_pos = idx * Sq + jnp.arange(Sq)  # global positions of my Q rows

    q32 = q.astype(jnp.float32)

    def accumulate(t, m, l, acc, k_cur, v_cur):
        """Fold the currently-held KV block into the online softmax."""
        src = (idx - t) % n_blocks  # which global block we hold at step t
        kv_pos = src * Sq + jnp.arange(k_cur.shape[1])

        def update(m, l, acc):
            if causal:
                invalid = kv_pos[None, None, None, :] > q_pos[None, None, :, None]
            else:
                invalid = jnp.zeros((1, 1, 1, k_cur.shape[1]), bool)
            return online_fold(q32, k_cur, v_cur, m, l, acc,
                               scale=scale, H=H, invalid=invalid)

        if not causal:
            return update(m, l, acc)
        # skip blocks that are entirely in this Q block's future (~half the
        # ring steps); predicate is local-only — no collectives under cond
        return lax.cond(src <= idx, update, lambda m, l, acc: (m, l, acc), m, l, acc)

    perm = [(i, (i + 1) % n_blocks) for i in range(n_blocks)]

    def step(t, carry):
        m, l, acc, k_cur, v_cur = carry
        m, l, acc = accumulate(t, m, l, acc, k_cur, v_cur)
        k_next = lax.ppermute(k_cur, axis, perm)
        v_next = lax.ppermute(v_cur, axis, perm)
        return m, l, acc, k_next, v_next

    # mark the accumulators device-varying so the fori_loop carry types match
    # (they're combined with ring-varying k/v inside the loop)
    m0 = pcast(jnp.full((B, H, Sq), _NEG, jnp.float32), varying, to="varying")
    l0 = pcast(jnp.zeros((B, H, Sq), jnp.float32), varying, to="varying")
    acc0 = pcast(jnp.zeros((B, H, Sq, D), jnp.float32), varying, to="varying")

    if prefix is not None:
        kp, vp, prefix_len = prefix
        m0, l0, acc0 = fold_prefix_blocks(
            q32, kp, vp, prefix_len, m0, l0, acc0,
            scale=scale, H=H, prefix_block=prefix_block,
        )
    # n_blocks-1 steps each ending in a ring hop; the final block is folded
    # in WITHOUT the trailing (discarded) ppermute pair
    m, l, acc, k_last, v_last = lax.fori_loop(
        0, n_blocks - 1, step, (m0, l0, acc0, k0, v0)
    )
    m, l, acc = accumulate(n_blocks - 1, m, l, acc, k_last, v_last)

    out = acc / jnp.maximum(l, 1e-30)[..., None]  # [B,H,Sq,D]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)  # [B,Sq,H,D]


@partial(jax.jit, static_argnames=("mesh", "axis", "batch_axis", "head_axis", "causal"))
def ring_attention(
    q: jax.Array,  # [B, S, H, D] sharded on S over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: str | None = None,
    head_axis: str | None = None,
    causal: bool = True,
) -> jax.Array:
    """Sequence-parallel attention; result sharded like q. ``batch_axis``
    (DP) and ``head_axis`` (TP over heads) compose with the seq ring."""
    n_blocks = mesh.shape[axis]
    scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, axis, head_axis, None)
    varying = tuple(a for a in (batch_axis, axis, head_axis) if a)
    fn = shard_map(
        partial(_ring_body, axis=axis, varying=varying, n_blocks=n_blocks, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)


@partial(jax.jit, static_argnames=("mesh", "axis", "batch_axis", "head_axis", "causal"))
def ring_attention_with_prefix(
    q: jax.Array,  # [B, S, H, D] sharded on S over `axis`
    k: jax.Array,  # [B, S, Hkv, D]
    v: jax.Array,
    k_prefix: jax.Array,  # [B, P, Hkv, D] cached earlier tokens (replicated
    v_prefix: jax.Array,  # over `axis`; may be padded past prefix_len)
    prefix_len: jax.Array,  # scalar int32 — valid prefix positions
    *,
    mesh: Mesh,
    axis: str = "seq",
    batch_axis: str | None = None,
    head_axis: str | None = None,
    causal: bool = True,
) -> jax.Array:
    """Ring attention for ONE SEGMENT of a longer sequence: the segment's
    Q/K/V ride the ring exactly as in ``ring_attention`` (intra-segment
    causality is offset-invariant), while the already-cached prefix K/V is
    folded into each device's online-softmax carry first. This is what
    makes the seq-sharded serving prefill chunkable — segments interleave
    with decode steps instead of one monolithic stall — without losing
    attention to earlier segments."""
    n_blocks = mesh.shape[axis]
    scale = q.shape[-1] ** -0.5
    spec = P(batch_axis, axis, head_axis, None)
    pspec = P(batch_axis, None, head_axis, None)  # prefix: whole copy per seq shard
    varying = tuple(a for a in (batch_axis, axis, head_axis) if a)

    def body(q, k0, v0, kp, vp, plen):
        return _ring_body(
            q, k0, v0, axis=axis, varying=varying, n_blocks=n_blocks,
            causal=causal, scale=scale, prefix=(kp, vp, plen),
        )

    fn = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, spec, pspec, pspec, P()),
        out_specs=spec,
    )
    return fn(q, k, v, k_prefix, v_prefix, jnp.asarray(prefix_len, jnp.int32))
