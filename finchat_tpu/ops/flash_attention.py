"""Pallas flash attention (contiguous KV) for TPU.

Blocked online-softmax attention — the prefill-side hot kernel (SURVEY §7.2
step 4). Replaces the all-at-once ``mha_reference`` (ops/refs.py), which
materializes the full [B, H, Sq, Sk] logit tensor in HBM; this kernel keeps
one (block_q × block_k) logit tile in VMEM at a time, so HBM traffic is
O(Q + K + V + O) instead of O(Sq·Sk).

Semantics match ``mha_reference`` exactly (same masking, same fp32-softmax /
bf16-PV numerics):

- causal with ``q_offset``: query row i has absolute position
  ``q_offset[b] + i`` within the KV axis (chunked prefill / decode);
- ``kv_len[b]`` masks KV right-padding per batch element;
- GQA: KV heads are grouped, never materialized at H (the grid iterates KV
  heads; each program handles that head's ``group = H // Hkv`` query heads).

Layout: kernels run head-major ([B, H, S, D]) so every block's trailing two
dims are a Mosaic-tileable (rows, head_dim) tile; the public API stays
[B, S, H, D] and the wrapper transposes (XLA fuses these into neighbors).

Grid layout: ``(B, Hkv, nq, nk)`` with the KV-block axis innermost, so the
m/l/acc scratch accumulators carry across KV blocks of one (batch, kv-head,
q-block) program family. Fully-future causal blocks are compute-skipped via
``pl.when``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _pick_block(size: int, preferred: int) -> int:
    """Largest power-of-two block ≤ preferred that divides size."""
    b = min(preferred, size)
    while size % b:
        b //= 2
    return max(b, 1)


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _online_softmax_update(
    q_blk: Array,  # [R, D] (R = group * block_q) input dtype
    k_blk: Array,  # [Bk, D]
    v_blk: Array,  # [Bk, D]
    invalid: Array,  # [R, Bk] bool — masked-out logits
    m_prev: Array,  # [R, 1] fp32
    l_prev: Array,  # [R, 1] fp32
    acc_prev: Array,  # [R, D] fp32
    scale: float,
) -> tuple[Array, Array, Array]:
    """One flash-attention block update, fp32 softmax state."""
    s = jax.lax.dot_general(
        q_blk, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    s = s * scale
    s = jnp.where(invalid, NEG_INF, s)

    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    # explicit zeroing: rows whose every logit is masked have m_new = NEG_INF
    # and exp(s - m_new) = 1 there — the mask, not the exp, must decide
    p = jnp.where(invalid, 0.0, jnp.exp(s - m_new))
    correction = jnp.exp(m_prev - m_new)
    l_new = l_prev * correction + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc_prev * correction + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _flash_kernel(
    # scalar prefetch
    q_offset_ref,  # [B] int32 in SMEM
    kv_len_ref,  # [B] int32
    # blocks (head-major)
    q_ref,  # [1, G, Bq, D]
    k_ref,  # [1, 1, Bk, D]
    v_ref,  # [1, 1, Bk, D]
    o_ref,  # [1, G, Bq, D]
    # scratch
    m_scr,  # [Rpad, 128] fp32
    l_scr,
    acc_scr,  # [Rpad, D] fp32
    *,
    block_q: int,
    block_k: int,
    group: int,
    scale: float,
    causal: bool,
):
    b = pl.program_id(0)
    qi = pl.program_id(2)
    ki = pl.program_id(3)
    nk = pl.num_programs(3)

    Bq, Bk = block_q, block_k
    R = group * Bq  # rows = (query head within group) × (query position)
    q_off = q_offset_ref[b]
    kv_len = kv_len_ref[b]

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[:] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[:] = jnp.zeros(acc_scr.shape, jnp.float32)

    # block-level skip: KV block entirely after this Q block's last row, or
    # entirely past the valid KV length
    q_max = q_off + (qi + 1) * Bq - 1
    k_start = ki * Bk
    needed = k_start < kv_len
    if causal:
        needed = jnp.logical_and(needed, k_start <= q_max)

    @pl.when(needed)
    def _accumulate():
        q_blk = q_ref[0].reshape(R, q_ref.shape[3])  # row r = head r//Bq, pos r%Bq
        k_blk = k_ref[0, 0]  # [Bk, D]
        v_blk = v_ref[0, 0]

        rows = jax.lax.broadcasted_iota(jnp.int32, (R, Bk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (R, Bk), 1)
        q_pos = q_off + qi * Bq + rows % Bq
        kv_pos = k_start + cols
        invalid = kv_pos >= kv_len
        if causal:
            invalid = jnp.logical_or(invalid, kv_pos > q_pos)

        m_new, l_new, acc_new = _online_softmax_update(
            q_blk, k_blk, v_blk, invalid,
            m_scr[:R, :1], l_scr[:R, :1], acc_scr[:R], scale,
        )
        m_scr[:R, :1] = m_new
        l_scr[:R, :1] = l_new
        acc_scr[:R] = acc_new

    @pl.when(ki == nk - 1)
    def _finalize():
        out = acc_scr[:R] / jnp.maximum(l_scr[:R, :1], 1e-30)
        o_ref[0] = out.reshape(group, Bq, -1).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret"),
)
def flash_attention(
    q: Array,  # [B, Sq, H, D]
    k: Array,  # [B, Sk, Hkv, D]
    v: Array,  # [B, Sk, Hkv, D]
    *,
    q_offset: Array | None = None,  # [B] int32 — abs position of q[:, 0]
    kv_len: Array | None = None,  # [B] int32 — valid KV length
    causal: bool = True,
    scale: float | None = None,
    block_q: int = 128,
    block_k: int = 256,
    interpret: bool | None = None,
) -> Array:
    """Drop-in Pallas replacement for ``ops.refs.mha_reference``."""
    B, Sq, H, D = q.shape
    Sk, Hkv = k.shape[1], k.shape[2]
    assert H % Hkv == 0, (H, Hkv)
    group = H // Hkv
    scale = scale if scale is not None else D ** -0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    if q_offset is None:
        q_offset = jnp.zeros((B,), jnp.int32)
    else:
        q_offset = jnp.broadcast_to(jnp.asarray(q_offset, jnp.int32), (B,))
    if kv_len is None:
        kv_len = jnp.full((B,), Sk, jnp.int32)
    else:
        kv_len = jnp.asarray(kv_len, jnp.int32)

    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    nq, nk = Sq // bq, Sk // bk
    r_pad = _round_up(max(group * bq, 8), 8)

    # head-major layouts for Mosaic-aligned trailing dims
    q_t = q.transpose(0, 2, 1, 3)  # [B, H, Sq, D]
    k_t = k.transpose(0, 2, 1, 3)  # [B, Hkv, Sk, D]
    v_t = v.transpose(0, 2, 1, 3)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, Hkv, nq, nk),
        in_specs=[
            pl.BlockSpec((1, group, bq, D), lambda b, h, qi, ki, *_: (b, h, qi, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, qi, ki, *_: (b, h, ki, 0)),
            pl.BlockSpec((1, 1, bk, D), lambda b, h, qi, ki, *_: (b, h, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, group, bq, D), lambda b, h, qi, ki, *_: (b, h, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, 128), jnp.float32),
            pltpu.VMEM((r_pad, D), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _flash_kernel,
        block_q=bq, block_k=bk, group=group, scale=scale, causal=causal,
    )
    out_t = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, Sq, D), q.dtype),
        interpret=interpret,
    )(q_offset, kv_len, q_t, k_t, v_t)
    return out_t.transpose(0, 2, 1, 3)
