"""Free-running loop staging: the descriptor queue + token ring layout
(ISSUE 13; engine.ragged_multi_round is the device program that drains it).

A captured run is ``F`` consecutive ragged rounds in one dispatch. The
device cannot ask the host anything mid-run, so everything the host
normally decides per round is PRE-STAGED here into ``[F, ...]`` descriptor
arrays — a queue in device memory the rounds drain in order:

- a prefilling prompt advances one chunk per round, deterministically, so
  its completion round is known at staging time; the completing round arms
  the row (its first token samples on-device) and every later round stages
  it as a device-read decode row — on-device admission of the pre-staged
  prompt, no host commit micro-step;
- decode budgets (``max_new_tokens`` minus delivered minus the tokens
  still in flight in an unconsumed ring) are consumed deterministically
  too (1 per round, ``loop_depth`` when the fused tail rides), so budget
  exhaustion is staged away: a row past its budget stops appearing.
  Equivalently, the staged schedule IS the budget stop mask — only EOS,
  the one data-dependent stop, is left to the device (engine
  ``row_live``);
- held overlap holds stage chunks up to their prefix end and never arm
  (they park, awaiting ``extend_prompt``); prefix-registration jobs stage
  chunks and never arm (no logits consumer).

The plan also fixes the RING layout the consumer reads back:
``ring_tokens[F, R]`` / ``ring_n[F, R]`` / ``ring_blocks[F, K-1, B]``
indexed by the same row order staged here, plus the ``row_arm`` matrix —
the exactly-once replay reference: a ring round may only deliver where the
staged plan armed, anything else is a free-run divergence anomaly.

Host-side numpy only (no device work, no syncs) — staging runs on the
scheduler loop at dispatch time.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class RowSpec:
    """One engine slot riding a captured multi-round run."""

    slot: int
    kind: str  # "prefill" | "job" | "decode"
    ids: list | None = None  # prompt token ids (prefill/job rows)
    pos: int = 0  # prefill position at staging time
    # commit/emit tokens once the prompt completes (False: held overlap
    # holds and prefix jobs — they park instead of decoding)
    arm: bool = True
    # decode tokens the captured run may emit for this row (remaining
    # max_new_tokens minus tokens still undelivered in an in-flight ring)
    budget: int = 0
    loop_ok: bool = False  # may ride the fused loop_depth tail
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0


@dataclass
class FreerunPlan:
    """Staged descriptor queue for one captured run (device-ready arrays
    + the host bookkeeping the dispatch/consume seams need)."""

    rounds: int
    n_rows: int
    packed_tokens: int  # T before bucketing (the bucket fn padded it)
    tokens: np.ndarray  # [F, T]
    tok_row: np.ndarray  # [F, T]
    row_slot: np.ndarray  # [R]
    row_start: np.ndarray  # [F, R]
    row_len: np.ndarray  # [F, R]
    row_from_device: np.ndarray  # [F, R]
    row_arm: np.ndarray  # [F, R] — the exactly-once replay reference
    loop_active: np.ndarray  # [F, B] — staged fused-tail schedule
    temperature: np.ndarray  # [R]
    top_p: np.ndarray  # [R]
    top_k: np.ndarray  # [R]
    # every round has at least one staged row — an underfilled plan means
    # the work runs out mid-capture and the caller should fall back to
    # host-stepped rounds instead of burning empty device rounds
    active_rounds: int = 0
    # row index -> prompt tokens staged across the run (the dispatch-time
    # prefill_pos / job.pos advance, as in the host-stepped round)
    advanced: dict = field(default_factory=dict)
    # row index -> round where the prompt completes and the first token
    # arms (consume marks prefill_done and moves the handle to decoding)
    completes_at: dict = field(default_factory=dict)
    # slot -> max tokens this run can emit for it (the _undelivered /
    # budget-ahead accounting for the NEXT capture staged before this
    # ring is consumed)
    ahead: dict = field(default_factory=dict)


def stage_freerun(specs: list[RowSpec], *, rounds: int, chunk: int,
                  loop_depth: int, max_seqs: int, bucket) -> FreerunPlan:
    """Build the staged-descriptor queue for one captured run of
    ``rounds`` rounds. ``bucket`` maps a packed-token count to the warmed
    pow-2 bucket (engine.ragged_bucket) — every round pads to the same
    bucket so the scan's xs are rectangular. Rows are assigned in spec
    order (ascending contiguous packing, the ragged step's invariant)."""
    F = rounds
    R = max_seqs
    n = len(specs)
    assert n <= R, f"{n} rows > {R} slots"
    K = max(1, loop_depth)

    row_slot = np.zeros((R,), np.int32)
    row_start = np.zeros((F, R), np.int32)
    row_len = np.zeros((F, R), np.int32)
    row_from_device = np.zeros((F, R), bool)
    row_arm = np.zeros((F, R), bool)
    loop_active = np.zeros((F, max_seqs), bool)
    temperature = np.zeros((R,), np.float32)
    top_p = np.ones((R,), np.float32)
    top_k = np.zeros((R,), np.int32)
    plan = FreerunPlan(
        rounds=F, n_rows=n, packed_tokens=0,
        tokens=np.zeros((F, 0), np.int32), tok_row=np.zeros((F, 0), np.int32),
        row_slot=row_slot, row_start=row_start, row_len=row_len,
        row_from_device=row_from_device, row_arm=row_arm,
        loop_active=loop_active,
        temperature=temperature, top_p=top_p, top_k=top_k,
    )

    pos = [s.pos for s in specs]  # prompt cursor (prefill/job rows)
    emitted = [0] * n  # staged-emission cursor (the budget stop)
    decoding = [s.kind == "decode" for s in specs]
    per_round: list[list[tuple[int, list[int]]]] = []  # (row, tokens)

    for i, s in enumerate(specs):
        row_slot[i] = s.slot
        temperature[i] = s.temperature
        top_p[i] = s.top_p
        top_k[i] = s.top_k

    for r in range(F):
        staged: list[tuple[int, list[int]]] = []
        for i, s in enumerate(specs):
            if not decoding[i]:
                if s.ids is not None and pos[i] < len(s.ids):
                    seg = list(s.ids[pos[i] : pos[i] + chunk])
                    row_start[r, i] = pos[i]
                    row_len[r, i] = len(seg)
                    staged.append((i, seg))
                    pos[i] += len(seg)
                    if s.kind == "prefill" and s.arm and pos[i] >= len(s.ids):
                        # prompt completes this round: arm it (the first
                        # token samples on-device with the row's params)
                        # and decode from the next round on
                        row_arm[r, i] = True
                        plan.completes_at[i] = r
                        emitted[i] = 1
                        decoding[i] = True
                # exhausted non-arming rows (jobs, held holds) park:
                # no further rounds staged
                continue
            rem = s.budget - emitted[i]
            if rem < 1:
                continue  # budget exhausted: staged away (the host evicts
                # the stream at drain time, exactly the round-stepped path)
            row_len[r, i] = 1
            row_from_device[r, i] = True
            row_arm[r, i] = True
            staged.append((i, [0]))  # token 0 reads last_tokens ON DEVICE
            if s.loop_ok and K > 1 and rem >= K:
                loop_active[r, s.slot] = True
                emitted[i] += K
            else:
                emitted[i] += 1
        per_round.append(staged)

    plan.active_rounds = sum(1 for staged in per_round if staged)
    for i, s in enumerate(specs):
        if s.kind in ("prefill", "job"):
            plan.advanced[i] = pos[i] - s.pos
        if emitted[i]:
            plan.ahead[s.slot] = emitted[i]

    plan.packed_tokens = max(
        (sum(len(toks) for _i, toks in staged) for staged in per_round),
        default=0,
    )
    T = bucket(max(1, plan.packed_tokens))
    tokens = np.zeros((F, T), np.int32)
    tok_row = np.full((F, T), R, np.int32)  # R = buffer padding
    for r, staged in enumerate(per_round):
        off = 0
        for i, toks in staged:
            tokens[r, off : off + len(toks)] = toks
            tok_row[r, off : off + len(toks)] = i
            off += len(toks)
    plan.tokens = tokens
    plan.tok_row = tok_row
    return plan
