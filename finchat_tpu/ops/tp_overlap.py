"""TP collective–compute overlap for the row-parallel layer outputs.

Under the all-manual TP stage path (parallel/pipeline.py →
models/llama._layer) each decoder layer ends in TWO blocking
``jax.lax.psum`` all-reduces — the attention output projection and the
MLP down projection (models/llama.py). Every one serializes the model
axis: the matmul must finish entirely before the collective starts, and
the collective must finish before the residual add. Kernel Looping
(PAPERS.md) names exactly this compute→collective boundary as the
remaining headroom once the host syncs are gone (PR 13).

``row_parallel_dense`` removes the boundary structurally: the
row-parallel matmul is CHUNKED along its OUTPUT columns, and each chunk's
partial-sum all-reduce is issued as soon as that chunk's matmul retires —
XLA's async collectives then overlap chunk c's psum with chunk c+1's
matmul (TPU all-reduces are async by default; on CPU the chunks simply
run back to back). This is the "async psum" arm the ISSUE allows, chosen
over a ppermute-pipelined reduce-scatter + all-gather ring deliberately:

- BYTE-IDENTITY at every dtype, by construction. Chunking the output
  axis leaves each output element's math untouched — the same full-K dot
  followed by the same single n-way collective reduction. A ring
  reduce-scatter reorders the cross-shard addition and is NOT bitwise at
  reduced precision, which would break the manual-TP path's
  bit-identical-to-unsharded contract (models/quant.py docstring,
  tests/test_parallel.py). The fp32 byte-identity pin plus the bf16
  envelope in tests/test_parallel.py hold à la the ring-prefill
  promotion.
- The chunk loop is trace-visible: the jaxpr carries ``n_chunks`` psum
  eqns instead of one, which is the dispatch/trace evidence the
  tp_overlap test asserts (engagement is observable, not just a knob).

Quantized weights chunk WITHOUT unpacking: a QTensor slices its int8
columns and per-column scales, a Q4Tensor slices its packed bytes' N
axis (the nibble pair lives along K, inside one byte — column slices
never split it), so each chunk still routes through the fused
quant_matmul path reading packed HBM.

Gate: ``engine.tp_overlap`` / ``FINCHAT_TP_OVERLAP`` (default off —
on CPU there is nothing to overlap and the serial psum is the reference
schedule), threaded through ``pipeline_forward`` and ``_layer``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import Array

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _slice_out_cols(w, start: int, size: int):
    """Slice the OUTPUT (last) axis of a plain or quantized weight."""
    from finchat_tpu.models.quant import Q4Tensor, QTensor

    if isinstance(w, QTensor):
        return QTensor(q=w.q[..., start:start + size],
                       scale=w.scale[..., start:start + size])
    if isinstance(w, Q4Tensor):
        return Q4Tensor(q=w.q[..., start:start + size],
                        scale=w.scale[..., start:start + size])
    return w[..., start:start + size]


def row_parallel_dense(
    x: Array,
    w,  # Array | QTensor | Q4Tensor — the row-parallel shard [K_local, N]
    axis: str,
    *,
    overlap: bool = False,
    n_chunks: int = 4,
    qm_backend: str | None = None,
) -> Array:
    """``psum(x @ w, axis)`` — the row-parallel layer output — either as
    the serial matmul + one blocking all-reduce (``overlap=False``, the
    reference schedule) or as ``n_chunks`` output-column chunks whose
    per-chunk psums overlap the next chunk's matmul. Both schedules are
    byte-identical per element (see module docstring); indivisible output
    dims fall back to serial with a warning."""
    from finchat_tpu.models.quant import dense

    N = w.shape[-1]
    if overlap and (n_chunks <= 1 or N % n_chunks):
        logger.warning(
            "tp_overlap: output dim %d not divisible into %d chunks; "
            "running the serial collective", N, n_chunks,
        )
        overlap = False
    if not overlap:
        return jax.lax.psum(dense(x, w, qm_backend=qm_backend), axis)
    size = N // n_chunks
    outs = []
    for c in range(n_chunks):
        wc = _slice_out_cols(w, c * size, size)
        # issue the chunk's all-reduce immediately: the next chunk's dot
        # has no data dependence on it, so the XLA scheduler can overlap
        outs.append(jax.lax.psum(dense(x, wc, qm_backend=qm_backend), axis))
    return jnp.concatenate(outs, axis=-1)
