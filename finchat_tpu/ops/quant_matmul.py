"""Fused dequant-matmul Pallas kernels: packed int8/int4 weight reads.

The quantized serving plane (models/quant.py, PR 14) stores matmul weights
as int8 (per-output-column scales) or packed int4 nibbles (per-group
scales along K), and every matmul site dequantizes INLINE —
``x @ dequantize(w, x.dtype)``. That contract is what keeps TP decode
bit-identical to unsharded, but on its own it leaves the HBM win to XLA's
mercy: whenever the fusion breaks (and on the measured decode step it
does, per layer), the bf16 weight REMATERIALIZES and the decode step
streams full-width weights again — the ~6 ms/step weight-read attribution
PERF_r04.md measured is only conditionally halved/quartered.

This module makes the packed read structural instead of incidental:

- ``quant_matmul_int8`` / ``quant_matmul_int4``: Pallas matmul kernels
  whose weight operand is the PACKED array exactly as stored — int8
  ``[K, N]`` or nibble-packed ``[K//2, N]`` — with per-channel or
  per-group fp32 scales. Dequantization (nibble unpack via the arithmetic
  ``<< 4 >> 4`` pair — the same idiom as models/quant._unpack_int4 —
  upcast, scale) happens in VMEM/registers inside the K-tile loop, so HBM
  only ever streams 1 or 0.5 bytes per weight. Accumulation is fp32
  (``preferred_element_type``), written back once per (m, n) tile.
- ``quant_matmul_ref``: the ``jax.lax`` oracle, constructed to be
  BITWISE the pre-existing inline-dequant math (literally
  ``x @ dequantize(w, x.dtype)``, or the ``preferred_element_type``
  einsum for the lm_head site). Exactly like
  ``ragged_paged_attention_ref``, the reference IS the CPU/tier-1
  serving path — routing through it must not change a single stream
  byte, and tests/test_quant_matmul.py pins that.

``ops/dispatch.py quant_matmul`` routes between them (FINCHAT_QUANT_MATMUL
env: pallas | ref | pallas-interpret), and ``models/quant.dense`` — the
one matmul entry every QTensor/Q4Tensor site in the decoder and the
quantized embed encoder goes through — calls the dispatcher.

Layout notes (why the kernel honors parallel/sharding.py's packed-K
specs): the kernel sees only the LOCAL shard — int8 ``[K_local, N_local]``
or packed ``[K_local//2, N_local]`` with the matching scale shard — and
never unpacks across the shard boundary, because the nibble pair (rows
2i, 2i+1) always lives inside one byte and byte rows shard as units.

Tiling: grid (M/bm, N/bn, K/bk) with K innermost ("arbitrary" — it
accumulates); fp32 VMEM accumulator scratch per (m, n) tile. Ragged
shapes are zero-padded in the wrapper — exact, since zero weight rows /
columns contribute zero to every output element (padded scale entries are
1.0 so no 0*inf hazards exist even in theory).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import Array
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def quant_matmul_ref(x: Array, w, *, preferred_element_type=None) -> Array:
    """The inline-dequant oracle — bitwise the serving math this kernel
    replaces. ``w`` is a models/quant QTensor or Q4Tensor. With
    ``preferred_element_type`` the contraction is the lm_head einsum
    (fp32 logits); without it, the plain ``@`` every dense site used."""
    from finchat_tpu.models.quant import dequantize

    w_deq = dequantize(w, x.dtype)
    if preferred_element_type is None:
        return x @ w_deq
    return jnp.einsum("...k,kn->...n", x, w_deq,
                      preferred_element_type=preferred_element_type)


def _round_up(v: int, m: int) -> int:
    return (v + m - 1) // m * m


def _pick_bk(K: int, g: int) -> int:
    """K-tile size honoring the scale-group layout: every K-tile must be
    a whole number of groups (bk % g == 0) or lie inside one group
    (g % bk == 0), so the in-kernel scale slice is static-shaped."""
    if g % 128 == 0 or 128 % g == 0:
        bk = 128
    else:
        bk = g  # odd group sizes: one group per tile
    return min(bk, max(g, _round_up(K, 2)))


def _qmm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *,
                bk: int, bn: int, g: int, n_groups: int, packed: bool,
                compute_dtype):
    """One (m, n, k) grid step: unpack + dequantize the weight tile in
    VMEM, fp32-accumulate its contribution to the (m, n) output tile."""
    @pl.when(pl.program_id(2) == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[...]
    if packed:
        # nibble unpack, the models/quant._unpack_int4 arithmetic: low
        # nibble = row 2i, high nibble = row 2i+1, sign via << 4 >> 4
        lo = (q << 4) >> 4
        hi = q >> 4
        q = jnp.stack([lo, hi], axis=-2).reshape(bk, q.shape[-1])

    # per-group scales: the scale block holds ALL groups' rows for this
    # n-tile (n_groups is small — K/g); slice this k-tile's rows with
    # static shapes (the wrapper guarantees bk % g == 0 or g % bk == 0)
    s_all = s_ref[...]  # [n_groups_padded, bn] fp32
    k_idx = pl.program_id(2)
    if bk <= g:
        # the whole tile lies inside one group
        grp = k_idx * bk // g
        s_rows = jax.lax.dynamic_slice_in_dim(s_all, grp, 1, 0)  # [1, bn]
        s_tile = jnp.broadcast_to(s_rows, (bk, s_all.shape[-1]))
    else:
        # whole groups per tile: broadcast each group row over its g rows
        npg = bk // g
        start = k_idx * npg
        s_rows = jax.lax.dynamic_slice_in_dim(s_all, start, npg, 0)
        s_tile = jnp.broadcast_to(
            s_rows[:, None, :], (npg, g, s_all.shape[-1])
        ).reshape(bk, s_all.shape[-1])

    # in-register dequant: int values are exact in fp32; the cast to the
    # activation dtype mirrors the reference's dequantize(w, x.dtype)
    w = (q.astype(jnp.float32) * s_tile).astype(compute_dtype)
    acc_ref[...] += jnp.dot(x_ref[...], w,
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _done():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("packed", "group_size", "out_dtype", "interpret"),
)  # finchat-lint: hot
def _quant_matmul_2d(x: Array, q: Array, scale: Array, *, packed: bool,
                     group_size: int, out_dtype, interpret: bool) -> Array:
    """Fused dequant-matmul on flattened operands: x [M, K] @ packed
    weight (int8 [K, N] / int4 [K//2, N]) with scale [G, N]."""
    M, K = x.shape
    N = q.shape[-1]
    g = group_size
    G = scale.shape[0]
    assert K % g == 0 and G == K // g, (K, g, G)

    bm = min(128, _round_up(M, 8))
    bn = 128
    bk = _pick_bk(K, g)

    Mp, Np, Kp = _round_up(M, bm), _round_up(N, bn), _round_up(K, bk)
    Gp = Kp // g
    # scale rows pad to the sublane tile so the block load stays aligned
    Gpad = max(8, _round_up(Gp, 8))
    if Mp != M:
        x = jnp.pad(x, ((0, Mp - M), (0, 0)))
    if Kp != K:
        # zero weight rows are exact padding (contribute 0 per element);
        # packed rows pad at K//2 granularity (one byte = two rows)
        x = jnp.pad(x, ((0, 0), (0, Kp - K)))
        krows = (Kp - K) // 2 if packed else Kp - K
        q = jnp.pad(q, ((0, krows), (0, 0)))
    if Np != N:
        q = jnp.pad(q, ((0, 0), (0, Np - N)))
    if (Gpad, Np) != scale.shape:
        scale = jnp.pad(scale, ((0, Gpad - G), (0, Np - N)),
                        constant_values=1.0)

    kq = bk // 2 if packed else bk
    out = pl.pallas_call(
        functools.partial(
            _qmm_kernel, bk=bk, bn=bn, g=g, n_groups=Gp, packed=packed,
            compute_dtype=x.dtype,
        ),
        grid=(Mp // bm, Np // bn, Kp // bk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((kq, bn), lambda m, n, k: (k, n)),
            pl.BlockSpec((Gpad, bn), lambda m, n, k: (0, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=jax.ShapeDtypeStruct((Mp, Np), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=interpret,
    )(x, q, scale)
    return out[:M, :N]


def quant_matmul_int8(x: Array, q: Array, scale: Array, *,
                      interpret: bool | None = None,
                      out_dtype=None) -> Array:
    """``x @ (q * scale)`` with q int8 ``[K, N]`` streamed packed and
    per-output-column fp32 ``scale [N]`` applied in-tile. ``x`` may carry
    leading batch dims; they flatten into M."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    lead = x.shape[:-1]
    out = _quant_matmul_2d(
        x.reshape(-1, x.shape[-1]), q, scale.reshape(1, -1),
        packed=False, group_size=q.shape[0],
        out_dtype=out_dtype or x.dtype, interpret=interpret,
    )
    return out.reshape(*lead, q.shape[-1])


def quant_matmul_int4(x: Array, q: Array, scale: Array, *,
                      interpret: bool | None = None,
                      out_dtype=None) -> Array:
    """``x @ dequant(q, scale)`` with q nibble-packed int4 ``[K//2, N]``
    streamed AS PACKED and per-group fp32 ``scale [G, N]`` (G = 1 is
    per-channel) applied in-tile after the in-register unpack."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    K = q.shape[0] * 2
    G = scale.shape[0]
    lead = x.shape[:-1]
    out = _quant_matmul_2d(
        x.reshape(-1, x.shape[-1]), q, scale,
        packed=True, group_size=K // G,
        out_dtype=out_dtype or x.dtype, interpret=interpret,
    )
    return out.reshape(*lead, q.shape[-1])
