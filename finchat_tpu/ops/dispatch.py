"""Kernel backend dispatch: Pallas kernels on TPU, jnp references on CPU.

One switch per kernel family for the whole engine (SURVEY §7.2 step 4
wiring): ``FINCHAT_ATTN`` for the attention kernels and
``FINCHAT_QUANT_MATMUL`` for the fused dequant-matmul plane. Resolution
order (same for both):

1. the env var: ``pallas`` | ``ref`` | ``pallas-interpret``
   (the last runs the Pallas kernels through the interpreter on any backend
   — what the CI mesh uses to exercise kernel code paths without a TPU);
2. default: ``pallas`` when the runtime backend is TPU, else ``ref``.

The reference implementations are the correctness oracles and stay the
fallback everywhere Mosaic can't lower (CPU test meshes, odd head_dims).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import Array

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_VALID = ("pallas", "ref", "pallas-interpret")


def attention_backend() -> str:
    """Resolve the default backend. Callers that jit should resolve ONCE and
    pass the result through as a static argument (the engine does) — reading
    env inside a traced function would bake the first resolution into the
    jit cache."""
    choice = os.getenv("FINCHAT_ATTN", "").strip().lower()
    if choice:
        if choice not in _VALID:
            raise ValueError(f"FINCHAT_ATTN must be one of {_VALID}, got {choice!r}")
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "ref"


def paged_attention(
    q: Array,  # [B, C, H, D]
    k_pages: Array,  # [L, P, page_size, Hkv*D] — full-depth cache (or int8)
    v_pages: Array,
    page_table: Array,  # [B, max_pages]
    q_offset: Array,  # [B]
    kv_len: Array,  # [B]
    layer: Array,  # [1] int32 — which layer's pages to read
    *,
    page_size: int,
    n_kv: int,
    backend: str | None = None,
    k_scales: Array | None = None,  # int8 cache: [L, P, SPAD, page_size] fp32
    v_scales: Array | None = None,
) -> Array:
    """Paged-KV attention via the requested (or default) backend. An int8
    cache (engine kv_quant) is detected from the page dtype; the scale
    arrays must then be provided."""
    backend = backend or attention_backend()
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        assert k_scales is not None and v_scales is not None
    if backend == "ref":
        from finchat_tpu.engine.kv_cache import gather_kv_any
        from finchat_tpu.ops.refs import mha_reference

        lay = jnp.asarray(layer, jnp.int32).reshape(())
        k_all, v_all = gather_kv_any(
            k_pages, v_pages, k_scales, v_scales, page_table, page_size,
            lay, n_kv, dtype=q.dtype,
        )
        return mha_reference(
            q, k_all, v_all, causal=True, q_offset=q_offset, kv_len=kv_len
        )
    interpret = backend == "pallas-interpret"
    if quantized:
        from finchat_tpu.ops.paged_attention import paged_flash_attention_q8

        return paged_flash_attention_q8(
            q, k_pages, v_pages, k_scales, v_scales, page_table,
            q_offset, kv_len, layer,
            page_size=page_size, n_kv=n_kv, interpret=interpret,
        )
    from finchat_tpu.ops.paged_attention import paged_flash_attention

    return paged_flash_attention(
        q, k_pages, v_pages, page_table, q_offset, kv_len, layer,
        page_size=page_size, n_kv=n_kv, interpret=interpret,
    )


def ragged_paged_attention(
    q: Array,  # [T, H, D] — packed ragged token buffer
    k_pages: Array,  # [L, P, page_size, Hkv*D] — full-depth cache (or int8)
    v_pages: Array,
    page_table: Array,  # [R, max_pages] — per-ROW physical page lists
    tok_row: Array,  # [T] — owning row per packed token (R = padding)
    tok_pos: Array,  # [T] — absolute position per packed token
    kv_len: Array,  # [R] — valid KV per row incl. this dispatch's tokens
    layer: Array,  # [1] int32
    *,
    page_size: int,
    n_kv: int,
    backend: str | None = None,
    k_scales: Array | None = None,  # int8 cache: [L, P, SPAD, page_size] fp32
    v_scales: Array | None = None,
    kv_gap: Array | None = None,  # [R] — bounded-KV window offset per row
) -> Array:
    """Ragged paged-KV attention (ops/ragged_paged_attention.py): prefill
    chunks, decode tokens, and spec verify blocks as rows of ONE packed
    buffer. An int8 cache (engine kv_quant) is detected from the page
    dtype; the scale arrays must then be provided. ``kv_gap`` is the
    bounded-KV per-row eviction offset (tokens dropped between the pinned
    sink pages and the surviving window — see
    ragged_paged_attention_ref); None/zeros = exact unbounded attention."""
    backend = backend or attention_backend()
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        assert k_scales is not None and v_scales is not None
    if backend == "ref":
        from finchat_tpu.ops.ragged_paged_attention import (
            ragged_paged_attention_ref,
        )

        return ragged_paged_attention_ref(
            q, k_pages, v_pages, page_table, tok_row, tok_pos, kv_len, layer,
            page_size=page_size, n_kv=n_kv,
            k_scales=k_scales if quantized else None,
            v_scales=v_scales if quantized else None,
            kv_gap=kv_gap,
        )
    interpret = backend == "pallas-interpret"
    if quantized:
        from finchat_tpu.ops.ragged_paged_attention import (
            ragged_flash_attention_q8,
        )

        return ragged_flash_attention_q8(
            q, k_pages, v_pages, k_scales, v_scales, page_table,
            tok_row, tok_pos, kv_len, layer,
            page_size=page_size, n_kv=n_kv, interpret=interpret,
            kv_gap=kv_gap,
        )
    from finchat_tpu.ops.ragged_paged_attention import ragged_flash_attention

    return ragged_flash_attention(
        q, k_pages, v_pages, page_table, tok_row, tok_pos, kv_len, layer,
        page_size=page_size, n_kv=n_kv, interpret=interpret,
        kv_gap=kv_gap,
    )


def quant_matmul_backend() -> str:
    """Resolve the fused dequant-matmul backend (``FINCHAT_QUANT_MATMUL``:
    ``pallas`` | ``ref`` | ``pallas-interpret``; default ``pallas`` on TPU,
    ``ref`` elsewhere — the reference is the CPU/tier-1 serving path).
    Same discipline as ``attention_backend``: jitted callers resolve ONCE
    outside the trace and pass the result through (the engine keys its
    compiled steps on it); a ``None`` backend reaching ``quant_matmul``
    inside a trace resolves env at TRACE time and bakes that answer into
    the jit cache."""
    choice = os.getenv("FINCHAT_QUANT_MATMUL", "").strip().lower()
    if choice:
        if choice not in _VALID:
            raise ValueError(
                f"FINCHAT_QUANT_MATMUL must be one of {_VALID}, got {choice!r}"
            )
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "ref"


def quant_matmul(
    x: Array,
    w,  # models/quant QTensor | Q4Tensor
    *,
    backend: str | None = None,
    preferred_element_type=None,
) -> Array:
    """Quantized matmul via the requested (or default) backend: the fused
    Pallas kernel streams the weight PACKED from HBM (ops/quant_matmul.py)
    and dequantizes in-tile; the reference is bitwise the historical
    inline-dequant math. Shapes the kernel does not tile — stacked
    (ndim > 2) weight leaves, i.e. the MoE expert einsums — fall back to
    the reference and count on ``finchat_quantmatmul_fallbacks_total``
    (once per TRACE, not per dispatch: this routing runs at trace time
    inside the engine's compiled steps)."""
    from finchat_tpu.models.quant import Q4Tensor
    from finchat_tpu.ops.quant_matmul import (
        quant_matmul_int4,
        quant_matmul_int8,
        quant_matmul_ref,
    )

    backend = backend or quant_matmul_backend()
    if backend != "ref" and w.q.ndim != 2:
        from finchat_tpu.utils.metrics import METRICS

        METRICS.inc("finchat_quantmatmul_fallbacks_total")
        logger.warning(
            "quant_matmul: no fused kernel for stacked weight shape %s; "
            "falling back to the inline-dequant reference", w.q.shape,
        )
        backend = "ref"
    if backend == "ref":
        return quant_matmul_ref(
            x, w, preferred_element_type=preferred_element_type
        )
    interpret = backend == "pallas-interpret"
    if isinstance(w, Q4Tensor):
        return quant_matmul_int4(
            x, w.q, w.scale, interpret=interpret,
            out_dtype=preferred_element_type,
        )
    return quant_matmul_int8(
        x, w.q, w.scale, interpret=interpret,
        out_dtype=preferred_element_type,
    )


def causal_attention(q: Array, k: Array, v: Array, *, backend: str | None = None) -> Array:
    """Full contiguous causal attention (training / one-shot prefill)."""
    backend = backend or attention_backend()
    if backend == "ref":
        from finchat_tpu.ops.refs import mha_reference

        return mha_reference(q, k, v, causal=True)
    from finchat_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=True, interpret=(backend == "pallas-interpret"))
