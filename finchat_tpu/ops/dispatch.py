"""Attention backend dispatch: Pallas kernels on TPU, jnp references on CPU.

One switch for the whole engine (SURVEY §7.2 step 4 wiring). Resolution
order:

1. ``FINCHAT_ATTN`` env var: ``pallas`` | ``ref`` | ``pallas-interpret``
   (the last runs the Pallas kernels through the interpreter on any backend
   — what the CI mesh uses to exercise kernel code paths without a TPU);
2. default: ``pallas`` when the runtime backend is TPU, else ``ref``.

The reference implementations are the correctness oracles and stay the
fallback everywhere Mosaic can't lower (CPU test meshes, odd head_dims).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
from jax import Array

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_VALID = ("pallas", "ref", "pallas-interpret")


def attention_backend() -> str:
    """Resolve the default backend. Callers that jit should resolve ONCE and
    pass the result through as a static argument (the engine does) — reading
    env inside a traced function would bake the first resolution into the
    jit cache."""
    choice = os.getenv("FINCHAT_ATTN", "").strip().lower()
    if choice:
        if choice not in _VALID:
            raise ValueError(f"FINCHAT_ATTN must be one of {_VALID}, got {choice!r}")
        return choice
    return "pallas" if jax.default_backend() == "tpu" else "ref"


def paged_attention(
    q: Array,  # [B, C, H, D]
    k_pages: Array,  # [L, P, page_size, Hkv*D] — full-depth cache (or int8)
    v_pages: Array,
    page_table: Array,  # [B, max_pages]
    q_offset: Array,  # [B]
    kv_len: Array,  # [B]
    layer: Array,  # [1] int32 — which layer's pages to read
    *,
    page_size: int,
    n_kv: int,
    backend: str | None = None,
    k_scales: Array | None = None,  # int8 cache: [L, P, SPAD, page_size] fp32
    v_scales: Array | None = None,
) -> Array:
    """Paged-KV attention via the requested (or default) backend. An int8
    cache (engine kv_quant) is detected from the page dtype; the scale
    arrays must then be provided."""
    backend = backend or attention_backend()
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        assert k_scales is not None and v_scales is not None
    if backend == "ref":
        from finchat_tpu.engine.kv_cache import gather_kv_any
        from finchat_tpu.ops.refs import mha_reference

        lay = jnp.asarray(layer, jnp.int32).reshape(())
        k_all, v_all = gather_kv_any(
            k_pages, v_pages, k_scales, v_scales, page_table, page_size,
            lay, n_kv, dtype=q.dtype,
        )
        return mha_reference(
            q, k_all, v_all, causal=True, q_offset=q_offset, kv_len=kv_len
        )
    interpret = backend == "pallas-interpret"
    if quantized:
        from finchat_tpu.ops.paged_attention import paged_flash_attention_q8

        return paged_flash_attention_q8(
            q, k_pages, v_pages, k_scales, v_scales, page_table,
            q_offset, kv_len, layer,
            page_size=page_size, n_kv=n_kv, interpret=interpret,
        )
    from finchat_tpu.ops.paged_attention import paged_flash_attention

    return paged_flash_attention(
        q, k_pages, v_pages, page_table, q_offset, kv_len, layer,
        page_size=page_size, n_kv=n_kv, interpret=interpret,
    )


def ragged_paged_attention(
    q: Array,  # [T, H, D] — packed ragged token buffer
    k_pages: Array,  # [L, P, page_size, Hkv*D] — full-depth cache (or int8)
    v_pages: Array,
    page_table: Array,  # [R, max_pages] — per-ROW physical page lists
    tok_row: Array,  # [T] — owning row per packed token (R = padding)
    tok_pos: Array,  # [T] — absolute position per packed token
    kv_len: Array,  # [R] — valid KV per row incl. this dispatch's tokens
    layer: Array,  # [1] int32
    *,
    page_size: int,
    n_kv: int,
    backend: str | None = None,
    k_scales: Array | None = None,  # int8 cache: [L, P, SPAD, page_size] fp32
    v_scales: Array | None = None,
    kv_gap: Array | None = None,  # [R] — bounded-KV window offset per row
) -> Array:
    """Ragged paged-KV attention (ops/ragged_paged_attention.py): prefill
    chunks, decode tokens, and spec verify blocks as rows of ONE packed
    buffer. An int8 cache (engine kv_quant) is detected from the page
    dtype; the scale arrays must then be provided. ``kv_gap`` is the
    bounded-KV per-row eviction offset (tokens dropped between the pinned
    sink pages and the surviving window — see
    ragged_paged_attention_ref); None/zeros = exact unbounded attention."""
    backend = backend or attention_backend()
    quantized = k_pages.dtype == jnp.int8
    if quantized:
        assert k_scales is not None and v_scales is not None
    if backend == "ref":
        from finchat_tpu.ops.ragged_paged_attention import (
            ragged_paged_attention_ref,
        )

        return ragged_paged_attention_ref(
            q, k_pages, v_pages, page_table, tok_row, tok_pos, kv_len, layer,
            page_size=page_size, n_kv=n_kv,
            k_scales=k_scales if quantized else None,
            v_scales=v_scales if quantized else None,
            kv_gap=kv_gap,
        )
    interpret = backend == "pallas-interpret"
    if quantized:
        from finchat_tpu.ops.ragged_paged_attention import (
            ragged_flash_attention_q8,
        )

        return ragged_flash_attention_q8(
            q, k_pages, v_pages, k_scales, v_scales, page_table,
            tok_row, tok_pos, kv_len, layer,
            page_size=page_size, n_kv=n_kv, interpret=interpret,
            kv_gap=kv_gap,
        )
    from finchat_tpu.ops.ragged_paged_attention import ragged_flash_attention

    return ragged_flash_attention(
        q, k_pages, v_pages, page_table, tok_row, tok_pos, kv_len, layer,
        page_size=page_size, n_kv=n_kv, interpret=interpret,
        kv_gap=kv_gap,
    )


def causal_attention(q: Array, k: Array, v: Array, *, backend: str | None = None) -> Array:
    """Full contiguous causal attention (training / one-shot prefill)."""
    backend = backend or attention_backend()
    if backend == "ref":
        from finchat_tpu.ops.refs import mha_reference

        return mha_reference(q, k, v, causal=True)
    from finchat_tpu.ops.flash_attention import flash_attention

    return flash_attention(q, k, v, causal=True, interpret=(backend == "pallas-interpret"))
