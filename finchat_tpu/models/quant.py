"""Int8 / int4 weight-only quantization for serving.

No reference counterpart (the reference calls an external LLM API —
``llm_agent.py:34-45``); this exists because the measured decode step is
weight-READ-bound on TPU (PERF_r04.md attribution: ~6 ms of the 9.6 ms
step is the dense forward streaming bf16 weights from HBM). Storing matmul
weights as int8 with per-output-channel scales halves that traffic; the
MXU still computes in bf16 (int8 values up to ±127 are exact in bf16), so
the only numeric change is the weight rounding itself — bounded by the
per-channel max / 127 and asserted in tests/test_quant.py.

Design notes (TPU/JAX-first):
- ``QTensor`` is a registered pytree dataclass, so quantized leaves ride
  ``lax.scan`` over stacked layers, jit boundaries, and GSPMD sharding
  exactly like plain arrays. Scanning slices ``q[L, K, N] -> [K, N]`` and
  ``scale[L, N] -> [N]`` together.
- Scales are per-OUTPUT-column (the non-contracted axis). Matmul sites
  dequantize INLINE (``x @ (q * s)``): inside jit XLA fuses the
  upcast+scale into the dot's operand read, so HBM still streams int8
  while the MXU computes bf16. Post-matmul scaling (``(x @ q) * s``) is
  mathematically equal but NOT used: under row-parallel TP it reorders
  the scale past the partial-sum psum, whose bf16 rounding then differs
  from the single-device result — inline dequant keeps TP decode
  bit-identical to unsharded (tests/test_quant.py).
- Quantize AFTER ``shard_params``: ``quantize`` is plain jnp, so on
  GSPMD-sharded inputs the amax reduce runs over the (replicated)
  contraction axis per shard and ``q``/``scale`` inherit the weight's
  placement — no parallel spec bookkeeping for the quantized tree.
- ``int4`` (ISSUE 14) rides the same machinery one level down:
  ``Q4Tensor`` packs two signed nibbles per int8 byte along the
  CONTRACTION axis (row 2i in the low nibble, row 2i+1 in the high — an
  arithmetic ``<< 4 >> 4`` / ``>> 4`` pair unpacks with sign), with
  per-output-column scales that may additionally be per-GROUP along K
  (``group_size``; 0 = one group = per-channel). Dequantization is
  inline at the matmul site exactly like int8 — HBM streams 0.5
  byte/weight, the MXU still computes in the activation dtype.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
from jax import Array

# layer-stack leaves that are matmul weights [., K, N] (contract over -2);
# norms and the (precision-sensitive, tiny) MoE router stay full precision
QUANT_LAYER_LEAVES = frozenset({
    "attn_q", "attn_k", "attn_v", "attn_o",
    "mlp_gate", "mlp_up", "mlp_down",
    "moe_gate", "moe_up", "moe_down",
})


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class QTensor:
    """Int8 weight + per-output-column scale for right-multiplication.

    ``q``: int8 ``[..., K, N]``; ``scale``: fp32 ``[..., N]`` such that the
    represented weight is ``q * scale[..., None, :]``.
    """

    q: Array
    scale: Array

    @property
    def shape(self) -> tuple[int, ...]:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim


def quantize(w: Array) -> QTensor:
    """Symmetric int8 per-output-column quantization of ``w[..., K, N]``."""
    w32 = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(w32), axis=-2)  # [..., N]
    scale = jnp.where(amax > 0, amax, 1.0) / 127.0
    q = jnp.clip(jnp.round(w32 / scale[..., None, :]), -127, 127).astype(jnp.int8)
    return QTensor(q=q, scale=scale)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class Q4Tensor:
    """Int4 weight (two nibbles per int8 byte along K) + per-group,
    per-output-column scales for right-multiplication.

    ``q``: int8 ``[..., K//2, N]`` — byte ``i`` holds row ``2i`` in its low
    nibble and row ``2i+1`` in its high nibble (signed, [-8, 7]).
    ``scale``: fp32 ``[..., G, N]`` with ``G = K / group_size`` groups along
    the contraction axis (G = 1 is per-output-channel). The represented
    weight row ``k`` is ``unpack(q)[k] * scale[k // group_size]``.
    """

    q: Array
    scale: Array

    @property
    def shape(self) -> tuple[int, ...]:
        # the LOGICAL weight shape (unpacked K), what callers reason about
        return self.q.shape[:-2] + (self.q.shape[-2] * 2, self.q.shape[-1])

    @property
    def ndim(self) -> int:
        return self.q.ndim


def quantize_int4(w: Array, group_size: int = 0) -> Q4Tensor:
    """Symmetric int4 quantization of ``w[..., K, N]`` with per-group
    (``group_size`` rows of K per scale; 0 = whole-column) scales."""
    w32 = w.astype(jnp.float32)
    K, N = w32.shape[-2:]
    assert K % 2 == 0, f"int4 packing needs an even contraction dim, got {K}"
    g = group_size or K
    assert K % g == 0 and g % 2 == 0, (K, g)
    G = K // g
    lead = w32.shape[:-2]
    wg = w32.reshape(*lead, G, g, N)
    amax = jnp.max(jnp.abs(wg), axis=-2)  # [..., G, N]
    scale = jnp.where(amax > 0, amax, 1.0) / 7.0
    q = jnp.clip(jnp.round(wg / scale[..., None, :]), -8, 7).astype(jnp.int8)
    q = q.reshape(*lead, K, N)
    packed = (q[..., 0::2, :] & jnp.int8(0x0F)) | (q[..., 1::2, :] << 4)
    return Q4Tensor(q=packed, scale=scale)


def _unpack_int4(packed: Array) -> Array:
    """[..., K//2, N] packed bytes → [..., K, N] signed nibble values
    (int8). Arithmetic shifts restore the sign of each nibble."""
    lo = (packed << 4) >> 4  # rows 0, 2, 4, ...
    hi = packed >> 4  # rows 1, 3, 5, ...
    half, N = packed.shape[-2:]
    lead = packed.shape[:-2]
    return jnp.stack([lo, hi], axis=-2).reshape(*lead, half * 2, N)


def _dequantize_int4(qt: Q4Tensor, dtype: Any) -> Array:
    K, N = qt.shape[-2:]
    G = qt.scale.shape[-2]
    lead = qt.q.shape[:-2]
    w = _unpack_int4(qt.q).astype(jnp.float32)
    wg = w.reshape(*lead, G, K // G, N) * qt.scale[..., None, :]
    return wg.reshape(*lead, K, N).astype(dtype)


def dequantize(qt: QTensor | Q4Tensor, dtype: Any = jnp.bfloat16) -> Array:
    """Materialize the represented weight (int8 or int4). Inside jit, XLA
    fuses the unpack+upcast+scale into the consuming dot's operand read —
    used at einsum sites where the scale cannot commute past a summed
    axis."""
    if isinstance(qt, Q4Tensor):
        return _dequantize_int4(qt, dtype)
    return (qt.q.astype(jnp.float32) * qt.scale[..., None, :]).astype(dtype)


def _set_stacked_slice(buf: Array, i: Array, part: Array) -> Array:
    """In-place-able write of slice ``i`` into the stacked output buffer
    (donated, so XLA updates the buffer rather than copying the stack)."""
    return jax.lax.dynamic_update_index_in_dim(buf, part, i, 0)


_set_stacked_slice = jax.jit(_set_stacked_slice, donate_argnums=(0,))


def quantize_stacked(w: Array, mode: str = "int8",
                     group_size: int = 0) -> QTensor | Q4Tensor:
    """``quantize`` (or ``quantize_int4`` per ``mode``) for layer-stacked
    leaves ``[L, ..., K, N]``, one leading slice at a time. BIT-identical
    to whole-leaf quantization (the amax reduce is over the contraction
    axis only — independent per leading index — and div/round/clip are
    elementwise; asserted in tests/test_quant.py), but the fp32 upcast
    transient inside ``quantize`` (``w32 = w.astype(float32)``) is capped
    at 1/L of the leaf — the difference between fitting and OOM when
    materializing an 8B int8 tree next to already-built leaves on one
    16 GB v5e chip.

    Two OOM guards beyond the slicing itself (ADVICE r5):

    - The loop SYNCHRONIZES on each slice (``jax.block_until_ready``)
      before dispatching the next. Async dispatch would otherwise enqueue
      all L slice programs at once and several ~235 MB fp32 transients
      could be live simultaneously during 8B init — exactly the cap this
      function promises.
    - The stacked q/scale build incrementally via DONATED in-place slice
      writes instead of ``jnp.stack``: the stack briefly held every
      per-slice part AND the stacked copy — a 2x-int8 transient, ~3.8 GB
      on the 8B mlp stack next to the still-live bf16 input — while the
      donated write keeps ONE output buffer plus a single in-flight slice.

    2D (unstacked) weights fall through to whole-leaf quantization."""
    qfn = (lambda x: quantize_int4(x, group_size)) if mode == "int4" else quantize
    cls = Q4Tensor if mode == "int4" else QTensor
    if w.ndim < 3:
        return qfn(w)
    L = w.shape[0]
    q = scale = None
    for i in range(L):
        # eager on purpose: jit-fusing quantize flips round() boundary
        # cases (see init_quantized_llama_params) and would break the
        # bit-identity promised above
        part = qfn(w[i])
        jax.block_until_ready(part.q)  # one slice's transients at a time  # finchat-lint: disable=event-loop-blocking -- deliberate per-slice sync bounding quantization transients (PR 1 satellite); startup/checkpoint path
        if q is None:
            q = jnp.zeros((L,) + part.q.shape, part.q.dtype)
            scale = jnp.zeros((L,) + part.scale.shape, part.scale.dtype)
        idx = jnp.int32(i)
        q = _set_stacked_slice(q, idx, part.q[None])
        scale = _set_stacked_slice(scale, idx, part.scale[None])
    return cls(q=q, scale=scale)


def dense(x: Array, w: Array | QTensor | Q4Tensor, *,
          qm_backend: str | None = None) -> Array:
    """``x @ w`` for a plain or quantized weight. Quantized leaves route
    through ``ops/dispatch.quant_matmul`` (PR 16): the reference backend
    is BITWISE the historical inline dequant ``x @ dequantize(w, x.dtype)``
    (see the module docstring for why not post-matmul scaling) and stays
    the CPU/tier-1 serving path; the Pallas backend streams the weight
    packed from HBM and dequantizes in the matmul tile loop, so the bf16
    tensor never rematerializes per layer. ``qm_backend`` follows the
    ops/dispatch contract: jitted callers (the engine) resolve once and
    pass it statically; ``None`` resolves env at trace time."""
    if isinstance(w, (QTensor, Q4Tensor)):
        from finchat_tpu.ops.dispatch import quant_matmul

        return quant_matmul(x, w, backend=qm_backend)
    return x @ w


def should_quantize(name: str) -> bool:
    """The ONE definition of which param leaves quantize: the layer-stack
    matmul weights plus the (untied) ``lm_head``. Shared by engine-side
    quantization, streaming random init, and the per-tensor checkpoint
    loader so the three paths can never diverge."""
    return name in QUANT_LAYER_LEAVES or name == "lm_head"


def validate_quant_mode(quant: str) -> None:
    """The ONE weight-quant-mode validator shared by the engine and the
    checkpoint loader, so the two serving construction paths cannot
    drift. (CLI surfaces additionally constrain via argparse choices,
    and the embed encoder supports only the int8 subset — both narrower
    than, never wider than, this set.)"""
    if quant and quant not in ("int8", "int4"):
        raise ValueError(
            f"unknown quant mode {quant!r} (supported: 'int8', 'int4')"
        )


def init_quantized_llama_params(config: Any, key: Any, mode: str = "int8",
                                group_size: int = 0) -> dict[str, Any]:
    """Random-init a param tree with matmul weights ALREADY int8/int4 — each
    leaf quantizes at creation (models/llama.py ``leaf_transform``), so the
    full bf16 tree never coexists with the int8 one. This is what lets a
    random-weight llama3-8b (16 GB bf16) materialize on one 16 GB v5e chip
    for benching; checkpoint serving gets the same effect from the loader's
    per-tensor path. Identical numerics to ``quantize_llama_params``
    applied after ``init_params`` (asserted in tests/test_quant.py).

    Stacked leaves go through ``quantize_stacked`` (shared with the HF
    loader's per-tensor path): whole-leaf eager ``quantize`` would
    MATERIALIZE its fp32 upcast on top of the already-built tree.
    (jit-fusing quantize would avoid the transient too but changes the
    division into reciprocal-multiply and flips round() boundary cases —
    observed 1 ulp on ~0.006% of weights — breaking the bit-identity
    this docstring promises.)"""

    def leaf_transform(name: str, w: Any) -> Any:
        return (quantize_stacked(w, mode=mode, group_size=group_size)
                if should_quantize(name) else w)

    from finchat_tpu.models.llama import init_params

    return init_params(config, key, leaf_transform=leaf_transform)


def quantize_llama_params(params: dict[str, Any], mode: str = "int8",
                          group_size: int = 0) -> dict[str, Any]:
    """Quantize a Llama/Mixtral param tree's matmul weights in place of the
    bf16 leaves (models/llama.py layout). Embedding (a gather, not a
    matmul), norms, and the MoE router stay full precision; ``lm_head`` is
    quantized when present (tied-embedding models keep the dense path).
    ``mode`` selects int8 (per-output-channel scales) or int4 (packed
    nibbles, ``group_size`` rows of K per scale; 0 = per-channel)."""
    validate_quant_mode(mode or "int8")

    def q(leaf: Any) -> Any:
        if isinstance(leaf, (QTensor, Q4Tensor)):
            return leaf  # idempotent (pre-quantized streaming load)
        if mode == "int4":
            return quantize_int4(leaf, group_size)
        return quantize(leaf)

    layers = {
        name: q(leaf) if should_quantize(name) else leaf
        for name, leaf in params["layers"].items()
    }
    out = {**params, "layers": layers}
    if "lm_head" in params:
        out["lm_head"] = q(params["lm_head"])
    return out
