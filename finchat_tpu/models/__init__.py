from finchat_tpu.models.llama import LlamaConfig, PRESETS, init_params, forward
from finchat_tpu.models.tokenizer import ByteTokenizer, IncrementalDecoder, get_tokenizer

__all__ = [
    "LlamaConfig",
    "PRESETS",
    "init_params",
    "forward",
    "ByteTokenizer",
    "IncrementalDecoder",
    "get_tokenizer",
]
