"""Tokenizers and chat templating.

The reference delegates tokenization to external APIs (Gemini/OpenAI); here
it is in-tree. Two implementations behind one protocol:

- ``ByteTokenizer`` — self-contained UTF-8 byte-level vocab (256 bytes +
  specials). Used by tests, the dev harness, and the random-weight bench so
  the whole stack runs with zero downloaded assets.
- ``HFTokenizer`` — adapter over a local HuggingFace tokenizer directory
  (Llama/TinyLlama checkpoints), gated on files being present.

Also here: ``IncrementalDecoder`` (UTF-8-safe streaming detokenization — a
multibyte codepoint split across two decode steps must not emit mojibake)
and the chat template that renders (system, history, user) into the prompt,
playing the role of the reference's ChatPromptTemplate (llm_agent.py:47-51).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Sequence

from finchat_tpu.io.schemas import ChatMessage


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_id: int
    pad_id: int

    def encode(self, text: str, add_bos: bool = False) -> list[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


@dataclass
class ByteTokenizer:
    """UTF-8 bytes 0..255, then PAD/BOS/EOS/EOT specials."""

    vocab_size: int = 260
    pad_id: int = 256
    bos_id: int = 257
    eos_id: int = 258
    eot_id: int = 259  # end-of-turn marker used by the chat template

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = list(text.encode("utf-8"))
        return ([self.bos_id] if add_bos else []) + ids

    def encode_with_specials(self, text: str) -> list[int]:
        """Encoder-style framing (the embedding path's [CLS]...[SEP])."""
        return [self.bos_id] + self.encode(text) + [self.eos_id]

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if i < 256).decode("utf-8", errors="replace")


class HFTokenizer:
    """Local HuggingFace tokenizer adapter (no network)."""

    def __init__(self, path: str):
        from transformers import AutoTokenizer  # deferred: heavy import

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.vocab_size = len(self._tok)
        self.bos_id = self._tok.bos_token_id or 0
        self.eos_id = self._tok.eos_token_id or 0
        self.pad_id = self._tok.pad_token_id if self._tok.pad_token_id is not None else self.eos_id
        self.eot_id = self.eos_id

    def encode(self, text: str, add_bos: bool = False) -> list[int]:
        ids = self._tok.encode(text, add_special_tokens=False)
        return ([self.bos_id] if add_bos else []) + ids

    def encode_with_specials(self, text: str) -> list[int]:
        """The tokenizer's own special framing — [CLS]...[SEP] for BERT
        vocabularies (what bge embeddings expect), <s>... for Llama ones."""
        return self._tok.encode(text, add_special_tokens=True)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)


def get_tokenizer(tokenizer_path: str = "") -> Tokenizer:
    if tokenizer_path:
        return HFTokenizer(tokenizer_path)
    return ByteTokenizer()


class IncrementalDecoder:
    """Streaming detokenizer that never emits a torn UTF-8 sequence.

    For byte-level vocabs a single emoji spans 4 tokens; flushing after each
    token must buffer incomplete prefixes. For HF tokenizers the same applies
    to byte-fallback pieces, handled by decoding the running tail.
    """

    def __init__(self, tokenizer: Tokenizer):
        self._tok = tokenizer
        self._pending: list[int] = []
        self._emitted = ""

    def push(self, token_id: int) -> str:
        """Feed one token id; return newly-safe text (possibly '')."""
        if isinstance(self._tok, ByteTokenizer):
            if token_id >= 256:
                return ""  # specials carry no text
            self._pending.append(token_id)
            raw = bytes(self._pending)
            try:
                text = raw.decode("utf-8")
                self._pending.clear()
                return text
            except UnicodeDecodeError as e:
                tail = len(raw) - e.start
                if tail > 3:
                    # a valid incomplete UTF-8 tail is ≤3 bytes; this is
                    # garbage — emit with replacement instead of buffering
                    # forever.
                    self._pending.clear()
                    return raw.decode("utf-8", errors="replace")
                # emit the valid prefix, keep the incomplete tail buffered
                valid = raw[: e.start].decode("utf-8")
                self._pending = list(raw[e.start:])
                return valid
        # HF path: decode the whole pending tail; emit only when the decoded
        # text doesn't end in the replacement char (torn byte-fallback).
        self._pending.append(token_id)
        text = self._tok.decode(self._pending)
        if text and not text.endswith("�"):
            self._pending.clear()
            return text
        return ""

    def flush(self) -> str:
        text = self._tok.decode(self._pending) if self._pending else ""
        self._pending.clear()
        return text


# ---------------------------------------------------------------------------
# Chat templating — the native replacement for the reference's
# ChatPromptTemplate: system(system_prompt + "\n" + context) / history / user
# (reference llm_agent.py:47-51).
# ---------------------------------------------------------------------------

_ROLE_TAGS = {"system": "<|system|>", "user": "<|user|>", "assistant": "<|assistant|>"}


def render_chat_head(system_prompt: str) -> str:
    """The constant leading string of a rendered prompt for a given system
    text — BY CONSTRUCTION a byte prefix of ``render_chat`` output (which
    builds its first part from this), so the shared-prefix KV cache and
    the prompt builders can never drift apart."""
    return f"{_ROLE_TAGS['system']}\n{system_prompt}\n"


def render_chat_prefix(
    system_prompt: str,
    context: str,
    history: Sequence[ChatMessage],
) -> str:
    """Everything of a rendered prompt that is known BEFORE the final user
    turn's content: system turn (system + context), the chat history, and
    the opening user tag. BY CONSTRUCTION a byte prefix of ``render_chat``
    with the same arguments (render_chat builds from this), so the
    retrieval/prefill overlap plane can prefill it while retrieval is
    still deciding what the user turn will carry — the two can never
    drift apart."""
    parts = [f"{render_chat_head(system_prompt)}{context}\n"]
    for turn in history:
        role = "user" if turn.is_user else "assistant"
        parts.append(f"{_ROLE_TAGS[role]}\n{turn.message}\n")
    parts.append(f"{_ROLE_TAGS['user']}\n")
    return "".join(parts)


def render_chat(
    system_prompt: str,
    context: str,
    history: Sequence[ChatMessage],
    user_input: str,
) -> str:
    """Render the prompt string fed to the decoder.

    Structure parity with the reference prompt template: one system turn
    holding ``{system_prompt}\\n{context}``, then the chat history in order,
    then the new user turn, then the assistant tag left open for generation.
    """
    return (
        f"{render_chat_prefix(system_prompt, context, history)}"
        f"{user_input}\n{_ROLE_TAGS['assistant']}\n"
    )
