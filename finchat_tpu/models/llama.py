"""Llama-family decoder in pure JAX.

Replaces the reference's external LLM calls (``llm_agent.py:34-45`` — two
ChatGoogleGenerativeAI instances) with an in-tree model. Design is TPU-first:

- Params are plain pytrees with all layers STACKED on a leading axis so the
  forward pass is a single ``lax.scan`` over layers — one compiled layer body
  instead of n_layers inlined copies (fast compiles, identical HLO per step).
- bf16 weights/activations, fp32 softmax and RMSNorm accumulation (MXU-
  friendly dtype policy).
- The attention inner op is a pluggable callback so the same forward serves
  training (full causal), chunked prefill, and paged decode, with either the
  jnp reference or Pallas kernels underneath.
- Static shapes everywhere; positions are explicit inputs (no data-dependent
  Python control flow under jit).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import Array, lax

from finchat_tpu.models.quant import Q4Tensor, QTensor, dense, dequantize

# attention callback signature:
#   fn(q[B,S,H,D], k[B,S,Hkv,D], v[B,S,Hkv,D], layer_cache, layer_idx) ->
#   (out[B,S,H,D], new_layer_cache)
AttentionFn = Callable[[Array, Array, Array, Any, Array], tuple[Array, Any]]


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 260
    dim: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    hidden_dim: int = 256
    rope_theta: float = 10_000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    # MoE (Mixtral-family): 0 = dense MLP. When > 0 the per-layer MLP is
    # n_experts SwiGLU experts with top-k routing; expert weights shard
    # over the mesh's `expert` axis (EP) — see moe_mlp below.
    n_experts: int = 0
    top_k_experts: int = 2

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


# Model shapes follow the public architecture cards; "tiny"/"mini" are
# random-weight debug/bench configs.
PRESETS: dict[str, LlamaConfig] = {
    "tiny": LlamaConfig(),
    "mini": LlamaConfig(vocab_size=260, dim=512, n_layers=8, n_heads=8, n_kv_heads=4, hidden_dim=1536, max_seq_len=4096),
    "tinyllama-1.1b": LlamaConfig(
        vocab_size=32_000, dim=2048, n_layers=22, n_heads=32, n_kv_heads=4,
        hidden_dim=5632, rope_theta=10_000.0, max_seq_len=2048,
    ),
    "llama3-8b": LlamaConfig(
        vocab_size=128_256, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        hidden_dim=14_336, rope_theta=500_000.0, max_seq_len=8192,
    ),
    "llama3-70b": LlamaConfig(
        vocab_size=128_256, dim=8192, n_layers=80, n_heads=64, n_kv_heads=8,
        hidden_dim=28_672, rope_theta=500_000.0, max_seq_len=8192,
    ),
    # random-weight MoE debug config (Mixtral-shaped routing, tiny dims)
    "moe-tiny": LlamaConfig(
        vocab_size=260, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        hidden_dim=256, n_experts=4, top_k_experts=2,
    ),
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32_000, dim=4096, n_layers=32, n_heads=32, n_kv_heads=8,
        hidden_dim=14_336, rope_theta=1_000_000.0, max_seq_len=8192,
        n_experts=8, top_k_experts=2,
    ),
}


def n_params(config: LlamaConfig) -> int:
    """Analytic parameter count (no materialization) — bench.py uses it
    to weight-bytes-normalize throughput across model sizes."""
    c = config
    d, hd = c.dim, c.head_dim
    attn = d * (c.n_heads * hd) + 2 * d * (c.n_kv_heads * hd) + (c.n_heads * hd) * d
    mlp = 3 * d * c.hidden_dim
    if c.n_experts:
        mlp = mlp * c.n_experts + d * c.n_experts  # experts + router
    per_layer = attn + mlp + 2 * d
    total = c.vocab_size * d + c.n_layers * per_layer + d
    if not c.tie_embeddings:
        total += d * c.vocab_size
    return total


# Leaves with more elements than this random-init directly in the model
# dtype instead of fp32-then-cast: the fp32 intermediate for a stacked 8B
# leaf (mlp_down [32,14336,4096] = 7.5 GB) plus the already-materialized
# quantized leaves would overflow one v5e chip's 16 GB HBM during
# init_quantized init. Small (test-preset) leaves keep the fp32->cast
# path so pinned golden decode sequences are unchanged. Module-level so
# tests can patch it to exercise the large-leaf branch at small shapes.
FP32_INIT_MAX_ELEMS = 1 << 28


def init_params(
    config: LlamaConfig, key: Array, leaf_transform: Any = None
) -> dict[str, Any]:
    """Random-init params as a pytree with stacked layers.

    Layout (L = n_layers, leading axis of every ``layers`` leaf):
      embed[vocab, dim]
      layers/attn_{q,k,v,o}[L, ...], layers/mlp_{gate,up,down}[L, ...],
      layers/ln_attn[L, dim], layers/ln_mlp[L, dim]
      norm[dim], lm_head[dim, vocab] (absent when tie_embeddings)

    ``leaf_transform(name, array)`` is applied to each MATMUL weight at
    creation, before the next leaf materializes — so e.g. int8 quantization
    (models/quant.py init_quantized_llama_params) never holds the full
    bf16 tree, which for llama3-8b alone exceeds one v5e chip's 16 GB HBM.
    """
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    tf = leaf_transform or (lambda name, x: x)

    def rand_init(name: str, k: Array, shape: tuple[int, ...], fan_in: int) -> Array:
        import math

        # see FP32_INIT_MAX_ELEMS: large leaves skip the fp32 intermediate
        gen_dtype = c.dtype if math.prod(shape) > FP32_INIT_MAX_ELEMS else jnp.float32
        return tf(name, (jax.random.normal(k, shape, gen_dtype) * fan_in ** -0.5).astype(c.dtype))

    keys = jax.random.split(k_layers, 8)
    L, D, H, Hkv, hd, F = c.n_layers, c.dim, c.n_heads, c.n_kv_heads, c.head_dim, c.hidden_dim
    params: dict[str, Any] = {
        "embed": rand_init("embed", k_embed, (c.vocab_size, D), D),
        "layers": {
            "attn_q": rand_init("attn_q", keys[0], (L, D, H * hd), D),
            "attn_k": rand_init("attn_k", keys[1], (L, D, Hkv * hd), D),
            "attn_v": rand_init("attn_v", keys[2], (L, D, Hkv * hd), D),
            "attn_o": rand_init("attn_o", keys[3], (L, H * hd, D), H * hd),
            "ln_attn": jnp.ones((L, D), c.dtype),
            "ln_mlp": jnp.ones((L, D), c.dtype),
        },
        "norm": jnp.ones((D,), c.dtype),
    }
    if c.n_experts:
        E = c.n_experts
        params["layers"].update(
            {
                # router stays fp32: routing is precision-sensitive, tiny
                "router": jax.random.normal(keys[7], (L, D, E), jnp.float32) * D ** -0.5,
                "moe_gate": rand_init("moe_gate", keys[4], (L, E, D, F), D),
                "moe_up": rand_init("moe_up", keys[5], (L, E, D, F), D),
                "moe_down": rand_init("moe_down", keys[6], (L, E, F, D), F),
            }
        )
    else:
        params["layers"].update(
            {
                "mlp_gate": rand_init("mlp_gate", keys[4], (L, D, F), D),
                "mlp_up": rand_init("mlp_up", keys[5], (L, D, F), D),
                "mlp_down": rand_init("mlp_down", keys[6], (L, F, D), F),
            }
        )
    if not c.tie_embeddings:
        params["lm_head"] = rand_init("lm_head", k_head, (D, c.vocab_size), D)
    return params


def rms_norm(x: Array, weight: Array, eps: float) -> Array:
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * weight


def rope(x: Array, positions: Array, theta: float) -> Array:
    """Rotary position embedding, fp32 math. x: [B,S,H,D], positions: [B,S]."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)  # [half]
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]  # [B,S,half]
    cos = jnp.cos(angles)[:, :, None, :]  # [B,S,1,half]
    sin = jnp.sin(angles)[:, :, None, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = x32[..., :half], x32[..., half:]
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rotated.astype(x.dtype)


def moe_mlp(h: Array, layer_params: dict[str, Array], config: LlamaConfig,
            qm_backend: str | None = None) -> Array:
    """Mixtral-style top-k routed SwiGLU experts, expert-parallel the GSPMD
    way: expert weights carry a leading E axis sharded over the mesh's
    ``expert`` axis (parallel/sharding.py), every expert computes over all
    tokens with its gate weight zeroed where not routed, and XLA turns the
    expert-sum into a psum over the EP shards. Dense dispatch — no token
    dropping / capacity factor; per-token FLOPs scale with E rather than
    top_k, the classic trade for static shapes at small E. A
    capacity-bucketed all_to_all dispatch is the upgrade path when E is
    large enough for dense dispatch to dominate the profile.
    """
    c = config
    E = c.n_experts
    # router in fp32 (routing decisions are precision-sensitive; the router
    # leaf itself is kept fp32 by init_params / the checkpoint loader)
    r = jnp.einsum("bsd,de->bse", h, layer_params["router"],
                   preferred_element_type=jnp.float32)  # [B,S,E]
    # exactly-k selection from top_k INDICES (threshold comparison would
    # over-select on tied logits); softmax over the selected logits only
    # (Mixtral renormalization), scattered back to expert positions
    top_vals, top_idx = jax.lax.top_k(r, c.top_k_experts)  # [B,S,k]
    w = jax.nn.softmax(top_vals, axis=-1)  # [B,S,k]
    onehot = jax.nn.one_hot(top_idx, E, dtype=w.dtype)  # [B,S,k,E]
    gates = jnp.einsum("bske,bsk->bse", onehot, w).astype(h.dtype)  # [B,S,E]

    def expert_mm(spec: str, x: Array, w: Array | QTensor | Q4Tensor) -> Array:
        # int8/int4 serving: the stacked-expert einsums keep INLINE dequant
        # (no fused kernel tiles the leading E axis — ops/dispatch
        # quant_matmul counts the would-be route as a fallback); XLA fuses
        # the upcast+scale into the dot's operand read where it can
        if isinstance(w, (QTensor, Q4Tensor)):
            if qm_backend not in (None, "ref"):
                from finchat_tpu.utils.metrics import METRICS

                METRICS.inc("finchat_quantmatmul_fallbacks_total")
            w = dequantize(w, x.dtype)
        return jnp.einsum(spec, x, w)

    gate = expert_mm("bsd,edf->bsef", h, layer_params["moe_gate"])
    up = expert_mm("bsd,edf->bsef", h, layer_params["moe_up"])
    act = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
    act = act * gates[..., None]  # zero non-routed experts pre-projection
    return expert_mm("bsef,efd->bsd", act, layer_params["moe_down"])


def _layer(
    x: Array,
    layer_params: dict[str, Array],
    layer_cache: Any,
    layer_idx: Array,
    *,
    positions: Array,
    config: LlamaConfig,
    attention: AttentionFn,
    tp_axis: str | None = None,
    tp_size: int = 1,
    tp_overlap: bool = False,
    tp_chunks: int = 4,
    qm_backend: str | None = None,
) -> tuple[Array, Any]:
    """One decoder layer. Under GSPMD (the usual path) ``tp_axis`` is
    None — the compiler partitions from the param shardings. Under an
    ALL-MANUAL ``shard_map`` (the stage pipeline, parallel/pipeline.py)
    pass the TP mesh axis + size: weights arrive as Megatron shards
    (column-parallel q/k/v/gate/up, row-parallel o/down), head counts are
    local, and the two row-parallel outputs all-reduce over ``tp_axis`` —
    serially, or with the chunked collective–compute overlap schedule
    (``tp_overlap``, ops/tp_overlap.py — byte-identical per element).
    ``qm_backend`` routes quantized matmul leaves (ops/dispatch)."""
    c = config
    B, S, D = x.shape
    hq = c.n_heads // tp_size
    hkv = c.n_kv_heads // tp_size

    h = rms_norm(x, layer_params["ln_attn"], c.norm_eps)
    q = dense(h, layer_params["attn_q"], qm_backend=qm_backend).reshape(B, S, hq, c.head_dim)
    k = dense(h, layer_params["attn_k"], qm_backend=qm_backend).reshape(B, S, hkv, c.head_dim)
    v = dense(h, layer_params["attn_v"], qm_backend=qm_backend).reshape(B, S, hkv, c.head_dim)
    q = rope(q, positions, c.rope_theta)
    k = rope(k, positions, c.rope_theta)

    attn_out, new_layer_cache = attention(q, k, v, layer_cache, layer_idx)
    if tp_axis is not None:
        from finchat_tpu.ops.tp_overlap import row_parallel_dense

        attn_proj = row_parallel_dense(
            attn_out.reshape(B, S, -1), layer_params["attn_o"], tp_axis,
            overlap=tp_overlap, n_chunks=tp_chunks, qm_backend=qm_backend,
        )
    else:
        attn_proj = dense(attn_out.reshape(B, S, -1), layer_params["attn_o"],
                          qm_backend=qm_backend)
    x = x + attn_proj

    h = rms_norm(x, layer_params["ln_mlp"], c.norm_eps)
    if c.n_experts:
        assert tp_axis is None, "manual-TP stage blocks are dense-only (PPxEP future work)"
        x = x + moe_mlp(h, layer_params, c, qm_backend=qm_backend)
    else:
        gate = dense(h, layer_params["mlp_gate"], qm_backend=qm_backend)
        up = dense(h, layer_params["mlp_up"], qm_backend=qm_backend)
        act = jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up
        if tp_axis is not None:
            from finchat_tpu.ops.tp_overlap import row_parallel_dense

            down = row_parallel_dense(
                act, layer_params["mlp_down"], tp_axis,
                overlap=tp_overlap, n_chunks=tp_chunks, qm_backend=qm_backend,
            )
        else:
            down = dense(act, layer_params["mlp_down"], qm_backend=qm_backend)
        x = x + down
    return x, new_layer_cache


def forward(
    params: dict[str, Any],
    tokens: Array,  # [B, S] int32
    positions: Array,  # [B, S] int32 absolute positions
    *,
    config: LlamaConfig,
    attention: AttentionFn,
    cache: Any = None,  # full-depth cache pytree (carried), or None
    remat: bool = False,  # checkpoint each scanned layer (training)
    return_hidden: bool = False,  # post-norm hidden states, no LM head
    qm_backend: str | None = None,  # quantized-matmul backend (ops/dispatch)
) -> tuple[Array, Any]:
    """Run the decoder; returns (logits[B,S,vocab] fp32, new_cache) — or
    (hidden[B,S,D], new_cache) with ``return_hidden``, for callers that
    project only a subset of positions (the seq-sharded long prefill keeps
    one row; a full [S, vocab] fp32 logits tensor there would cost GBs).

    The cache rides the layer scan as part of the CARRY and the attention
    callback receives the whole cache plus the layer index (kernels index
    the layer via scalar prefetch). The alternative — slicing the cache as
    scan xs and restacking updates as ys — forces XLA to write a fresh
    full-cache buffer every step (~22 ms/step measured for a 1.5 GB cache,
    benchmarks/probe_cache_styles.py); carrying it lets the in-place Pallas
    writers (ops/kv_append.py) keep the buffer aliased end to end.
    """
    c = config
    x = params["embed"][tokens]  # [B,S,D]

    def scan_body(carry, scanned):
        x, cache = carry
        layer_params, layer_idx = scanned
        x, cache = _layer(
            x, layer_params, cache, layer_idx,
            positions=positions, config=c, attention=attention,
            qm_backend=qm_backend,
        )
        return (x, cache), None

    if remat:
        # per-layer remat: backward recomputes one layer at a time, so live
        # residuals stay O(one layer) instead of O(n_layers)
        scan_body = jax.checkpoint(scan_body)

    layer_ids = jnp.arange(c.n_layers)
    (x, new_cache), _ = lax.scan(scan_body, (x, cache), (params["layers"], layer_ids))

    x = rms_norm(x, params["norm"], c.norm_eps)
    if return_hidden:
        return x, new_cache
    logits = lm_head(params, x, config=c, qm_backend=qm_backend)
    return logits, new_cache


def lm_head(params: dict[str, Any], x: Array, *, config: LlamaConfig,
            qm_backend: str | None = None) -> Array:
    """Project hidden states [..., D] to fp32 logits [..., vocab]. A
    quantized head routes through quant_matmul (the reference backend is
    bitwise the historical dequantize-then-einsum; the fused kernel
    accumulates fp32 and streams the head packed)."""
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    if isinstance(head, (QTensor, Q4Tensor)):
        from finchat_tpu.ops.dispatch import quant_matmul

        return quant_matmul(x, head, backend=qm_backend,
                            preferred_element_type=jnp.float32)
    return jnp.einsum("...d,dv->...v", x, head, preferred_element_type=jnp.float32)


def make_causal_attention(backend: str) -> AttentionFn:
    """Cache-less causal attention over the whole sequence (training, tests,
    one-shot prefill) on an explicitly-resolved backend. Callers that jit
    must resolve the backend OUTSIDE the traced function and key their jit
    cache on it — resolving env state at trace time bakes the first answer
    into the cache (see ops/dispatch.py)."""
    from finchat_tpu.ops.dispatch import causal_attention

    def attention(q: Array, k: Array, v: Array, layer_cache: Any, layer_idx: Array) -> tuple[Array, Any]:
        return causal_attention(q, k, v, backend=backend), layer_cache

    return attention


def full_causal_attention(q: Array, k: Array, v: Array, layer_cache: Any, layer_idx: Array) -> tuple[Array, Any]:
    """Backend resolved per-call — ONLY for non-jitted use or single-trace
    contexts; jitted callers should use make_causal_attention(backend)."""
    from finchat_tpu.ops.dispatch import causal_attention

    return causal_attention(q, k, v), layer_cache


@partial(jax.jit, static_argnames=("config", "attn_backend", "qm_backend"))
def _forward_full_jit(
    params: dict[str, Any], tokens: Array, positions: Array, *, config: LlamaConfig, attn_backend: str,
    qm_backend: str | None = None,
) -> Array:
    logits, _ = forward(
        params, tokens, positions, config=config,
        attention=make_causal_attention(attn_backend), cache=None,
        qm_backend=qm_backend,
    )
    return logits


def forward_full(
    params: dict[str, Any], tokens: Array, positions: Array, *,
    config: LlamaConfig, attn_backend: str | None = None,
    qm_backend: str | None = None,
) -> Array:
    """Convenience jitted forward with full causal attention, no cache.
    The backends resolve at CALL time and key the jit cache."""
    if attn_backend is None:
        from finchat_tpu.ops.dispatch import attention_backend

        attn_backend = attention_backend()
    return _forward_full_jit(params, tokens, positions, config=config,
                             attn_backend=attn_backend, qm_backend=qm_backend)
