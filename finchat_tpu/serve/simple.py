"""Agent-less streaming chain — reference ``llm_service.py`` parity.

The reference keeps a dormant ``LLMService`` beside the agent: a bare
``prompt | llm`` streaming chain with no tools and no RAG
(``llm_service.py:18-32``) — the minimum end-to-end slice (BASELINE
config 1's single-turn chat shape, SURVEY §3.5). This is its TPU-native
analog: the same prompt structure the agent renders (system + context /
history / user) streamed straight through a ``TextGenerator`` — no
graph, no retrieval, no status events.

Useful for exactly what the reference kept it for: a minimal serving
path for debugging the engine, and a fallback chat mode when the agent
stack is not wanted.
"""

from __future__ import annotations

from typing import AsyncIterator, Sequence

from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.io.schemas import ChatMessage
from finchat_tpu.models.tokenizer import render_chat
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class LLMService:
    """``prompt | llm`` with streaming, nothing else (llm_service.py:18-32).

    The generator is any ``TextGenerator`` (engine-backed in production,
    stub in dev) — the seam the reference has at its ChatGoogleGenerativeAI
    construction (:12-16).
    """

    def __init__(self, generator, system_prompt: str,
                 sampling: SamplingParams | None = None):
        self.generator = generator
        self.system_prompt = system_prompt
        self.sampling = sampling or SamplingParams()

    async def process_message(
        self,
        message: str,
        context: str = "",
        chat_history: Sequence[ChatMessage] = (),
        system_prompt: str | None = None,
    ) -> AsyncIterator[str]:
        """Stream the response to one user message (reference
        ``process_message``, llm_service.py:21-32: same prompt pieces —
        system + context as the system turn, history, user input — same
        chunked streaming output)."""
        prompt = render_chat(
            system_prompt if system_prompt is not None else self.system_prompt,
            context, list(chat_history), message,
        )
        async for chunk in self.generator.stream(prompt, self.sampling):
            yield chunk
