"""Pod-scale multi-host fleet plane (ISSUE 20; ROBUSTNESS.md §7).

One process = one HOST = one failure domain. Inside a host, PR 6's
``EngineFleet`` runs N replicas over one (possibly model-parallel
sharded) weights tree; across hosts this module makes the pod cohere:

- **Routing is partition assignment.** Each host's App is ONE member of
  the Kafka consumer group, so the broker's partition assignment IS the
  cross-host routing table (the same routing ≡ assignment alignment the
  in-host router already has, one level up). A host's death is a group
  rebalance: only the dead host's partition share moves, and a rejoin
  restores the exact prior mapping (assignment is positional round-robin
  over the member list).
- **Liaison channel.** A minimal length-prefixed frame protocol
  (``FPOD`` magic, version byte, JSON header with a payload CRC) over
  asyncio TCP or an in-process registry (``inproc:`` — the simulated-pod
  and test transport), carrying two ops: ``ping``/``pong`` heartbeats
  (the failure detector; pongs also teach each peer's Kafka member id)
  and ``pull_session`` (session-byte transfer: the newest record for a
  conversation, in the session disk tier's own checksummed v2 record
  format — the drain-handoff wire format going cross-host unchanged).
  Every call has timeout + retry with exponential backoff, and each peer
  has a circuit breaker (``pod.breaker_threshold`` consecutive failures
  open the channel; a half-open probe rides the next call after
  ``pod.breaker_cooldown_seconds``). Fault sites ``pod.heartbeat`` and
  ``pod.transfer`` are armable like every other plane's.
- **Host-death adoption.** ``pod.heartbeat_miss_threshold`` consecutive
  missed heartbeats declare a peer dead: the coordinator evicts its
  group member (what a real broker's ``session.timeout.ms`` does; the
  memory broker has no timer, so the pod's verdict drives it), diffs its
  OWN assignment to find the partitions it just inherited, and replays
  exactly those per-partition journals into the dedupe ring
  (``AnsweredJournal.replay(partitions=..., compact=False)`` — journal
  ownership aligns with partition ownership, so there is no global
  journal to merge and no double-answer after a host-level kill -9).
  The dead host's conversations then resume on the adopter via the
  normal admission path: warm from the shared disk fabric (PR 17) when
  one is configured, warm via a liaison ``pull_session`` from a live
  prior owner otherwise, counted cold start as the last resort.
- **Graceful degradation.** ``pod.host_id`` empty = this module never
  constructed: bit-identical to the PR 17 fleet. Peers configured but
  unreachable, a transfer CRC mismatch, an import refusal (cross-KV-mode
  records are refused and counted by ``import_session_entry`` itself) —
  every pod-path failure falls back to a counted cold start on
  ``finchat_pod_cold_starts_total{reason=...}``, never a user error.

Multi-host journal note: per-partition journal files make adoption
replay exact only when the adopter can READ the dead host's files — in
a real pod the journal directory lives on the shared disk fabric (PR
17's shared tier storage); simulated pods in one process share a local
directory, which is the same thing.
"""

from __future__ import annotations

import asyncio
import json
import time
import zlib

from finchat_tpu.utils.config import GROUP_ID, PodConfig
from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

MAGIC = b"FPOD"
VERSION = 1

PEER_LIVE = "LIVE"
PEER_DEAD = "DEAD"

# finchat_pod_cold_starts_total reasons, pre-seeded (R5):
# breaker_open     — the peer's liaison channel is open; no pull attempted
# peer_unreachable — transport failure through every retry
# transfer_corrupt — frame or record failed its checksum/shape checks
# import_refused   — the record arrived intact but the engine refused it
#                    (cross-KV-mode, unmatched shared head, over budget)
COLD_START_REASONS = ("breaker_open", "peer_unreachable",
                      "transfer_corrupt", "import_refused")

# bound on the known-cold conversation memo (see PodCoordinator.maybe_pull)
_PULL_MEMO_CAP = 65536


# --- frame codec -----------------------------------------------------------

def encode_frame(op: str, meta: dict | None = None, payload: bytes = b"") -> bytes:
    """``FPOD | u8 version | u32 header_len | header JSON | payload`` —
    the same length-prefixed + checksummed shape as the session disk
    tier's records, so a torn or bit-flipped frame is always detected,
    never misparsed."""
    header = json.dumps({
        "op": op,
        "meta": meta or {},
        "payload_len": len(payload),
        "crc": zlib.crc32(payload),
    }).encode()
    return (MAGIC + bytes([VERSION]) + len(header).to_bytes(4, "big")
            + header + payload)


def decode_frame(raw: bytes) -> tuple[str, dict, bytes]:
    """(op, meta, payload); raises ValueError on any anomaly."""
    if raw[:4] != MAGIC:
        raise ValueError("bad liaison frame magic")
    if raw[4] != VERSION:
        raise ValueError(f"unknown liaison frame version {raw[4]}")
    hlen = int.from_bytes(raw[5:9], "big")
    header = json.loads(raw[9:9 + hlen].decode())
    payload = raw[9 + hlen:]
    if len(payload) != header["payload_len"]:
        raise ValueError("truncated liaison frame")
    if zlib.crc32(payload) != header["crc"]:
        raise ValueError("liaison frame checksum mismatch")
    return header["op"], header.get("meta") or {}, payload


async def _read_frame(reader: asyncio.StreamReader) -> tuple[str, dict, bytes]:
    head = await reader.readexactly(9)
    if head[:4] != MAGIC:
        raise ValueError("bad liaison frame magic")
    hlen = int.from_bytes(head[5:9], "big")
    header_bytes = await reader.readexactly(hlen)
    payload_len = json.loads(header_bytes.decode())["payload_len"]
    payload = await reader.readexactly(payload_len)
    return decode_frame(head + header_bytes + payload)


def _parse_addr(addr: str) -> tuple[str, str]:
    """``tcp:host:port`` / ``inproc:name`` → (kind, rest)."""
    kind, sep, rest = addr.partition(":")
    if not sep or kind not in ("tcp", "inproc") or not rest:
        raise ValueError(f"bad liaison address {addr!r} "
                         "(expected tcp:<host>:<port> or inproc:<name>)")
    return kind, rest


def parse_peers(spec: str) -> dict[str, str]:
    """``pod.peers`` ("hostB=tcp:127.0.0.1:9710,hostC=inproc:hostC") →
    {host_id: addr}. Raises ValueError on a malformed entry — a typo'd
    peer table should fail loudly at startup, not silently drop a host
    from the failure detector."""
    out: dict[str, str] = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        host, sep, addr = item.partition("=")
        if not sep or not host.strip():
            raise ValueError(f"bad pod.peers entry {item!r} "
                             "(expected <host_id>=<addr>)")
        _parse_addr(addr.strip())
        out[host.strip()] = addr.strip()
    return out


# --- in-process transport --------------------------------------------------

# inproc liaison registry: name -> PodLiaison. The simulated-pod/test
# transport — requests still round-trip through encode/decode on both
# sides, so the codec (and its CRC) is exercised identically to TCP.
_INPROC: dict[str, "PodLiaison"] = {}


class PodLiaison:
    """The host's liaison endpoint: serves ping/pull_session for peers
    and dials theirs. All I/O is asyncio (finchat-lint R1: no blocking
    socket primitive ever touches the event loop — the rule now covers
    recv/sendall/accept/create_connection to keep it that way)."""

    def __init__(self, cfg: PodConfig, coordinator: "PodCoordinator"):
        self.cfg = cfg
        self.coordinator = coordinator
        self._server: asyncio.AbstractServer | None = None
        self._inproc_name: str | None = None
        self._closed = False

    async def start(self) -> None:
        if not self.cfg.listen:
            return
        kind, rest = _parse_addr(self.cfg.listen)
        if kind == "inproc":
            _INPROC[rest] = self
            self._inproc_name = rest
        else:
            host, _, port = rest.rpartition(":")
            self._server = await asyncio.start_server(
                self._serve_conn, host or "127.0.0.1", int(port)
            )
        logger.info("pod: liaison for %s listening on %s",
                    self.coordinator.host_id, self.cfg.listen)

    def kill(self) -> None:
        """Drop off the wire with no goodbye — also the kill -9
        simulation: peers see timeouts/refusals, never a clean close."""
        self._closed = True
        if self._inproc_name is not None:
            _INPROC.pop(self._inproc_name, None)
            self._inproc_name = None
        if self._server is not None:
            self._server.close()
            self._server = None

    # --- server side -----------------------------------------------------
    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    op, meta, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    break
                except ValueError as e:
                    logger.warning("pod: dropping corrupt liaison frame: %s", e)
                    break
                rop, rmeta, rpayload = await self._handle(op, meta, payload)
                writer.write(encode_frame(rop, rmeta, rpayload))
                await writer.drain()
        except (ConnectionError, asyncio.CancelledError):
            pass
        finally:
            writer.close()

    async def _handle(self, op: str, meta: dict,
                      payload: bytes) -> tuple[str, dict, bytes]:
        if self._closed:
            raise ConnectionError("liaison is down")
        if op == "ping":
            return "pong", self.coordinator.identity(), b""
        if op == "pull_session":
            rec = await self.coordinator.export_record(meta.get("key", ""))
            if rec is None:
                return "miss", {}, b""
            return "record", {"key": meta.get("key", "")}, rec
        return "error", {"message": f"unknown liaison op {op!r}"}, b""

    # --- client side -----------------------------------------------------
    async def call(self, addr: str, op: str, meta: dict | None = None,
                   payload: bytes = b"",
                   timeout: float = 5.0) -> tuple[str, dict, bytes]:
        kind, rest = _parse_addr(addr)
        if kind == "inproc":
            target = _INPROC.get(rest)
            if target is None:
                raise ConnectionError(f"inproc liaison {rest!r} not listening")
            # round-trip both frames through the codec so inproc and TCP
            # exercise identical bytes (CRC checks included)
            rop, rmeta, rpayload = decode_frame(encode_frame(op, meta, payload))
            reply = await asyncio.wait_for(
                target._handle(rop, rmeta, rpayload), timeout
            )
            return decode_frame(encode_frame(*reply))
        host, _, port = rest.rpartition(":")
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host or "127.0.0.1", int(port)), timeout
        )
        try:
            writer.write(encode_frame(op, meta, payload))
            await writer.drain()
            return await asyncio.wait_for(_read_frame(reader), timeout)
        finally:
            writer.close()
            try:
                await writer.wait_closed()
            except Exception:
                pass


# --- peer bookkeeping ------------------------------------------------------

class PeerChannel:
    """One peer host: liveness verdict + per-peer circuit breaker."""

    def __init__(self, host_id: str, addr: str, cfg: PodConfig):
        self.host_id = host_id
        self.addr = addr
        self.cfg = cfg
        self.state = PEER_LIVE  # optimistic until the detector says otherwise
        self.misses = 0
        self.member_id: str | None = None  # learned from pongs
        self._consec_failures = 0
        self._open_until = 0.0

    def breaker_allows(self) -> bool:
        """Closed, or open with the cooldown elapsed (the half-open
        probe: one call rides through; a failure re-opens)."""
        if self._consec_failures < self.cfg.breaker_threshold:
            return True
        return time.monotonic() >= self._open_until

    def record_success(self) -> None:
        self._consec_failures = 0

    def record_failure(self) -> None:
        self._consec_failures += 1
        if self._consec_failures == self.cfg.breaker_threshold:
            METRICS.inc("finchat_pod_breaker_trips_total")
            logger.warning("pod: liaison breaker to %s opened after %d "
                           "consecutive failures", self.host_id,
                           self._consec_failures)
        if self._consec_failures >= self.cfg.breaker_threshold:
            self._open_until = (time.monotonic()
                                + self.cfg.breaker_cooldown_seconds)


class PodCoordinator:
    """The host's pod brain: heartbeats the peer table, adopts a dead
    peer's partitions (journal replay into the dedupe ring included),
    serves and performs cross-host session pulls."""

    def __init__(self, cfg: PodConfig, *, fleet=None, kafka=None,
                 journal=None, dedupe=None):
        self.cfg = cfg
        self.host_id = cfg.host_id
        self.fleet = fleet
        self.kafka = kafka
        self.journal = journal
        self.dedupe = dedupe
        self.liaison = PodLiaison(cfg, self)
        self.peers: dict[str, PeerChannel] = {
            host: PeerChannel(host, addr, cfg)
            for host, addr in parse_peers(cfg.peers).items()
        }
        self._hb_task: asyncio.Task | None = None
        self._prev_assignment: set[tuple[str, int]] = set()
        # partitions whose conversations may have lived on another host
        # (everything we own at join time, plus everything adopted since)
        self._pull_partitions: set[int] = set()
        # conversations already pulled-or-missed: one liaison round per
        # conversation, not one per turn
        self._pull_done: set[str] = set()
        self.on_peer_dead: list = []  # callbacks(host_id, PeerChannel)
        self.on_peer_alive: list = []
        METRICS.inc("finchat_pod_heartbeats_total", 0.0)
        METRICS.inc("finchat_pod_heartbeat_failures_total", 0.0)
        METRICS.inc("finchat_pod_peer_deaths_total", 0.0)
        METRICS.inc("finchat_pod_peer_rejoins_total", 0.0)
        METRICS.inc("finchat_pod_partition_adoptions_total", 0.0)
        METRICS.inc("finchat_pod_adopted_ids_replayed_total", 0.0)
        METRICS.inc("finchat_pod_session_pulls_total", 0.0)
        METRICS.inc("finchat_pod_pull_misses_total", 0.0)
        METRICS.inc("finchat_pod_breaker_trips_total", 0.0)
        for reason in COLD_START_REASONS:
            METRICS.inc("finchat_pod_cold_starts_total", 0.0,
                        labels={"reason": reason})

    def identity(self) -> dict:
        return {
            "host_id": self.host_id,
            "member_id": getattr(self.kafka, "member_id", None),
        }

    # --- lifecycle -------------------------------------------------------
    async def start(self) -> None:
        await self.liaison.start()
        if self.kafka is not None:
            self._prev_assignment = set(self.kafka.assignment())
            if self.peers:
                # a host joining a pod presumes any of its partitions may
                # have been served elsewhere before (rejoin after a kill,
                # scale-out into a running pod): first contact with each
                # conversation is allowed one pull round
                self._pull_partitions = {p for _t, p in self._prev_assignment}
        self._publish_hosts_live()
        if self.peers:
            self._hb_task = asyncio.create_task(self._heartbeat_loop())

    async def stop(self) -> None:
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except asyncio.CancelledError:
                pass
            self._hb_task = None
        self.liaison.kill()

    def kill(self) -> None:
        """kill -9 simulation: no drain, no goodbye — the liaison drops
        off the wire and the heartbeat task dies mid-flight."""
        if self._hb_task is not None:
            self._hb_task.cancel()
            self._hb_task = None
        self.liaison.kill()

    def _publish_hosts_live(self) -> None:
        live = 1 + sum(1 for p in self.peers.values() if p.state == PEER_LIVE)
        METRICS.set_gauge("finchat_pod_hosts_live", live)

    # --- failure detector ------------------------------------------------
    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(self.cfg.heartbeat_interval_seconds)
            for peer in list(self.peers.values()):
                await self._heartbeat(peer)

    async def _heartbeat(self, peer: PeerChannel) -> None:
        try:
            inject("pod.heartbeat", peer=peer.host_id, host=self.host_id)
            op, meta, _ = await self.liaison.call(
                peer.addr, "ping", {"host_id": self.host_id},
                timeout=self.cfg.transfer_timeout_seconds,
            )
            if op != "pong":
                raise ConnectionError(f"unexpected heartbeat reply {op!r}")
            METRICS.inc("finchat_pod_heartbeats_total")
            peer.misses = 0
            peer.record_success()
            if meta.get("member_id"):
                peer.member_id = meta["member_id"]
            if peer.state == PEER_DEAD:
                self._peer_rejoined(peer)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            METRICS.inc("finchat_pod_heartbeat_failures_total")
            peer.misses += 1
            peer.record_failure()
            if (peer.state == PEER_LIVE
                    and peer.misses >= self.cfg.heartbeat_miss_threshold):
                self._peer_died(peer, reason=str(e))

    def _peer_died(self, peer: PeerChannel, reason: str = "") -> None:
        peer.state = PEER_DEAD
        METRICS.inc("finchat_pod_peer_deaths_total")
        logger.error("pod: host %s declared dead after %d missed "
                     "heartbeats (%s); adopting its partition share",
                     peer.host_id, peer.misses, reason)
        TRACER.anomaly("pod_host_lost",
                       args={"host": peer.host_id, "misses": peer.misses})
        self._publish_hosts_live()
        for cb in list(self.on_peer_dead):
            try:
                cb(peer.host_id, peer)
            except Exception as e:
                logger.error("pod: on_peer_dead hook failed: %s", e)
        self._evict_peer_member(peer)
        self._adopt_new_partitions(dead_host=peer.host_id)

    def _peer_rejoined(self, peer: PeerChannel) -> None:
        peer.state = PEER_LIVE
        peer.misses = 0
        METRICS.inc("finchat_pod_peer_rejoins_total")
        logger.info("pod: host %s is back; its partition share returns on "
                    "the next rebalance", peer.host_id)
        self._publish_hosts_live()
        for cb in list(self.on_peer_alive):
            try:
                cb(peer.host_id, peer)
            except Exception as e:
                logger.error("pod: on_peer_alive hook failed: %s", e)
        if self.kafka is not None:
            # re-snapshot so the next death's adoption diff is computed
            # against the restored mapping, not the widened interim one
            self._prev_assignment = set(self.kafka.assignment())

    def _evict_peer_member(self, peer: PeerChannel) -> None:
        """Memory-broker pods: the broker has no session timer, so the
        pod's death verdict evicts the member (a real broker does this
        itself at ``session.timeout.ms``). No member id learned yet —
        the peer died before its first pong — means nothing to evict."""
        broker = getattr(self.kafka, "_broker", None)
        if broker is None or not peer.member_id:
            return
        try:
            broker.evict_member(GROUP_ID, peer.member_id)
        except Exception as e:
            logger.error("pod: evicting %s (%s) from the group failed: %s",
                         peer.host_id, peer.member_id, e)

    # --- partition adoption ----------------------------------------------
    def _adopt_new_partitions(self, dead_host: str = "") -> None:
        if self.kafka is None:
            return
        new = set(self.kafka.assignment())
        inherited = sorted({p for _t, p in new - self._prev_assignment})
        self._prev_assignment = new
        if not inherited:
            return
        METRICS.inc("finchat_pod_partition_adoptions_total", len(inherited))
        self._pull_partitions.update(inherited)
        # first contact with an inherited conversation gets a fresh pull
        # round even if it missed before the rebalance
        self._pull_done.clear()
        replayed = 0
        if self.journal is not None and self.dedupe is not None:
            try:
                # compact=False: these files belonged to the dead host a
                # heartbeat ago — read, never rewrite, while the handoff
                # settles
                ids = self.journal.replay(partitions=inherited, compact=False)
                replayed = self.dedupe.preload(ids)
                if ids:
                    METRICS.inc("finchat_pod_adopted_ids_replayed_total",
                                len(ids))
            except Exception as e:
                logger.error("pod: journal replay for adopted partitions "
                             "%s failed: %s", inherited, e)
        logger.info("pod: %s adopted partition(s) %s from %s (%d answered "
                    "id(s) replayed into the dedupe ring)", self.host_id,
                    inherited, dead_host or "the group", replayed)
        TRACER.event("pod_adopt", track="fleet",
                     args={"host": dead_host, "partitions": inherited,
                           "replayed": replayed})

    # --- session transfer: server side -----------------------------------
    async def export_record(self, key: str) -> bytes | None:
        """Serve a peer's ``pull_session``: the conversation's newest
        record as session-disk-tier v2 bytes — the deepest RAM entry
        across this host's replicas (exported through the scheduler so
        shared-head bookkeeping is honored), else the local disk tier's
        record. None = this host has nothing for the key."""
        if not key or self.fleet is None:
            return None
        from finchat_tpu.engine.session_cache import SessionDiskTier

        best = None
        best_sched = None
        for rep in self.fleet.replicas:
            sched = rep.scheduler
            cache = getattr(sched, "session_cache", None)
            if cache is None:
                continue
            entry = cache.get(key)
            if entry is not None and (best is None
                                      or entry.n_tokens > best.n_tokens):
                best, best_sched = entry, sched
        if best is not None:
            payload = best_sched.export_session(key)
            if payload is not None:
                return SessionDiskTier._serialize(
                    key, payload["token_ids"], payload["prefix_len"],
                    payload["snap"], payload["kv_gap"], payload["kv_sink"],
                )
        for rep in self.fleet.replicas:
            cache = getattr(rep.scheduler, "session_cache", None)
            disk = cache.disk if cache is not None else None
            if disk is not None and key in disk:
                # blocking record read: off-loop, like every disk-tier I/O
                payload = await asyncio.to_thread(disk.load, key)
                if payload is not None:
                    return SessionDiskTier._serialize(
                        key, payload["token_ids"], payload["prefix_len"],
                        payload["snap"], payload["kv_gap"],
                        payload["kv_sink"],
                    )
                return None  # quarantined: nothing intact to serve
        return None

    # --- session transfer: client side ------------------------------------
    async def maybe_pull(self, sched, conversation_id: str,
                         trace_id: str | None = None) -> None:
        """Called by a serving scheduler's ``submit`` before admission
        (mirroring the disagg hook): if the conversation's partition was
        (or may have been) served by another host and nothing local can
        resume it warm, pull its newest record from a live peer and
        import it. Best-effort by contract: every failure is a counted
        cold start, nothing here may raise into submit."""
        if not conversation_id or not self.peers:
            return
        cache = getattr(sched, "session_cache", None)
        if cache is None:
            return
        if cache.get(conversation_id) is not None:
            return  # already warm here
        if cache.disk is not None and conversation_id in cache.disk:
            return  # the local/fabric disk restore path covers it
        if conversation_id in self._pull_done:
            return
        if self.kafka is not None and self._pull_partitions:
            from finchat_tpu.engine.session_cache import conversation_of

            part = self.kafka.partition_for(conversation_of(conversation_id))
            if part not in self._pull_partitions:
                return
        if len(self._pull_done) >= _PULL_MEMO_CAP:
            self._pull_done.clear()
        self._pull_done.add(conversation_id)
        live = [p for p in self.peers.values() if p.state == PEER_LIVE]
        for peer in live:
            if await self._pull_from(peer, sched, cache, conversation_id,
                                     trace_id):
                return

    async def _pull_from(self, peer: PeerChannel, sched, cache,
                         key: str, trace_id: str | None) -> bool:
        """One peer's pull: True = resolved (imported, or an authoritative
        refusal); False = try the next peer (miss/unreachable)."""
        from finchat_tpu.engine.session_cache import SessionDiskTier

        if not peer.breaker_allows():
            METRICS.inc("finchat_pod_cold_starts_total",
                        labels={"reason": "breaker_open"})
            return False
        t0 = time.perf_counter()
        for attempt in range(self.cfg.transfer_retries + 1):
            try:
                inject("pod.transfer", peer=peer.host_id, key=key,
                       attempt=attempt)
                op, _meta, payload = await self.liaison.call(
                    peer.addr, "pull_session", {"key": key},
                    timeout=self.cfg.transfer_timeout_seconds,
                )
                peer.record_success()
                if op == "miss":
                    METRICS.inc("finchat_pod_pull_misses_total")
                    return False
                if op != "record":
                    raise ValueError(f"unexpected pull reply {op!r}")
                rec = SessionDiskTier._deserialize(payload)
                rec = cache.fit_payload(rec)
                ok = rec is not None and sched.import_session_entry(rec)
                if not ok:
                    # authoritative refusal (cross-mode / no matching
                    # head / over budget): retrying cannot change it
                    METRICS.inc("finchat_pod_cold_starts_total",
                                labels={"reason": "import_refused"})
                    return True
                METRICS.inc("finchat_pod_session_pulls_total")
                METRICS.observe("finchat_pod_transfer_seconds",
                                time.perf_counter() - t0)
                TRACER.event("pod_session_pull", trace_id, track="fleet",
                             args={"peer": peer.host_id, "key": key,
                                   "bytes": len(payload)})
                logger.info("pod: pulled %s warm from %s (%d bytes)",
                            key, peer.host_id, len(payload))
                return True
            except asyncio.CancelledError:
                raise
            except ValueError as e:
                # corrupt frame/record: the bytes are wrong, not the wire
                # — a retry would refetch the same corruption
                METRICS.inc("finchat_pod_cold_starts_total",
                            labels={"reason": "transfer_corrupt"})
                logger.warning("pod: pull of %s from %s corrupt (%s) — "
                               "cold start", key, peer.host_id, e)
                return True
            except Exception as e:
                peer.record_failure()
                if attempt < self.cfg.transfer_retries:
                    await asyncio.sleep(
                        self.cfg.retry_backoff_seconds * (2 ** attempt)
                    )
                    continue
                METRICS.inc("finchat_pod_cold_starts_total",
                            labels={"reason": "peer_unreachable"})
                logger.warning("pod: pull of %s from %s failed after %d "
                               "attempt(s): %s — cold start", key,
                               peer.host_id, attempt + 1, e)
                return False
        return False
