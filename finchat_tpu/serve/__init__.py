from finchat_tpu.serve.http import HTTPServer, Request, Response, StreamingResponse
from finchat_tpu.serve.app import App, build_app

__all__ = ["HTTPServer", "Request", "Response", "StreamingResponse", "App", "build_app"]
