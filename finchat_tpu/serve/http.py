"""Minimal asyncio HTTP/1.1 server.

The reference serves FastAPI under gunicorn/uvicorn (``main.py:32-37``);
this image has neither, and the surface is tiny (three routes), so the
server is ~150 lines of stdlib asyncio: request parsing, routing, JSON
responses, and chunked/SSE streaming for token streams. No third-party
dependency, no ASGI indirection in the token hot path.
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import AsyncIterator, Awaitable, Callable

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_MAX_HEADER_BYTES = 64 * 1024
_MAX_BODY_BYTES = 16 * 1024 * 1024


@dataclass
class Request:
    method: str
    path: str
    headers: dict[str, str]
    body: bytes

    def json(self) -> dict:
        return json.loads(self.body.decode("utf-8"))


@dataclass
class Response:
    status: int = 200
    body: bytes = b""
    content_type: str = "application/json"
    headers: dict[str, str] = field(default_factory=dict)

    @classmethod
    def json(cls, payload: object, status: int = 200) -> "Response":
        return cls(status=status, body=json.dumps(payload).encode())

    @classmethod
    def text(cls, text: str, status: int = 200, content_type: str = "text/plain") -> "Response":
        return cls(status=status, body=text.encode(), content_type=content_type)


@dataclass
class StreamingResponse:
    """Chunked-transfer response; ``chunks`` yields byte chunks (e.g. SSE
    ``data:`` lines). Each chunk is flushed immediately — this is the token
    streaming path, so no buffering."""

    chunks: AsyncIterator[bytes]
    status: int = 200
    content_type: str = "text/event-stream"


Handler = Callable[[Request], Awaitable[Response | StreamingResponse]]

_STATUS_TEXT = {200: "OK", 400: "Bad Request", 404: "Not Found", 405: "Method Not Allowed", 500: "Internal Server Error"}


class HTTPServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self._routes: dict[tuple[str, str], Handler] = {}
        # (method, prefix) -> handler, matched after exact routes for
        # path-parameter endpoints like GET /debug/trace/<trace_id>
        self._prefix_routes: list[tuple[str, str, Handler]] = []
        self._server: asyncio.AbstractServer | None = None

    def route(self, method: str, path: str, handler: Handler) -> None:
        self._routes[(method.upper(), path)] = handler

    def route_prefix(self, method: str, prefix: str, handler: Handler) -> None:
        """Register a prefix-matched route; the handler reads the path
        suffix off ``request.path`` (longest prefix wins)."""
        self._prefix_routes.append((method.upper(), prefix, handler))
        self._prefix_routes.sort(key=lambda r: len(r[1]), reverse=True)

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]  # resolve port 0
        logger.info("HTTP server listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    # --- connection handling -------------------------------------------
    async def _read_request(self, reader: asyncio.StreamReader) -> Request | None:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except (asyncio.IncompleteReadError, asyncio.LimitOverrunError):
            return None
        if len(head) > _MAX_HEADER_BYTES:
            return None
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, _version = lines[0].split(" ", 2)
        except ValueError:
            return None
        headers: dict[str, str] = {}
        for line in lines[1:]:
            if ":" in line:
                name, _, value = line.partition(":")
                headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0") or "0")
        if length > _MAX_BODY_BYTES:
            return None
        body = await reader.readexactly(length) if length else b""
        path = target.split("?", 1)[0]
        return Request(method=method.upper(), path=path, headers=headers, body=body)

    @staticmethod
    def _head(status: int, content_type: str, extra: dict[str, str] | None = None, chunked: bool = False, length: int | None = None) -> bytes:
        lines = [f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'OK')}"]
        lines.append(f"Content-Type: {content_type}")
        if chunked:
            lines.append("Transfer-Encoding: chunked")
            lines.append("Cache-Control: no-cache")
        elif length is not None:
            lines.append(f"Content-Length: {length}")
        lines.append("Connection: close")
        for name, value in (extra or {}).items():
            lines.append(f"{name}: {value}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            request = await self._read_request(reader)
            if request is None:
                writer.write(self._head(400, "text/plain", length=0))
                return
            handler = self._routes.get((request.method, request.path))
            if handler is None:
                for method, prefix, h in self._prefix_routes:
                    if method == request.method and request.path.startswith(prefix):
                        handler = h
                        break
            if handler is None:
                if any(path == request.path for _, path in self._routes):
                    writer.write(self._head(405, "text/plain", length=0))
                else:
                    body = b'{"detail":"Not Found"}'
                    writer.write(self._head(404, "application/json", length=len(body)) + body)
                return

            try:
                result = await handler(request)
            except json.JSONDecodeError as e:
                body = json.dumps({"detail": f"invalid JSON body: {e}"}).encode()
                writer.write(self._head(400, "application/json", length=len(body)) + body)
                return
            except LookupError as e:
                # unknown conversation/context → client error, not a 500
                body = json.dumps({"detail": str(e)}).encode()
                writer.write(self._head(404, "application/json", length=len(body)) + body)
                return
            except Exception as e:
                logger.error("handler error on %s %s: %s", request.method, request.path, e, exc_info=True)
                body = json.dumps({"detail": "internal error"}).encode()
                writer.write(self._head(500, "application/json", length=len(body)) + body)
                return

            if isinstance(result, StreamingResponse):
                writer.write(self._head(result.status, result.content_type, chunked=True))
                await writer.drain()
                try:
                    async for chunk in result.chunks:
                        writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                        await writer.drain()  # flush per token chunk
                finally:
                    writer.write(b"0\r\n\r\n")
            else:
                writer.write(
                    self._head(result.status, result.content_type, extra=result.headers, length=len(result.body))
                    + result.body
                )
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass  # client went away mid-stream
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass


def sse_event(payload: dict) -> bytes:
    """Render one server-sent event carrying a JSON payload."""
    return f"data: {json.dumps(payload)}\n\n".encode()
