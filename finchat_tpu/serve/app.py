"""Application wiring: the Kafka worker loop + HTTP surface.

Behavior parity with the reference ``main.py``:

- lifespan: store connection check → consumer setup → consume task
  (main.py:24-30), plus scheduler startup (new).
- ``GET /health`` → ``{"status": "healthy"}`` (main.py:51-53).
- ``process_message``: context+history fetch (errors drop the message,
  main.py:64-70), stream_with_status fan-out where ONLY ``response_chunk``
  and ``complete`` events reach Kafka (main.py:81-110), flushed error chunk
  on failure (main.py:112-122), post-hoc persistence (main.py:125-129).
- consume loop: per-message watchdog (100 s default — main.py:138) emitting
  the timeout chunk, 10 ms idle sleep, 1 s error backoff (main.py:131-159).
- ``POST /chat`` — the reference's commented-out REST path (main.py:44-49),
  implemented: batch ``llm_agent.query``.
- ``POST /chat/stream`` — SSE stream of the FULL internal event protocol
  (status/retrieval_complete/response_chunk/complete), the "richer consumer"
  SURVEY §2.4 calls for.
- ``GET /metrics`` — Prometheus text (new; SURVEY §5.5).
- Conversation plumbing (new): every chat path assembles its inputs through
  ``_conversation_inputs``, which also threads ``conversation_id`` into the
  agent → generator → scheduler chain as the session-KV-cache key
  (engine/session_cache.py), so a conversation's next turn resumes the KV
  its previous turn already computed.
- Transaction ingestion (new; the reference's upsert pipeline lives outside
  its repo, feeding Qdrant out-of-band — qdrant_tool.py:24-37): both
  ``POST /transactions`` and the ``transaction_upsert`` Kafka topic embed
  rows on-device into the vector index, which snapshots to
  ``vector.persist_path`` so retrieval is not empty-at-boot.
"""

from __future__ import annotations

import asyncio
import json
import time
import uuid
from pathlib import Path

import jax

from finchat_tpu.agent.graph import LLMAgent
from finchat_tpu.engine.generator import EngineGenerator, StubGenerator, TextGenerator
from finchat_tpu.engine.engine import InferenceEngine
from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.engine.scheduler import ContinuousBatchingScheduler
from finchat_tpu.io.kafka import KafkaClient
from finchat_tpu.io.schemas import (
    complete_chunk,
    error_chunk,
    plot_chunk,
    response_chunk,
    timeout_chunk,
)
from finchat_tpu.io.store import ConversationStore, make_store
from finchat_tpu.models.llama import PRESETS, init_params
from finchat_tpu.serve.fleet import LIVE, DedupeRing, EngineFleet, EngineReplica
from finchat_tpu.models.tokenizer import get_tokenizer
from finchat_tpu.serve.http import HTTPServer, Request, Response, StreamingResponse, sse_event
from finchat_tpu.tools.retrieval import TransactionRetriever
from finchat_tpu.utils.config import (
    AI_RESPONSE_TOPIC,
    TRANSACTION_UPSERT_TOPIC,
    USER_MESSAGE_TOPIC,
    AppConfig,
)
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

_PROMPTS_DIR = Path(__file__).resolve().parent.parent.parent / "prompts"


_REPACE_DONE = object()


async def _repace_bursts(updates, loop_depth: int, burst_cap: int | None = None):
    """Smooth the fused decode loop's K-token bursts for SSE clients.

    With ``decode_loop_depth`` K > 1 the scheduler delivers K token events
    per device dispatch, so the raw stream is K chunks back-to-back then a
    block-length gap — a visible stutter at the terminal. (The free-run
    capture multiplies the burst: its ring drains up to
    ``freerun_rounds`` x ``loop_depth`` tokens at once — callers pass
    that product as ``burst_cap`` while ``loop_depth`` stays the
    steady-state seed, and the observed-width EMA below adapts between
    them.) This pacer keeps
    the per-chunk emit (every token is still its own SSE frame, flushed
    individually by HTTPServer) but spreads each burst over the observed
    block cadence.

    A reader task timestamps arrivals BEFORE any pacing sleep — measuring
    gaps on the paced consumer side would fold our own sleeps into the
    estimate (the boundary gap shrinks by (K-1)·pace and the EMA converges
    to ~half the true block time, leaving a residual stall). Burst starts
    are detected on the true timeline (members of one block land within
    ~µs of each other), the EMA runs over burst-START-to-burst-start
    deltas (= the true block period), and members are emitted ~block/K
    apart. Added latency is bounded: a chunk is never held past one
    EMA-block after its arrival (drain guard) nor paced more than
    50 ms/token. K <= 1 with no wider cap is a passthrough."""
    burst_cap = max(loop_depth, burst_cap or loop_depth)
    if burst_cap <= 1:
        async for update in updates:
            yield update
        return
    import time as _time

    queue: asyncio.Queue = asyncio.Queue()

    async def _reader():
        try:
            async for update in updates:
                queue.put_nowait((_time.monotonic(), update))
        except BaseException as e:  # propagate into the consumer
            queue.put_nowait((0.0, e))
            return
        queue.put_nowait((0.0, _REPACE_DONE))

    reader = asyncio.create_task(_reader())
    ema: float | None = None
    burst_start: float | None = None
    last_arrival: float | None = None
    next_emit = 0.0
    # observed burst WIDTH (chunks per burst), EMA'd alongside the period:
    # free-run captures engage solely in coexist windows, so steady-state
    # bursts are loop_depth-sized while ring drains reach
    # loop_depth x freerun_rounds — pacing by the static product would
    # spread a steady-state block over 1/freerun_rounds of its period and
    # bring the stutter back. Seed at the steady-state loop_depth (the
    # common case is right from burst one; a wide ring drain is bounded
    # by the never-hold-past-one-block cap while the EMA widens), clamp
    # to [1, burst_cap].
    eff_width = float(max(1, loop_depth))
    burst_n = 0
    try:
        while True:
            t_arr, update = await queue.get()
            if update is _REPACE_DONE:
                return
            if isinstance(update, BaseException):
                raise update
            if update.get("type") != "response_chunk":
                yield update
                continue
            # burst-boundary threshold: EMA-relative with a 10 ms floor —
            # a µs-scale cutoff would let ordinary event-loop jitter
            # between same-block dequeues fragment one burst into several,
            # polluting the EMA with near-zero deltas until the pacer
            # silently degrades to passthrough under load. The floor is
            # safe: a stream whose REAL block boundaries are under 10 ms
            # is already >100 tokens/s/slot and needs no smoothing
            threshold = max(1e-2, ema / (2 * eff_width)) if ema else 1e-2
            if last_arrival is None or t_arr - last_arrival > threshold:
                if burst_start is not None:
                    delta = t_arr - burst_start
                    ema = delta if ema is None else 0.7 * ema + 0.3 * delta
                    eff_width = min(
                        max(0.7 * eff_width + 0.3 * max(burst_n, 1), 1.0),
                        float(burst_cap),
                    )
                burst_start = t_arr
                burst_n = 0
            burst_n += 1
            last_arrival = t_arr
            if ema:
                pace = min(ema / eff_width, 0.05)
                now = _time.monotonic()
                # pace from the previous emit, but never hold a chunk past
                # one block after its true arrival (bounds added latency
                # and lets a backed-up queue drain)
                target = min(max(now, next_emit), t_arr + ema)
                if target > now:
                    await asyncio.sleep(target - now)
                next_emit = target + pace
            yield update
    finally:
        reader.cancel()
        try:
            await reader
        except (asyncio.CancelledError, Exception):
            pass


def load_prompts() -> tuple[str, str]:
    system_prompt = (_PROMPTS_DIR / "system_prompt.txt").read_text()
    tool_prompt = (_PROMPTS_DIR / "tool_prompt.txt").read_text()
    return system_prompt, tool_prompt


def _encode_head(tokenizer, head: str) -> list[int]:
    """Encode a shared prompt head for prefix registration. The final
    encoded token is dropped: a subword tokenizer can merge across the
    head/context string boundary, so the last head token is the only one
    whose identity depends on what follows (the byte tokenizer is
    trivially boundary-stable, but Mixtral serving uses HF BPE). The ONE
    place this boundary rule lives — startup registration and the
    midnight refresh must encode identically or refreshed prefixes would
    silently stop matching."""
    return tokenizer.encode(head, add_bos=True)[:-1]


def register_prompt_prefixes(agent, scheduler, tokenizer) -> set[str]:
    """Prefill each LLM role's constant system head once and share its KV
    across requests (scheduler shared-prefix cache). Returns the
    SUCCESSFULLY registered heads — per head, so one persistently
    failing head (too short for a page, pages exhausted) cannot poison the
    other's registration (see _maybe_refresh_prefix_cache).
    """
    registered: set[str] = set()
    for head in agent.prompt_heads():
        if scheduler.register_prefix(_encode_head(tokenizer, head)) > 0:
            registered.add(head)
    return registered


async def _maybe_refresh_prefix_cache(app: "App") -> None:
    """Re-register the shared prompt heads when they change (midnight date
    rollover): retire the stale prefixes (pages free once the last
    in-flight reference releases) and prefill the fresh heads. Runs from
    the app's periodic checker task — NOT the request path — and registers
    via the scheduler's chunked path (register_prefix_async), so in-flight
    streams keep decoding between head chunks instead of stalling for a
    whole multi-second prefill once a day (VERDICT r4 weak #6)."""
    if not app._prefix_cache_enabled or app.scheduler is None:
        return
    heads = app.agent.prompt_heads()
    if all(h in app._registered_heads for h in heads):
        return  # every current head is live
    tokenizer = getattr(app.agent.tool_generator, "tokenizer", None)
    if tokenizer is None:
        return
    stale = [h for h in app._registered_heads if h not in heads]
    if stale:
        # date rollover: nothing previously registered can match anymore —
        # retire (pages free as in-flight references release) and rebuild
        logger.info("prompt heads changed (date rollover); refreshing prefix cache")
        app.scheduler.retire_prefixes()
        app._registered_heads = set()
    # (re)try only the missing heads; registration is idempotent and cheap
    # on failure, so a persistently failing head retries without churning
    # the successfully registered one
    for head in heads:
        if head in app._registered_heads:
            continue
        if await app.scheduler.register_prefix_async(_encode_head(tokenizer, head)) > 0:
            app._registered_heads.add(head)


async def _prefix_refresh_loop(app: "App") -> None:
    """Periodic freshness checker for the shared-prefix cache. The check
    itself is a few rendered-string comparisons; actual re-registration
    happens at most once a day (date rollover) and runs chunked through
    the scheduler loop. With a fleet, every LIVE replica is checked —
    registration is per device state."""
    while app._running:
        try:
            for target in app._prefix_targets():
                await _maybe_refresh_prefix_cache(target)
        except Exception as e:  # best-effort: the cache is an optimization
            logger.error("prefix cache refresh error: %s", e)
        await asyncio.sleep(app._prefix_refresh_check_s)


class _ReplicaPrefixView:
    """Adapter giving ``_maybe_refresh_prefix_cache`` a per-replica
    target: the single-engine App attribute surface, with the registered
    head set stored ON the replica (shared-head prefill lives in that
    replica's device state, so each replica tracks its own)."""

    def __init__(self, app: "App", rep: EngineReplica):
        self._rep = rep
        self._prefix_cache_enabled = app._prefix_cache_enabled
        self.scheduler = rep.scheduler
        self.agent = rep.agent

    @property
    def _registered_heads(self) -> set:
        return self._rep.registered_heads

    @_registered_heads.setter
    def _registered_heads(self, value: set) -> None:
        self._rep.registered_heads = set(value)


def _make_rebuild_hook(rep: EngineReplica):
    """on_rebuild callback for one fleet replica: the rebuild dropped that
    replica's prefilled heads, so mark them unregistered there (the
    refresh loop re-registers through the chunked path). Keyed so App.start
    can keep the hook idempotent across restarts."""

    def hook() -> None:
        rep.registered_heads.clear()

    hook.key = ("fleet_heads", rep.replica_id)
    return hook


def _load_model_artifacts(cfg: AppConfig) -> tuple:
    """Load everything the engine replicas SHARE — (model config, params,
    tokenizer, mesh). The params tree is immutable jax arrays, so a fleet
    of N replicas costs N KV pools and schedulers, not N copies of the
    weights."""
    config = PRESETS[cfg.model.preset]
    if cfg.model.dtype:
        import dataclasses

        import jax.numpy as jnp

        config = dataclasses.replace(config, dtype=getattr(jnp, cfg.model.dtype))
    tokenizer = get_tokenizer(cfg.model.tokenizer_path)
    if cfg.model.checkpoint_path:
        from finchat_tpu.checkpoints.hf_loader import load_llama_params

        # quantize per-tensor AT LOAD so the full bf16 tree never has to
        # fit in HBM (8B int8/int4 on one 16 GB chip); the engine's own
        # quantize pass is idempotent on the already-quantized leaves
        params = load_llama_params(cfg.model.checkpoint_path, config,
                                   quant=cfg.model.quant,
                                   quant_group=cfg.model.quant_group)
    else:
        logger.warning("no checkpoint configured; using RANDOM weights (preset=%s)", cfg.model.preset)
        if cfg.model.quant:
            from finchat_tpu.models.quant import init_quantized_llama_params

            params = init_quantized_llama_params(
                config, jax.random.key(cfg.model.seed),
                mode=cfg.model.quant, group_size=cfg.model.quant_group,
            )
        else:
            params = init_params(config, jax.random.key(cfg.model.seed))
    from finchat_tpu.parallel.mesh import MeshSpec, build_mesh

    spec = MeshSpec.from_config(cfg.mesh)
    sizes = (spec.data, spec.pipe, spec.seq, spec.expert, spec.model)
    fixed = 1
    for s in sizes:
        if s != -1:
            fixed *= s
    # -1 axes absorb all devices; a fully fixed mesh uses exactly its own
    # product (so e.g. an explicit all-1 config opts out of parallelism even
    # on a multi-chip host, and a 4-chip mesh config works on an 8-chip host)
    n_mesh = jax.device_count() if -1 in sizes else fixed
    mesh = build_mesh(spec, devices=jax.devices()[:n_mesh]) if n_mesh > 1 else None
    return config, params, tokenizer, mesh


def make_engine_replica(
    cfg: AppConfig, artifacts: tuple, replica_id: str | None = None,
    fabric=None,
) -> tuple[EngineGenerator, ContinuousBatchingScheduler]:
    """One engine replica over the shared artifacts: its own KV page pool
    (InferenceEngine device state), scheduler, and session cache. A
    ``replica_id`` routes the scheduler's metrics through a labeled view
    (every metric family per replica) and stamps its fault-injection
    sites. ``fabric`` (engine/warm_fabric.py — ISSUE 17) makes the
    replica's session tier the fleet-shared one and lets its shared
    prompt heads restore from / publish to the cluster-wide store."""
    config, params, tokenizer, mesh = artifacts
    metrics = METRICS.labeled(replica=replica_id) if replica_id is not None else None
    engine = InferenceEngine(config, params, cfg.engine, mesh=mesh,
                             quant=cfg.model.quant,
                             quant_group=cfg.model.quant_group)
    if cfg.engine.warmup_on_start:
        engine.warmup()
    scheduler = ContinuousBatchingScheduler(
        engine, eos_id=tokenizer.eos_id, metrics=metrics,
        replica_id=replica_id, fabric=fabric,
    )
    return EngineGenerator(scheduler, tokenizer), scheduler


def make_warm_fabric(cfg: AppConfig):
    """The process's warm-state fabric per config, or None. Best-effort:
    an unusable path logs and serves without the fabric rather than
    failing assembly (the per-replica PR 7 layout still applies)."""
    if not (cfg.fabric.enabled and cfg.fabric.path):
        if cfg.fabric.enabled:
            logger.warning("fabric.enabled is set but fabric.path is empty; "
                           "warm-state fabric stays off")
        return None
    from finchat_tpu.engine.warm_fabric import WarmFabric

    try:
        return WarmFabric(cfg.fabric.path, cfg.engine.session_cache_disk_bytes,
                          kv_quant=cfg.engine.kv_quant)
    except Exception as e:
        logger.error("warm-state fabric unavailable at %s: %s",
                     cfg.fabric.path, e)
        return None


def build_generators(cfg: AppConfig, fabric=None) -> tuple[TextGenerator, TextGenerator, ContinuousBatchingScheduler | None, object]:
    """Construct (tool_generator, response_generator, scheduler, tokenizer).

    ``model.preset == "stub"`` wires canned generators (dev/no-TPU); anything
    else builds the TPU engine with one shared continuous-batching scheduler
    serving both agent roles.
    """
    if cfg.model.preset == "stub":
        stub = StubGenerator(default="I'm Penny, here to help with your finances.")
        return stub, stub, None, get_tokenizer()
    artifacts = _load_model_artifacts(cfg)
    generator, scheduler = make_engine_replica(cfg, artifacts, fabric=fabric)
    return generator, generator, scheduler, artifacts[2]


class App:
    """One worker process: HTTP surface + Kafka consume loop + engine."""

    def __init__(self, cfg: AppConfig, *, agent: LLMAgent, store: ConversationStore,
                 kafka: KafkaClient, scheduler: ContinuousBatchingScheduler | None = None,
                 retriever: TransactionRetriever | None = None,
                 fleet: EngineFleet | None = None):
        self.cfg = cfg
        self.agent = agent
        self.store = store
        self.kafka = kafka
        self.scheduler = scheduler
        # engine fleet (serve/fleet.py; ISSUE 6): when set, every chat path
        # routes its conversation to a replica via _agent_for — ``agent``/
        # ``scheduler`` remain replica 0's for the single-engine surface
        # (tests, dev) and are managed THROUGH the fleet lifecycle
        self.fleet = fleet
        self.retriever = retriever
        self.server = HTTPServer(cfg.serve.host, cfg.serve.port)
        self.server.route("GET", "/health", self.health)
        self.server.route("GET", "/metrics", self.metrics)
        # end-to-end request tracing (utils/tracing.py — ISSUE 12): one
        # request's correlated Kafka-ingress→dispatch timeline as Chrome
        # trace-event JSON (open in Perfetto)
        self.server.route_prefix("GET", "/debug/trace/", self.debug_trace)
        self.server.route("POST", "/chat", self.chat)
        self.server.route("POST", "/chat/stream", self.chat_stream)
        self.server.route("POST", "/transactions", self.upsert_transactions)
        self._consume_task: asyncio.Task | None = None
        self._running = False
        # Kafka-driven concurrency: one task per in-flight message so many
        # conversations batch onto the engine together, with a per-
        # conversation ordering chain (same conversation stays serial —
        # the guarantee the reference gets from partition keying + serial
        # processing, main.py:96/138)
        self._inflight: set[asyncio.Task] = set()
        self._conv_tails: dict[str, asyncio.Task] = {}
        # shared-prefix cache freshness: the registered heads embed today's
        # date, so they go stale at midnight — _maybe_refresh_prefix_cache
        # compares and re-registers on the request paths. build_app fills
        # _registered_heads with what actually registered.
        self._prefix_cache_enabled = cfg.engine.prefix_cache and scheduler is not None
        self._registered_heads: set[str] = set()
        self._prefix_refresh_check_s = 60.0
        self._prefix_refresh_task: asyncio.Task | None = None
        # at-least-once bookkeeping (kafka.commit_after_process): offsets
        # commit only at the CONTIGUOUS-completion watermark per partition
        # — committing a bare message offset would implicitly commit every
        # earlier message still in flight on that partition — plus a
        # bounded message_id dedupe ring so redelivery after a crash
        # doesn't double-answer a conversation
        self._commit_enabled = cfg.kafka.commit_after_process
        self._done_offsets: dict[tuple[str, int], set[int]] = {}
        self._commit_next: dict[tuple[str, int], int] = {}
        # answered-message_id dedupe lives at the ROUTER level (the fleet's
        # ring when one exists): a replica crash plus Kafka redelivery to a
        # sibling replica consults the same ring the original answer was
        # recorded in, so it cannot double-answer (ISSUE 6 satellite —
        # closes the per-replica hole PR 5 documented)
        # ring size's single source of truth is the DedupeRing default,
        # so the fleet's shared ring and this one can never drift
        self._dedupe = fleet.dedupe if fleet is not None else DedupeRing()
        # answered-message journal (io/journal.py — ISSUE 7): answered ids
        # fsync to disk BEFORE their Kafka offset commits, and a restart
        # replays them into the ring, so crash + redelivery cannot
        # double-answer. Failed ids are never journaled (see _done).
        self._journal = None
        if cfg.journal.path:
            from finchat_tpu.io.journal import AnsweredJournal

            try:
                self._journal = AnsweredJournal(
                    cfg.journal.path, fsync=cfg.journal.fsync,
                    keep=self._dedupe.size,
                    num_partitions=getattr(kafka, "num_partitions", 1),
                )
                self._dedupe.preload(self._journal.replay())
            except Exception as e:  # durability is best-effort
                logger.error("answered journal unavailable at %s: %s",
                             cfg.journal.path, e)
                self._journal = None
        # pod plane (serve/pod.py — ISSUE 20): with pod.host_id set, this
        # process is one HOST of a multi-host pod — liaison heartbeats to
        # the peer table, partition adoption (with per-partition journal
        # replay into the shared dedupe ring) on a peer's death, and
        # cross-host session pulls before admission. Off = bit-identical
        # to the plain fleet.
        self.pod = None
        if cfg.pod.host_id:
            from finchat_tpu.serve.pod import PodCoordinator

            try:
                self.pod = PodCoordinator(
                    cfg.pod, fleet=fleet, kafka=kafka,
                    journal=self._journal, dedupe=self._dedupe,
                )
                for sched in self._all_schedulers():
                    sched.pod = self.pod
            except Exception as e:  # the pod plane is best-effort too
                logger.error("pod plane unavailable: %s", e)
                self.pod = None
        # graceful SIGTERM drain (ISSUE 7): set while drain_and_stop runs
        # so the HTTP chat paths stop admitting with a retryable 503
        self._draining = False

    # --- lifespan -------------------------------------------------------
    def _embed_batcher(self):
        """The embedding microbatcher, wherever it is wired: the app's own
        ingestion retriever or the agent's (they are the same object on
        the default on-device path)."""
        return getattr(self.retriever, "batcher", None) or getattr(
            self.agent.retriever, "batcher", None
        )

    async def start(self, serve_http: bool = True) -> None:
        await self.store.check_connection()
        batcher = self._embed_batcher()
        if batcher is not None:
            # bind the coalescing flusher to the serving loop so the
            # threadsafe ingest path can ride the same window as queries
            batcher.bind_loop()
        topics = [USER_MESSAGE_TOPIC]
        if self.retriever is not None:
            topics.append(TRANSACTION_UPSERT_TOPIC)
        self.kafka.setup_consumer(topics=topics)
        if self.fleet is not None:
            # per-replica head bookkeeping: a rebuild drops that replica's
            # prefilled heads only; the refresh loop re-registers them
            # per replica, and a supervisor respawn re-registers eagerly
            for rep in self.fleet.replicas:
                hook = _make_rebuild_hook(rep)
                if hook.key not in {getattr(cb, "key", None)
                                    for cb in rep.scheduler.on_rebuild}:
                    rep.scheduler.on_rebuild.append(hook)
            if self._respawn_heads not in self.fleet.on_respawn:
                self.fleet.on_respawn.append(self._respawn_heads)
            await self.fleet.start()
        elif self.scheduler is not None:
            if self._on_engine_rebuild not in self.scheduler.on_rebuild:
                self.scheduler.on_rebuild.append(self._on_engine_rebuild)
            await self.scheduler.start()
        if self.pod is not None:
            # after setup_consumer: the coordinator snapshots this host's
            # REAL partition assignment as its adoption baseline
            await self.pod.start()
        self._running = True
        self._consume_task = asyncio.create_task(self.consume_messages())
        if self._prefix_cache_enabled:
            self._prefix_refresh_task = asyncio.create_task(_prefix_refresh_loop(self))
        if serve_http:
            await self.server.start()

    async def stop(self) -> None:
        self._running = False
        if self._prefix_refresh_task:
            self._prefix_refresh_task.cancel()
            try:
                await self._prefix_refresh_task
            except asyncio.CancelledError:
                pass
        if self._consume_task:
            self._consume_task.cancel()
            try:
                await self._consume_task
            except asyncio.CancelledError:
                pass
        for task in list(self._inflight):  # in-flight conversations
            task.cancel()
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        batcher = self._embed_batcher()
        if batcher is not None:
            await batcher.close()
        if self.pod is not None:
            await self.pod.stop()
        if self.fleet is not None:
            await self.fleet.stop()
        elif self.scheduler is not None:
            await self.scheduler.stop()
        self._persist_index(force=True)
        await self.server.stop()
        self.kafka.close()
        if self._journal is not None:
            self._journal.close()

    def _all_schedulers(self) -> list:
        if self.fleet is not None:
            return [rep.scheduler for rep in self.fleet.replicas]
        return [self.scheduler] if self.scheduler is not None else []

    async def drain_and_stop(self) -> None:
        """Graceful SIGTERM shutdown (ISSUE 7; ROBUSTNESS.md §5): stop
        admission (Kafka polling halts, HTTP chat returns a retryable
        503), let in-flight streams COMPLETE within
        ``shutdown.deadline_seconds`` (their answers journal and their
        offsets commit exactly as in steady state), then preempt the
        stragglers to host — each one's coherent KV spills through the
        session disk tier and its client gets a retryable
        ``shutting_down`` error — spill every session entry, and exit
        with zero slot/page leaks. The restarted process replays the
        journal, rewinds to the committed watermark, and resumes
        conversations warm from the disk tier."""
        t0 = time.perf_counter()
        METRICS.inc("finchat_durability_graceful_drains_total")
        # black box of the shutdown itself (ISSUE 12): what was in flight
        # when SIGTERM landed; flushed to disk before the process exits
        TRACER.anomaly("sigterm_drain",
                       args={"inflight": len(self._inflight)})
        self._draining = True
        self._running = False
        if self._consume_task:
            self._consume_task.cancel()
            try:
                await self._consume_task
            except asyncio.CancelledError:
                pass
            self._consume_task = None
        deadline = max(0.0, self.cfg.shutdown.deadline_seconds)
        if self._inflight:
            _done, stragglers = await asyncio.wait(
                set(self._inflight), timeout=deadline
            )
            if stragglers:
                logger.warning(
                    "graceful drain: %d in-flight message(s) past the "
                    "%.1fs deadline; preempting to host", len(stragglers),
                    deadline,
                )
        # the fleet supervisor must be down before the per-replica drain:
        # a respawn's device rebuild (revive_async) racing shutdown_drain
        # on the same engine could corrupt allocator/slot state and defeat
        # the zero-leak exit (fleet.stop later is an idempotent no-op for
        # the already-cleared tasks)
        if self.fleet is not None:
            await self.fleet.stop_supervisor()
        # stragglers' engine handles fail with the retryable shutting_down
        # error and their coherent KV spills to the session tier; the loop
        # stops first, so no dispatch races the offload
        for sched in self._all_schedulers():
            try:
                await sched.shutdown_drain()
            except Exception as e:
                logger.error("scheduler shutdown drain failed: %s", e)
        # the straggler tasks observe the error events, emit their
        # retryable error chunks, and complete — committing their offsets
        if self._inflight:
            await asyncio.gather(*self._inflight, return_exceptions=True)
        METRICS.observe(
            "finchat_durability_shutdown_drain_seconds",
            time.perf_counter() - t0,
        )
        # the flight dumps write in worker threads; join them (off-loop)
        # so the black box is on disk before the process exits
        await asyncio.to_thread(TRACER.flush_dumps)
        await self.stop()

    # snapshots are full rewrites (np.savez over the whole collection), so
    # debounce streaming-ingest saves; shutdown always forces one
    _PERSIST_DEBOUNCE_S = 30.0

    def _persist_index(self, force: bool = False) -> None:
        base = self.cfg.vector.snapshot_base()
        if not base or getattr(self.retriever, "index", None) is None:
            return  # no local index (none, or external Qdrant backend)
        import time as _time

        now = _time.monotonic()
        if not force and now - getattr(self, "_last_persist", 0.0) < self._PERSIST_DEBOUNCE_S:
            self._persist_dirty = True
            return
        try:
            self.retriever.index.save(base)
            self._last_persist = now
            self._persist_dirty = False
        except Exception as e:
            logger.error("failed to persist vector index: %s", e)

    def _on_engine_rebuild(self) -> None:
        """Scheduler breaker trip rebuilt the engine's device state: the
        shared prompt heads' prefilled KV is gone with it. Mark them
        unregistered so the periodic prefix-refresh loop re-registers them
        through the chunked path — recovery itself never stalls on a
        multi-second head prefill."""
        self._registered_heads = set()

    # --- fleet routing (serve/fleet.py; ISSUE 6) ------------------------
    def _agent_for(self, conversation_id: str) -> LLMAgent:
        """The agent serving this conversation: the fleet's
        conversation-affinity route (which also migrates the session-cache
        bytes home) with a fleet, the single agent otherwise."""
        if self.fleet is not None:
            return self.fleet.agent_for(conversation_id)
        return self.agent

    def _prefix_targets(self) -> list:
        """Per-scheduler shared-prefix refresh targets (one per LIVE
        replica with a fleet; the app itself single-engine)."""
        if self.fleet is not None:
            return [_ReplicaPrefixView(self, rep) for rep in self.fleet.replicas
                    if rep.state == LIVE and rep.agent is not None]
        return [self]

    async def _respawn_heads(self, rep: EngineReplica) -> None:
        """fleet.on_respawn hook: re-register the shared prompt heads on a
        just-revived replica EAGERLY (the periodic refresh would leave it
        serving head-cold for up to a refresh interval)."""
        if self._prefix_cache_enabled and rep.agent is not None:
            rep.registered_heads = set()
            await _maybe_refresh_prefix_cache(_ReplicaPrefixView(self, rep))

    def _request_deadline(self, wall_anchor_s: float | None = None) -> float | None:
        """Per-request deadline on the scheduler's monotonic clock, or
        None when ``engine.request_deadline_seconds`` is unset. Anchored at
        the Kafka message's producer timestamp when given (broker queueing
        time counts against the allowance, exactly as the waiting client
        experiences it) or at arrival for the HTTP paths."""
        allowance = self.cfg.engine.request_deadline_seconds
        if allowance <= 0:
            return None
        now_wall = time.time()
        anchor = now_wall if wall_anchor_s is None else wall_anchor_s
        return time.perf_counter() + (anchor - now_wall) + allowance

    @staticmethod
    def _message_wall_ts(message) -> float | None:
        """Producer wall-clock seconds from a Kafka message, if stamped."""
        try:
            ts_type, ts_ms = message.timestamp()
        except Exception:
            return None
        if ts_type == 0 or ts_ms is None or ts_ms <= 0:
            return None
        return ts_ms / 1000.0

    # --- at-least-once commit plumbing (kafka.commit_after_process) ------
    # (dedupe ring size lives on serve/fleet.py DedupeRing — one default
    # for the single-engine ring and the fleet-shared ring alike)

    def _note_message_polled(self, msg) -> None:
        """Anchor the partition's commit watermark at the FIRST polled
        offset (poll order is offset order per partition)."""
        if not self._commit_enabled or msg.offset() < 0:
            return
        self._commit_next.setdefault((msg.topic(), msg.partition()), msg.offset())

    def _note_message_done(self, msg) -> None:
        """A message's watchdog-wrapped handling completed (answered,
        errored, timed out, or deduped — all terminal): advance the
        partition's contiguous-completion watermark and commit it."""
        if not self._commit_enabled or msg.offset() < 0:
            return
        tp = (msg.topic(), msg.partition())
        done = self._done_offsets.setdefault(tp, set())
        done.add(msg.offset())
        nxt = self._commit_next.setdefault(tp, msg.offset())
        advanced = False
        while nxt in done:
            done.discard(nxt)
            nxt += 1
            advanced = True
        if advanced:
            self._commit_next[tp] = nxt
            try:
                self.kafka.commit_offset(tp[0], tp[1], nxt)
            except Exception as e:
                logger.error("offset commit failed for %s: %s", tp, e)

    def _seen_message_id(self, message_id) -> bool:
        """Bounded dedupe ring over inbound ``message_id``s: True when this
        id was already handled this process lifetime (redelivery after a
        crash/rebalance must not double-answer). Shared fleet-wide — see
        serve/fleet.py DedupeRing."""
        return self._dedupe.seen(message_id)

    @property
    def _seen_ids(self) -> set:
        """Introspection view of the dedupe ring's id set (tests)."""
        return self._dedupe._ids

    # --- conversation plumbing ------------------------------------------
    def _payload_error(self, payload: dict) -> Response | None:
        """Shared HTTP validation for the chat endpoints; also the
        admission gate during a graceful drain (new work gets a retryable
        503 while in-flight streams finish)."""
        if self._draining:
            return Response.json(
                {"detail": "server shutting down; retry with backoff",
                 "retryable": True}, status=503,
            )
        missing = [k for k in ("conversation_id", "message", "user_id") if k not in payload]
        if missing:
            return Response.json({"detail": f"missing fields: {missing}"}, status=400)
        return None

    async def _conversation_inputs(
        self, payload: dict, *, payload_user_id: bool = True
    ) -> tuple[str, str, str, list]:
        """THE one place a request's conversation state is assembled —
        every chat path (REST, SSE, Kafka) goes through here, so the
        ``conversation_id`` that keys the engine's session KV cache and the
        context/history fetch can never drift apart. Returns
        ``(conversation_id, user_id, user_context, chat_history)``. The
        HTTP paths take ``user_id`` from the validated payload; the Kafka
        path passes ``payload_user_id=False`` to keep the STORED user id
        authoritative (reference main.py:64-70 — a spoofed message field
        must not re-key whose transactions are retrieved)."""
        conversation_id = payload["conversation_id"]
        user_context, stored_user_id = await self.store.get_context(conversation_id)
        chat_history = await self.store.get_history(conversation_id)
        user_id = stored_user_id
        if payload_user_id and "user_id" in payload:
            user_id = payload["user_id"]
        return conversation_id, user_id, user_context, chat_history

    # --- tracing (utils/tracing.py — ISSUE 12) --------------------------
    @staticmethod
    def _kafka_trace_id(message_value: dict | None) -> str | None:
        """The trace id a Kafka message carries BY ITSELF: its
        ``message_id`` (the same id the answered journal and dedupe ring
        key on). None when the producer stamped no id — the handler then
        mints one, which correlation-at-the-watchdog can't recover (the
        watchdog only holds the raw message)."""
        if message_value is None:
            return None
        mid = message_value.get("message_id")
        return str(mid) if mid is not None else None

    @staticmethod
    def _http_trace_id(request: Request) -> str:
        """HTTP ingress trace id: the client's ``x-trace-id`` header when
        given (so an upstream gateway's id correlates end-to-end), else
        minted here."""
        return request.headers.get("x-trace-id") or uuid.uuid4().hex[:16]

    @staticmethod
    def _trace_ingress(trace_id: str, source: str, conversation_id: str) -> None:
        if TRACER.enabled:
            TRACER.event("ingress", trace_id, track="ingress",
                         args={"source": source,
                               "conversation_id": conversation_id})

    # --- HTTP handlers --------------------------------------------------
    async def health(self, request: Request) -> Response:
        return Response.json({"status": "healthy"})

    async def metrics(self, request: Request) -> Response:
        return Response.text(METRICS.render_prometheus(), content_type="text/plain; version=0.0.4")

    async def debug_trace(self, request: Request) -> Response:
        """``GET /debug/trace/<trace_id>`` → Chrome trace-event JSON of
        that request's correlated timeline (ingress, agent decide, tool
        launch/adopt, prefill, every dispatch that carried its rows,
        first token, done). Open the body in Perfetto / chrome://tracing."""
        trace_id = request.path.rsplit("/", 1)[-1]
        if not trace_id:
            return Response.json({"detail": "missing trace id"}, status=400)
        export = TRACER.export(trace_id)
        if not export["traceEvents"]:
            return Response.json(
                {"detail": f"no events for trace_id {trace_id!r} "
                           "(expired from the ring, or never traced)"},
                status=404,
            )
        return Response.json(export)

    async def chat(self, request: Request) -> Response:
        """Batch REST path (the reference's commented POST /process_message,
        main.py:44-49): runs the compiled agent graph."""
        payload = request.json()
        err = self._payload_error(payload)
        if err is not None:
            return err
        conversation_id, user_id, user_context, chat_history = (
            await self._conversation_inputs(payload)
        )
        trace_id = self._http_trace_id(request)
        self._trace_ingress(trace_id, "http:/chat", conversation_id)
        try:
            agent = self._agent_for(conversation_id)
        except RuntimeError:
            # whole fleet out: same retryable signal the Kafka path emits
            return Response.json(
                {"detail": "no live engine replica; retry with backoff",
                 "retryable": True}, status=503,
            )
        result = await agent.query(
            payload["message"], user_id, user_context, chat_history,
            conversation_id=conversation_id,
            deadline=self._request_deadline(),
            trace_id=trace_id,
        )
        body = {
            "response": result["response"],
            "retrieved_transactions_count": result["retrieved_transactions_count"],
        }
        if result.get("plot_data_uri"):
            body["plot_data_uri"] = result["plot_data_uri"]
        return Response.json(body)

    async def chat_stream(self, request: Request) -> Response | StreamingResponse:
        """SSE stream of the full internal event protocol."""
        payload = request.json()
        err = self._payload_error(payload)
        if err is not None:
            return err
        conversation_id, user_id, user_context, chat_history = (
            await self._conversation_inputs(payload)
        )

        deadline = self._request_deadline()
        trace_id = self._http_trace_id(request)
        self._trace_ingress(trace_id, "http:/chat/stream", conversation_id)
        try:
            agent = self._agent_for(conversation_id)
        except RuntimeError:
            return Response.json(
                {"detail": "no live engine replica; retry with backoff",
                 "retryable": True}, status=503,
            )

        async def events():
            updates = agent.stream_with_status(
                payload["message"], user_id, user_context, chat_history,
                conversation_id=conversation_id, deadline=deadline,
                trace_id=trace_id,
            )
            # decode_loop AND free-run bursts re-pace through the SAME
            # per-chunk emit — clients see a smooth token cadence, not
            # K-frame stutters. A captured multi-round dispatch can drain
            # up to freerun_rounds x loop_depth tokens at once, but only
            # during coexist windows — loop_depth seeds the pacer's
            # steady-state width and the product bounds the observed-width
            # EMA (see _repace_bursts).
            cap = (max(1, self.cfg.engine.decode_loop_depth)
                   * max(1, self.cfg.engine.freerun_rounds))
            async for update in _repace_bursts(
                    updates, self.cfg.engine.decode_loop_depth, burst_cap=cap):
                yield sse_event(update)

        return StreamingResponse(chunks=events())

    async def upsert_transactions(self, request: Request) -> Response:
        """Ingestion endpoint: embed rows on-device and upsert them into the
        vector index (the reference's out-of-band Qdrant pipeline made
        first-class). Body: {"user_id": ..., "transactions":
        [{"text": ..., "date"?: unix-ts, ...metadata}]}."""
        if self.retriever is None:
            return Response.json({"detail": "no retriever configured"}, status=503)
        payload = request.json()
        missing = [k for k in ("user_id", "transactions") if k not in payload]
        if missing:
            return Response.json({"detail": f"missing fields: {missing}"}, status=400)
        rows = payload["transactions"]
        if not isinstance(rows, list) or not all(
            isinstance(r, dict) and r.get("text") for r in rows
        ):
            return Response.json(
                {"detail": "transactions must be [{text, date?, ...metadata}]"}, status=400
            )
        try:
            count = await asyncio.to_thread(
                self._ingest_rows, str(payload["user_id"]), rows
            )
        except (TypeError, ValueError) as e:
            return Response.json({"detail": f"bad transaction row: {e}"}, status=400)
        return Response.json({"upserted": count})

    def _ingest_rows(self, user_id: str, rows: list[dict]) -> int:
        """Embed + upsert (blocking: device matmuls); callers thread it off
        the loop. Rows without a ``date`` are stamped individually with now
        (a malformed date raises ValueError → 400 at the handler)."""
        texts = [str(r["text"]) for r in rows]
        now = self.retriever.now()
        dates = [float(r["date"]) if "date" in r else now for r in rows]
        metadatas = [
            {k: v for k, v in r.items() if k not in ("text", "date")} for r in rows
        ]
        self.retriever.upsert_transactions(user_id, texts, dates=dates, metadatas=metadatas)
        self._persist_index()
        return len(texts)

    # --- Kafka worker loop ----------------------------------------------
    async def process_message(self, message, message_value: dict | None = None) -> bool:
        """Handle one user message end-to-end. Returns True only when the
        client was ANSWERED (stream completed); False for drops, errors,
        and sheds — the dedupe ring keeps only answered message_ids, so a
        producer retrying a failed/shed message (as the retryable error
        chunk invites) is reprocessed, never black-holed."""
        if message_value is None:
            message_value = json.loads(message.value().decode("utf-8"))
        msg = message_value["message"]
        conversation_id = message_value["conversation_id"]
        full_message = ""
        logger.info("Received message from Kafka: |%s| %s", conversation_id, msg)

        try:
            conversation_id, user_id, context, chat_history = (
                await self._conversation_inputs(message_value, payload_user_id=False)
            )
        except Exception as e:
            logger.error("Error retrieving context or history for conversation %s: %s", conversation_id, e)
            return False

        # stream_flush_tokens > 1 coalesces N model chunks into one outbound
        # Kafka produce — fewer, larger messages for high-throughput topics
        # (1 = reference behavior: one produce per chunk, main.py:86-96)
        flush_every = max(1, self.cfg.engine.stream_flush_tokens)
        pending_chunks: list[str] = []

        def flush_pending() -> None:
            if pending_chunks:
                text = "".join(pending_chunks)
                pending_chunks.clear()
                self.kafka.produce_message(
                    AI_RESPONSE_TOPIC, conversation_id, response_chunk(message_value, text)
                )
                logger.debug("Processed chunk: %s", text)

        try:
            agent = self._agent_for(conversation_id)
        except RuntimeError as e:
            # whole fleet out: the client gets a retryable error instead of
            # a silent drop (the dedupe ring forgets the id — see _done)
            logger.error("no replica for conversation %s: %s", conversation_id, e)
            self.kafka.produce_error_message(
                AI_RESPONSE_TOPIC, conversation_id,
                error_chunk(message_value, code="overloaded", retryable=True),
            )
            return False

        # trace id minted at ingress (ISSUE 12): the Kafka message_id when
        # the producer stamped one — the SAME id the journal/dedupe plane
        # keys on, so a postmortem can pivot between the answered journal
        # and the timeline — else minted here
        trace_id = self._kafka_trace_id(message_value) or uuid.uuid4().hex[:16]
        self._trace_ingress(trace_id, f"kafka:{USER_MESSAGE_TOPIC}",
                            conversation_id)
        # deadline anchored at the PRODUCER timestamp: broker queueing time
        # counts against the allowance, so a message that sat through a
        # backlog sheds (structured retryable error) instead of burning
        # prefill compute on an answer its client gave up on
        updates = agent.stream_with_status(
            msg, user_id, context, chat_history, conversation_id=conversation_id,
            deadline=self._request_deadline(self._message_wall_ts(message)),
            trace_id=trace_id,
        )
        try:
            async for update in updates:
                if update["type"] == "response_chunk":
                    chunk_text = update["content"]
                    full_message += chunk_text
                    pending_chunks.append(chunk_text)
                    if len(pending_chunks) >= flush_every:
                        flush_pending()
                elif update["type"] == "plot":
                    # NEW capability (additive chunk type; schemas.plot_chunk)
                    self.kafka.produce_message(
                        AI_RESPONSE_TOPIC, conversation_id, plot_chunk(message_value, update["data_uri"])
                    )
                elif update["type"] == "complete":
                    flush_pending()  # never reorder text after the marker
                    self.kafka.produce_message(
                        AI_RESPONSE_TOPIC, conversation_id, complete_chunk(message_value)
                    )
                    logger.info("Complete message sent to Kafka for conversation %s", conversation_id)
                # status / retrieval_complete events are intentionally NOT
                # forwarded (main.py:81-110 forwards only response_chunk +
                # complete; plot is the one additive extension)
        except Exception as e:
            logger.error("Error streaming LLM response: %s", e)
            # best-effort: text the client was owed goes out before the
            # error marker (at flush=1 this is reference behavior exactly)
            try:
                flush_pending()
            except Exception:
                pass
            # structured failures (deadline shed, overload) carry their
            # code + retryable flag so the producer can back off and
            # retry; ordinary errors keep the reference's exact shape
            self.kafka.produce_error_message(
                AI_RESPONSE_TOPIC, conversation_id,
                error_chunk(
                    message_value,
                    code=getattr(e, "code", None),
                    retryable=True if getattr(e, "retryable", False) else None,
                ),
            )
            return False
        finally:
            # guarantee generator finalization: the engine handle's
            # slot/KV release lives in the generator's finally, which a
            # consumer cancelled OUTSIDE __anext__ (watchdog timeout)
            # would otherwise leave to the GC
            await updates.aclose()

        try:
            await self.store.save_ai_message(conversation_id=conversation_id, message=full_message, user_id=user_id)
            logger.info("Message saved to DB for conversation %s", conversation_id)
        except Exception as e:
            logger.error("Error saving AI message to DB: %s", e)
        return True

    async def process_upsert(self, message) -> None:
        """transaction_upsert topic: same body as POST /transactions."""
        payload = json.loads(message.value().decode("utf-8"))
        rows = payload.get("transactions") or []
        user_id = str(payload.get("user_id", ""))
        if not user_id or not all(isinstance(r, dict) and r.get("text") for r in rows):
            logger.error("malformed transaction_upsert message; dropped")
            return
        count = await asyncio.to_thread(self._ingest_rows, user_id, rows)
        logger.info("ingested %d transactions for user %s via Kafka", count, user_id)

    async def _process_with_watchdog(
        self, msg, message_value: dict | None, prev: asyncio.Task | None
    ) -> bool:
        """One in-flight message: wait for the SAME conversation's previous
        message to finish (chunk-ordering guarantee), then run under the
        per-message watchdog (reference main.py:138-153 semantics).
        Returns process_message's answered flag (False on timeout/error)
        — what decides whether the message_id stays in the dedupe ring."""
        if prev is not None:
            try:
                await asyncio.shield(prev)
            except Exception:
                pass  # predecessor's failure was already reported on its stream
        watchdog = self.cfg.engine.watchdog_seconds
        task = asyncio.create_task(self.process_message(msg, message_value))
        try:
            return bool(await asyncio.wait_for(asyncio.shield(task), timeout=watchdog))
        except asyncio.TimeoutError:
            logger.error("Message processing timed out after %s seconds", watchdog)
            # flight recorder (ISSUE 12): the ring at this instant holds
            # the stuck request's dispatch/lifecycle events — exactly what
            # a "why did the watchdog fire" postmortem needs
            TRACER.anomaly(
                "watchdog_timeout", self._kafka_trace_id(message_value),
                args={"watchdog_seconds": watchdog,
                      "conversation_id": (message_value or {}).get(
                          "conversation_id")},
            )
            # cancel the in-flight generation and AWAIT its cleanup — the
            # agent/generator finalizers release the scheduler slot and KV
            # pages — BEFORE emitting the timeout chunk, so a timed-out
            # message can never leak engine capacity (the pre-fix path
            # abandoned the coroutine to wait_for's cancellation and raced
            # the chunk against the release; tests/test_resilience.py pins
            # zero slot/page leakage)
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
            try:
                if message_value is not None:
                    self.kafka.produce_error_message(
                        AI_RESPONSE_TOPIC,
                        message_value["conversation_id"],
                        timeout_chunk(message_value),
                    )
            except Exception as e:
                logger.error("Failed to send timeout error message: %s", e)
            return False
        except asyncio.CancelledError:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
            raise
        except Exception as e:
            logger.error("Error processing message: %s", e)
            return False

    def _spawn_message_task(self, msg) -> None:
        # parse ONCE here; process_message / the timeout path reuse the dict
        try:
            message_value = json.loads(msg.value().decode("utf-8"))
            conv_id = message_value.get("conversation_id", "")
        except Exception:
            message_value = None  # malformed: process_message reports it
            conv_id = ""
        mid = None
        if self._commit_enabled and message_value is not None:
            # redelivery dedupe (at-least-once): a message_id this process
            # already ANSWERED (or holds in flight) is not re-answered —
            # its offset still counts as done so the watermark (and the
            # group) can move past it. Ids whose handling FAILS are
            # removed from the ring in _done below, so a producer retrying
            # a shed/overloaded/timed-out message is reprocessed.
            mid = message_value.get("message_id")
            if mid is not None and self._seen_message_id(mid):
                METRICS.inc("finchat_kafka_dedupe_skips_total")
                logger.warning(
                    "duplicate message_id %s (redelivery); already answered, skipping",
                    mid,
                )
                self._note_message_done(msg)
                return
        prev = self._conv_tails.get(conv_id)
        task = asyncio.create_task(self._process_with_watchdog(msg, message_value, prev))
        self._inflight.add(task)
        if conv_id:
            self._conv_tails[conv_id] = task

        def _done(t: asyncio.Task, conv_id=conv_id, mid=mid) -> None:
            self._inflight.discard(t)
            if conv_id and self._conv_tails.get(conv_id) is t:
                del self._conv_tails[conv_id]
            answered = (
                not t.cancelled() and t.exception() is None and bool(t.result())
            )
            if mid is not None and not answered:
                # never answered: drop the id (set AND ring slot) so a
                # producer retry (the retryable error chunk's invitation)
                # is reprocessed instead of black-holed
                self._dedupe.forget(mid)
            elif mid is not None and self._journal is not None:
                # ANSWERED: journal the id under the message's PARTITION —
                # fsync completes BEFORE the watermark commit below, so a
                # crash between them redelivers the message to a process
                # that already knows it was answered (ISSUE 7; §5), and a
                # host that ADOPTS this partition replays exactly this
                # file into its ring (ISSUE 20; §7)
                self._journal.append(mid, partition=msg.partition())
            # the watchdog-wrapped handler completed (answered, errored, or
            # timed out with the timeout chunk emitted): only now may this
            # offset count toward the committed watermark
            self._note_message_done(msg)

        task.add_done_callback(_done)

    def _max_inflight(self) -> int:
        """Poll-gate bound: a full batch per LIVE replica. OUT/RESPAWNING
        replicas are not capacity — counting them would keep this worker
        claiming messages sized for the whole fleet during an outage,
        load the survivors must absorb instead of the consumer group
        redistributing it. Floored at one batch so a whole-fleet-out
        window still polls (each message gets its structured retryable
        error instead of rotting unclaimed on the partition)."""
        n_replicas = 1
        if self.fleet is not None:
            n_replicas = max(1, len(self.fleet.live_replicas()))
        return max(self.cfg.engine.max_seqs, 1) * n_replicas

    async def consume_messages(self) -> None:
        """Poll Kafka and fan messages out as concurrent tasks — MANY
        conversations in flight batch onto the engine together (the whole
        point of the continuous-batching scheduler; the reference processes
        one message at a time per worker, SURVEY §2.3). Backpressure: stop
        polling while a full batch's worth of messages is already in
        flight, so the broker's consumer group redistributes load instead
        of this worker hoarding it."""
        while self._running:
            try:
                if len(self._inflight) >= self._max_inflight():
                    await asyncio.wait(
                        set(self._inflight), return_when=asyncio.FIRST_COMPLETED
                    )
                    continue
                # poll in a worker thread: the confluent backend's poll
                # blocks up to 100 ms (librdkafka), which would stall every
                # in-flight stream now that polling overlaps processing
                msg = await asyncio.to_thread(self.kafka.poll_message)
                if msg is not None:
                    self._note_message_polled(msg)
                if msg is not None and msg.topic() == TRANSACTION_UPSERT_TOPIC:
                    try:
                        await self.process_upsert(msg)
                    except Exception as e:
                        logger.error("Error ingesting transactions: %s", e)
                    finally:
                        self._note_message_done(msg)
                elif msg is not None:
                    self._spawn_message_task(msg)
                    await asyncio.sleep(0)  # let the new task reach the engine
                else:
                    # deferred snapshot from a debounced ingest save
                    if getattr(self, "_persist_dirty", False):
                        self._persist_index()
                    await asyncio.sleep(0.01)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.error("Error in message consumption: %s", e)
                await asyncio.sleep(1)


def build_app(cfg: AppConfig | None = None, *, store: ConversationStore | None = None,
              kafka: KafkaClient | None = None,
              tool_generator: TextGenerator | None = None,
              response_generator: TextGenerator | None = None,
              retriever=None) -> App:
    """Assemble a worker from config, with injection points for tests/dev."""
    from finchat_tpu.utils.config import load_config

    cfg = cfg or load_config()
    # tracing + flight recorder (utils/tracing.py — ISSUE 12): applied at
    # assembly so every component (scheduler, agent, app ingress) sees one
    # consistently configured process tracer
    TRACER.configure(enabled=cfg.tracing.enabled,
                     ring_events=cfg.tracing.ring_events,
                     flight_dir=cfg.tracing.flight_dir)
    store = store or make_store(cfg.store)
    kafka = kafka or KafkaClient(cfg.kafka)

    scheduler = None
    tokenizer = None
    fleet_replicas: list[EngineReplica] | None = None
    if tool_generator is None or response_generator is None:
        # cluster-wide warm-state fabric (ISSUE 17): one shared session
        # disk tier + head store for every replica built below (a single-
        # engine worker uses it too — restarts and multi-process fleets
        # sharing the path resume each other's conversations warm)
        fabric = make_warm_fabric(cfg) if cfg.model.preset != "stub" else None
        if cfg.fleet.replicas > 1 and cfg.model.preset != "stub":
            # engine fleet (ISSUE 6): N replicas over ONE shared weights
            # tree, each with its own KV pool, scheduler, session cache,
            # and replica-labeled metrics; agents bind per replica below.
            # fleet.roles (ISSUE 17) types each replica into the prefill
            # or serving pool; EngineFleet wires the disagg coordinator.
            from finchat_tpu.serve.disagg import parse_roles

            roles = parse_roles(cfg.fleet.roles, cfg.fleet.replicas)
            artifacts = _load_model_artifacts(cfg)
            tokenizer = artifacts[2]
            fleet_replicas = []
            for i in range(cfg.fleet.replicas):
                gen, sched = make_engine_replica(cfg, artifacts,
                                                 replica_id=str(i),
                                                 fabric=fabric)
                fleet_replicas.append(
                    EngineReplica(replica_id=str(i), scheduler=sched,
                                  generator=gen, role=roles[i])
                )
            scheduler = fleet_replicas[0].scheduler
            tool_generator = tool_generator or fleet_replicas[0].generator
            response_generator = response_generator or fleet_replicas[0].generator
        else:
            tool_gen, resp_gen, scheduler, tokenizer = build_generators(cfg, fabric=fabric)
            tool_generator = tool_generator or tool_gen
            response_generator = response_generator or resp_gen

    if retriever is None:
        from finchat_tpu.embed.batcher import EmbedMicrobatcher
        from finchat_tpu.embed.encoder import EMBED_PRESETS, EmbeddingEncoder, init_bert_params
        from finchat_tpu.embed.index import DeviceVectorIndex

        embed_cfg = EMBED_PRESETS[cfg.embed.preset]
        if cfg.embed.checkpoint_path:
            from finchat_tpu.checkpoints.bert_loader import load_bert_params

            embed_params = load_bert_params(cfg.embed.checkpoint_path, embed_cfg)
        else:
            logger.warning(
                "no embedding checkpoint configured; using RANDOM weights "
                "(preset=%s) — retrieval rankings will be meaningless", cfg.embed.preset,
            )
            embed_params = init_bert_params(embed_cfg, jax.random.key(1))
        if cfg.embed.tokenizer_path:
            embed_tokenizer = get_tokenizer(cfg.embed.tokenizer_path)
        else:
            if cfg.embed.checkpoint_path:
                logger.warning(
                    "embed.checkpoint_path is set but embed.tokenizer_path is "
                    "not; falling back to the LLM/byte tokenizer, whose ids "
                    "will NOT match the BERT vocab — retrieval rankings will "
                    "be meaningless. Set FINCHAT_EMBED_TOKENIZER."
                )
            embed_tokenizer = tokenizer or get_tokenizer()
        encoder = EmbeddingEncoder(
            embed_cfg, embed_params, embed_tokenizer,
            batch_size=cfg.embed.batch_size, quant=cfg.embed.quant,
        )
        if cfg.vector.api_key and not cfg.vector.url:
            logger.warning(
                "QDRANT_API_KEY is set but QDRANT_URL is not; using the "
                "on-device vector index — set QDRANT_URL to select the "
                "external Qdrant backend"
            )
        if cfg.vector.url:
            # deployments with an existing populated Qdrant cluster drop
            # in via QDRANT_URL (reference qdrant_tool.py:24-37); the
            # embeddings still run on-device, only ANN search is external
            from finchat_tpu.tools.qdrant_retriever import QdrantRetriever

            retriever = QdrantRetriever(
                encoder, url=cfg.vector.url, api_key=cfg.vector.api_key,
                collection=cfg.vector.collection,
                default_limit=cfg.vector.default_limit,
            )
        else:
            base = cfg.vector.snapshot_base()
            if base:
                index = DeviceVectorIndex.load(base, dim=embed_cfg.dim)
            else:
                index = DeviceVectorIndex(dim=embed_cfg.dim)
            # the embedding microbatcher coalesces concurrent query embeds
            # and ingest upserts into shared encode_batch dispatches; it
            # binds to the serving event loop at App.start
            batcher = EmbedMicrobatcher(
                encoder, window_ms=cfg.embed.batch_window_ms,
                max_batch=cfg.embed.batch_max,
            )
            retriever = TransactionRetriever(
                encoder, index, default_limit=cfg.vector.default_limit,
                batcher=batcher,
            )

    system_prompt, tool_prompt = load_prompts()

    def make_agent(tool_gen, resp_gen) -> LLMAgent:
        return LLMAgent(
            tool_gen, resp_gen, retriever, system_prompt, tool_prompt,
            response_sampling=SamplingParams(
                temperature=cfg.engine.temperature, top_p=cfg.engine.top_p,
                top_k=cfg.engine.top_k, max_new_tokens=cfg.engine.max_new_tokens,
            ),
            retrieval_overlap=cfg.engine.retrieval_overlap,
            # tool-streaming plane (ISSUE 9): eager tool launch + early
            # prefix hold during the decision decode; the agent derives
            # its finchat_tool_* metrics view from the generator's
            # scheduler, so fleet replicas label the family per replica
            tool_streaming=cfg.engine.tool_streaming,
        )

    agent = make_agent(tool_generator, response_generator)
    fleet = None
    if fleet_replicas is not None:
        # one agent per replica (prompts + retriever shared; each agent's
        # generators are bound to its replica's scheduler); replica 0
        # reuses the agent above so App.agent and the fleet stay one object
        fleet_replicas[0].agent = agent
        for rep in fleet_replicas[1:]:
            rep.agent = make_agent(rep.generator, rep.generator)
        fleet = EngineFleet(fleet_replicas, cfg.fleet,
                            num_partitions=kafka.num_partitions)
    # the App's ingestion endpoints work with any backend exposing
    # upsert_transactions (device index or external Qdrant); snapshot
    # persistence additionally needs a local .index (guarded there)
    app_retriever = retriever if hasattr(retriever, "upsert_transactions") else None
    app = App(cfg, agent=agent, store=store, kafka=kafka, scheduler=scheduler,
              retriever=app_retriever, fleet=fleet)
    if app._prefix_cache_enabled and tokenizer is not None:
        if fleet is not None:
            for rep in fleet.replicas:
                rep.registered_heads = register_prompt_prefixes(
                    rep.agent, rep.scheduler, tokenizer
                )
        else:
            app._registered_heads = register_prompt_prefixes(agent, scheduler, tokenizer)
    return app
