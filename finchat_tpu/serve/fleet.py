"""Engine fleet: N replicas under one serving plane (ISSUE 6; ROBUSTNESS.md).

After PRs 1-5 everything was one scheduler driving one engine: the breaker
could rebuild a wedged engine, but the whole service shed while it rebuilt,
and a persistently dead engine (breaker give-up) took every conversation
down with it. This module is the millions-of-users step (ROADMAP item 2):

- **Replicas**: ``fleet.replicas`` engine replicas, each its own scheduler,
  KV page pool, and session cache, sharing one immutable weights tree (the
  params leaves are read-only jax arrays — N replicas cost N KV pools, not
  N models). Each replica's scheduler observes through a
  ``METRICS.labeled(replica=...)`` view, so every existing metric family
  separates per replica.
- **Conversation-affinity router**: a conversation routes to the replica
  that rendezvous-hashes highest for its KAFKA PARTITION
  (``io/kafka.py partition_for_key`` — the exact hash the broker uses for
  key→partition placement), so session-cache entries and prefix heads stay
  local, same-partition conversations land on the same replica, and
  routing agrees with partition assignment by construction. (CRC32 is
  librdkafka's ``consistent`` partitioner and the memory broker's; Java
  producers default to murmur2 — see the ``partition_for_key`` caveat.
  Misalignment only costs affinity, never correctness.) Rendezvous
  (highest-random-weight) hashing makes membership changes minimal: a
  replica leaving moves ONLY its own partitions (spread over the
  survivors); rejoining moves exactly those back.
- **Drain-on-trip**: a replica's breaker trip no longer sheds — its live
  streams are recompute-preempted to host (prompt + generated tokens on
  the handle, device-free) and offered to the drain sink, which routes
  each to a sibling and hands off the conversation's session-cache host
  bytes (device-independent by construction). The handle's event queue
  travels with it, so the client's stream continues byte-identical from
  the sibling; the tripped replica rebuilds in the background.
- **Give-up → OUT → supervised respawn**: a breaker give-up marks the
  replica OUT (the router drops it; its partitions reassign), drains
  whatever is still live, and the supervisor respawns it in the background
  (``scheduler.revive`` — rebuild device state from a clean slate,
  re-register prompt heads) with exponential backoff while the rest of the
  fleet absorbs the load. On rejoin its partitions route back.
- **Cross-replica session migration**: session-cache entries are host-RAM
  byte snapshots keyed by conversation — exportable without the device.
  Handoffs move them at drain time; ``replica_for`` additionally migrates
  lazily at route time, so a conversation whose bytes ended up on a
  sibling (drain, or a respawned replica re-adopting its partitions) gets
  its resumed-prefill profile back on the very next turn. Entries whose KV
  rode a shared-prefix head re-link against the importer's OWN live
  registration of the same head (every replica registers the same heads).

Single-process by design: the replicas share one asyncio loop (handles and
their event queues cross schedulers freely), matching how one host serves
one TPU pod slice with per-chip/per-slice engines. Multi-HOST fleets stack
this under the existing consumer-group layer (__main__.py), where the same
partition alignment applies across processes.
"""

from __future__ import annotations

import asyncio
import hashlib
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable

from finchat_tpu.engine.session_cache import (
    SESSION_KEY_ROLES,
    conversation_of,
    session_key,
)
from finchat_tpu.io.kafka import DEFAULT_NUM_PARTITIONS, partition_for_key
from finchat_tpu.serve.disagg import (
    FALLBACK_REASONS,
    ROLE_DECODE,
    ROLE_MIXED,
    ROLE_PREFILL,
    DisaggCoordinator,
)
from finchat_tpu.utils.config import FleetConfig
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

# replica lifecycle: LIVE (routed to), OUT (breaker gave up; router skips
# it), RESPAWNING (supervisor is reviving it; still skipped)
LIVE = "live"
OUT = "out"
RESPAWNING = "respawning"


def rendezvous_hash(key: str, candidates: list[str]) -> str | None:
    """Highest-random-weight (rendezvous) choice of ``candidates`` for
    ``key``: every (key, candidate) pair gets a stable pseudo-random
    weight and the max wins. Removing a candidate reassigns ONLY the keys
    it owned (each to its runner-up); adding one back restores exactly
    the old mapping — the ≤ ~1/N reshuffle property the fleet router
    needs across replica loss/join (tests/test_fleet.py pins it)."""
    if not candidates:
        return None
    best, best_w = None, -1
    for cand in candidates:
        w = int.from_bytes(
            hashlib.blake2b(
                f"{key}\x00{cand}".encode(), digest_size=8
            ).digest(),
            "big",
        )
        if w > best_w or (w == best_w and (best is None or cand < best)):
            best, best_w = cand, w
    return best


class DedupeRing:
    """Bounded answered-``message_id`` ring, lifted from the per-replica
    serving loop to the ROUTER level (ISSUE 6 satellite): with one ring
    shared across the fleet, a replica crash plus Kafka redelivery of its
    uncommitted messages to a sibling replica cannot double-answer a
    conversation — the sibling consults the same ring the dead replica's
    answers were recorded in. Across PROCESSES, the answered-message
    journal (io/journal.py; ISSUE 7) replays into this ring at startup via
    ``preload``, closing the crash-redelivery window too."""

    def __init__(self, size: int = 1024):
        self.size = size
        self._ids: set = set()
        self._ring: deque = deque()

    def seen(self, message_id) -> bool:
        """True when ``message_id`` was already recorded (answered or in
        flight); records it otherwise."""
        if message_id in self._ids:
            return True
        self._ids.add(message_id)
        self._ring.append(message_id)
        if len(self._ring) > self.size:
            self._ids.discard(self._ring.popleft())
        return False

    def preload(self, message_ids) -> int:
        """Seed the ring with journaled answered ids at startup (ISSUE 7:
        the answered-message journal replays here, oldest-first, so ring
        recency matches journal recency). Returns how many were new."""
        return sum(1 for mid in message_ids if not self.seen(mid))

    def forget(self, message_id) -> None:
        """Drop an id whose handling FAILED (never answered), so a
        producer retry is reprocessed — including its ring slot, else a
        stale duplicate would age out the re-added answered id early."""
        self._ids.discard(message_id)
        try:
            self._ring.remove(message_id)
        except ValueError:
            pass


@dataclass
class EngineReplica:
    """One engine replica: scheduler + generator (+ per-replica agent once
    the serving layer binds one). ``registered_heads`` tracks which shared
    prompt heads are live on THIS replica's scheduler — registration is
    per device state, so every replica re-registers after its own
    rebuilds."""

    replica_id: str
    scheduler: Any
    generator: Any = None
    agent: Any = None
    state: str = LIVE
    registered_heads: set = field(default_factory=set)
    # pool role (serve/disagg.py — ISSUE 17): ``prefill`` replicas never
    # own conversations (the router hashes over decode+mixed only); they
    # run cold prompts for the serving pool and hand the KV over the
    # drain-handoff wire format. Lifecycle (drain, OUT, respawn) is
    # role-blind — a tripped prefill replica drains to the serving pool
    # like any sibling.
    role: str = ROLE_MIXED


class EngineFleet:
    """The router + drain plumbing + supervisor over a replica list."""

    def __init__(self, replicas: list[EngineReplica], cfg: FleetConfig | None = None,
                 num_partitions: int = DEFAULT_NUM_PARTITIONS, metrics=None):
        assert replicas, "a fleet needs at least one replica"
        self.replicas = list(replicas)
        self.cfg = cfg or FleetConfig()
        self.num_partitions = num_partitions
        if len(self.replicas) > num_partitions:
            # the Kafka partition is THE routing unit: at most one replica
            # per partition can ever be selected, so extras idle (full KV
            # pool, scheduler loop, zero traffic) — raise kafka.num_partitions
            logger.warning(
                "fleet: %d replicas but only %d Kafka partitions — routing "
                "can address at most one replica per partition, the rest "
                "will receive NO traffic; raise kafka.num_partitions",
                len(self.replicas), num_partitions,
            )
        self.metrics = metrics if metrics is not None else METRICS
        # router-level answered-message dedupe (see DedupeRing)
        self.dedupe = DedupeRing()
        self._by_id = {r.replica_id: r for r in self.replicas}
        self._running = False
        self._supervisor_task: asyncio.Task | None = None
        # strong refs to in-flight _respawn tasks: an unreferenced task may
        # be GC'd mid-flight (replica stuck RESPAWNING forever), and stop()
        # must cancel them so a revive can't run against a stopped scheduler
        self._respawn_tasks: set[asyncio.Task] = set()
        # serving-layer hooks run after a replica respawns (e.g. the app
        # re-registers its shared prompt heads on the fresh device state);
        # sync or async callables taking the replica
        self.on_respawn: list[Callable[[EngineReplica], Any]] = []
        for rep in self.replicas:
            self._wire(rep)
        # disaggregated serving (ISSUE 17): with any prefill-role replica,
        # serving-pool schedulers route cold prompt prefills through the
        # coordinator. All-prefill is a misconfiguration that could serve
        # nothing — demote to all-mixed loudly instead.
        self.disagg: DisaggCoordinator | None = None
        if all(r.role == ROLE_PREFILL for r in self.replicas):
            logger.error("fleet: every replica has role=prefill — no "
                         "serving pool; running all replicas mixed")
            for rep in self.replicas:
                rep.role = ROLE_MIXED
        if any(r.role == ROLE_PREFILL for r in self.replicas):
            self.disagg = DisaggCoordinator(self)
            for rep in self.replicas:
                if rep.role != ROLE_PREFILL:
                    rep.scheduler.disagg = self.disagg
        for rep in self.replicas:
            self._seed_disagg_metrics(rep)
        self._publish_live_gauge()

    # --- wiring ---------------------------------------------------------
    def _wire(self, rep: EngineReplica) -> None:
        sched = rep.scheduler
        if self.cfg.drain_on_trip and len(self.replicas) > 1:
            sched.drain_sink = self._make_drain_sink(rep)
        sched.on_give_up.append(lambda rep=rep: self._mark_out(rep))

    def _seed_disagg_metrics(self, rep: EngineReplica) -> None:
        """Per-replica disagg families at zero (R5: the quiet state is
        visible, and the role gauge says which pool a series belongs to).
        Skipped for test stubs without a metrics view."""
        m = getattr(rep.scheduler, "metrics", None)
        if m is None:
            return
        m.set_gauge("finchat_disagg_role",
                    {ROLE_MIXED: 0, ROLE_PREFILL: 1, ROLE_DECODE: 2}[rep.role])
        m.inc("finchat_disagg_handoffs_total", 0.0)
        for reason in FALLBACK_REASONS:
            m.inc("finchat_disagg_fallbacks_total", 0.0,
                  labels={"reason": reason})

    def _publish_live_gauge(self) -> None:
        self.metrics.set_gauge(
            "finchat_fleet_replicas_live",
            sum(1 for r in self.replicas if r.state == LIVE),
        )

    def _mark_out(self, rep: EngineReplica) -> None:
        if rep.state == LIVE:
            logger.error("fleet: replica %s is OUT (breaker give-up); "
                         "reassigning its partitions", rep.replica_id)
            rep.state = OUT
            self._publish_live_gauge()

    # --- routing --------------------------------------------------------
    def live_replicas(self) -> list[EngineReplica]:
        return [r for r in self.replicas if r.state == LIVE]

    def serving_replicas(self) -> list[EngineReplica]:
        """The pool conversations route over: live decode/mixed replicas.
        An empty serving pool (every decode replica drained or tripped)
        falls back to ALL live replicas — a prefill replica serving
        decode beats shedding, and the fallback is counted per message
        on the chosen replica (ISSUE 17 clean-fallback contract)."""
        live = self.live_replicas()
        pool = [r for r in live if r.role != ROLE_PREFILL]
        return pool if pool else live

    def partition_for(self, conversation_id: str) -> int:
        return partition_for_key(conversation_id, self.num_partitions)

    def replica_for_partition(self, partition: int,
                              exclude: EngineReplica | None = None) -> EngineReplica | None:
        """The live serving replica owning a Kafka partition — THE routing
        unit, so every conversation of one partition routes together and
        the assignment is expressible as a partition→replica map."""
        pool = self.serving_replicas()
        ids = [r.replica_id for r in pool if r is not exclude]
        rid = rendezvous_hash(str(partition), ids)
        if rid is None:
            return None
        target = self._by_id[rid]
        if target.role == ROLE_PREFILL:
            # serving-pool-empty fallback engaged: counted on the replica
            # actually absorbing the decode load, per message
            m = getattr(target.scheduler, "metrics", None)
            if m is not None:
                m.inc("finchat_disagg_fallbacks_total",
                      labels={"reason": "serving_pool_empty"})
        return target

    def replica_for(self, conversation_id: str,
                    exclude: EngineReplica | None = None) -> EngineReplica | None:
        """Route a conversation: partition affinity → live replica, with
        lazy cross-replica session migration — if another replica still
        holds this conversation's session-cache bytes (it drained here
        earlier, or this replica just respawned and took its partitions
        back), the entry moves to the routed replica first, so admission
        resumes from it instead of cold-prefilling."""
        target = self.replica_for_partition(
            self.partition_for(conversation_id), exclude=exclude
        )
        if target is None:
            return None
        if len(self.replicas) > 1:
            if any(r is not target and r.state != LIVE for r in self.replicas):
                # affinity owner may be out: count messages routed away
                # from the all-live assignment while a sibling is down
                all_ids = [r.replica_id for r in self.replicas]
                home = rendezvous_hash(str(self.partition_for(conversation_id)), all_ids)
                if home is not None and home != target.replica_id:
                    self.metrics.inc("finchat_fleet_reroutes_total")
            self._migrate_session(conversation_id, target)
        return target

    def agent_for(self, conversation_id: str):
        """The routed replica's agent (serving-layer entry point). Raises
        when no replica is live — the caller surfaces a retryable error."""
        rep = self.replica_for(conversation_id)
        if rep is None or rep.agent is None:
            raise RuntimeError("no live engine replica")
        return rep.agent

    # --- session migration ----------------------------------------------
    def _migrate_session(self, conversation_id: str, target: EngineReplica) -> None:
        """Move a conversation's session-cache bytes to its routed replica
        if a sibling holds (strictly deeper) ones — host-array reference
        moves, no KV copy. The agent keys one entry PER LLM ROLE
        (``conv#tool`` / ``conv#resp``, engine/session_cache.py), so every
        role key is migrated alongside the bare id (direct scheduler
        submissions). Best-effort: a refused import (no matching shared
        head on the target) just means a cold resume."""
        if getattr(target.scheduler, "session_cache", None) is None:
            return
        self._migrate_key(conversation_id, target)
        for role in SESSION_KEY_ROLES:
            self._migrate_key(session_key(conversation_id, role), target)

    def _migrate_key(self, key: str, target: EngineReplica) -> None:
        t_cache = target.scheduler.session_cache
        have = t_cache.get(key)
        have_n = have.n_tokens if have is not None else 0
        fabric = getattr(t_cache, "fabric", None)
        if fabric is not None:
            # warm-state fabric (ISSUE 17): deeper-entry-wins is an O(1)
            # index lookup — the fabric knows which replica's RAM holds
            # the key and how deep. No holder (or only a shallower one)
            # means nothing to move: the SHARED disk tier already serves
            # any replica's record at admission, so the pairwise scan's
            # other job — finding disk-only bytes — is moot by design.
            hold = fabric.holder(key)
            if hold is None:
                return
            rid, n_tokens = hold
            if rid == target.replica_id or n_tokens <= have_n:
                return
            rep = self._by_id.get(rid)
            if rep is not None and rep is not target:
                self._move_entry(rep, target, key)
            return
        for rep in self.replicas:
            if rep is target:
                continue
            s_cache = getattr(rep.scheduler, "session_cache", None)
            if s_cache is None:
                continue
            entry = s_cache.get(key)
            if entry is None or entry.n_tokens <= have_n:
                continue
            if self._move_entry(rep, target, key) is not None:
                return

    def _move_entry(self, rep: EngineReplica, target: EngineReplica,
                    key: str) -> bool | None:
        """Export ``key`` from ``rep``'s RAM cache into ``target``'s
        (the one migration wire format). Returns the import verdict, or
        None when there was nothing to export (the caller may keep
        scanning)."""
        payload = rep.scheduler.export_session(key)
        if payload is None:
            return None
        try:
            imported = target.scheduler.import_session_entry(payload)
        except Exception as e:
            logger.error("session migration %s→%s failed for %s: %s",
                         rep.replica_id, target.replica_id, key, e)
            return False
        s_cache = rep.scheduler.session_cache
        if imported and s_cache.fabric is not None:
            # shared tier: the target's put just refreshed the record —
            # deleting it here would erase the target's own disk twin
            # (both ride the one writer queue); drop only the RAM copy
            s_cache.drop_local(key)
        else:
            # the source copy goes either way: a stale twin left behind
            # could serve diverged KV if routing ever flips back
            s_cache.discard(key)
        if imported:
            self.metrics.inc("finchat_fleet_session_migrations_total")
            if TRACER.enabled:
                TRACER.event("session_migrate", track="fleet",
                             args={"key": key,
                                   "source": rep.replica_id,
                                   "target": target.replica_id})
            logger.info("fleet: migrated session %s %s→%s (%d tokens)",
                        key, rep.replica_id, target.replica_id,
                        payload["token_ids"].shape[0])
        return imported

    # --- drain ----------------------------------------------------------
    def _make_drain_sink(self, source: EngineReplica):
        """The tripped/given-up replica's breaker calls this with each
        preempted handle + its conversation's exported session bytes; a
        sibling adopts both and the stream continues there."""

        def sink(handle, session_payload) -> bool:
            # route by the CONVERSATION, not the per-role cache key the
            # handle carries — the adopter must be the replica the
            # conversation's next turns route to, or the handed-off
            # session bytes strand on a non-affinity sibling (and a
            # conversation's #tool/#resp streams could split)
            key = conversation_of(handle.conversation_id or handle.seq_id)
            target = self.replica_for_partition(
                self.partition_for(key), exclude=source
            )
            if target is None:
                # not counted here: on a plain trip a refused handle stays
                # pending and replays locally after the rebuild (no stream
                # fails), and at give-up the scheduler's pending-fail loop
                # counts every stream the drain couldn't save exactly once
                return False
            if not target.scheduler.adopt(handle):
                # the adopter is at its backpressure bound and the handle
                # was never admitted on the source — plain queued load.
                # Refused like a fresh submit would be: on a trip it stays
                # pending and replays locally after the rebuild; at
                # give-up the pending-fail loop sheds it with the
                # retryable replica_out error. adopt runs BEFORE the
                # session import so a refusal leaves no twin of the
                # conversation's bytes on the non-serving sibling.
                return False
            if session_payload is not None:
                try:
                    if target.scheduler.import_session_entry(session_payload):
                        self.metrics.inc("finchat_fleet_session_handoffs_total")
                except Exception as e:
                    logger.error("session handoff to %s failed for %s: %s",
                                 target.replica_id, key, e)
            self.metrics.inc("finchat_fleet_drained_streams_total")
            trace_id = getattr(handle, "trace_id", None)  # test doubles
            if TRACER.enabled and trace_id is not None:
                TRACER.event("drain_handoff", trace_id, track="fleet",
                             args={"source": source.replica_id,
                                   "target": target.replica_id})
            logger.info("fleet: drained %s (%s) %s→%s", handle.seq_id, key,
                        source.replica_id, target.replica_id)
            return True

        return sink

    # --- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        for rep in self.replicas:
            await rep.scheduler.start()
        self._running = True
        if self.cfg.respawn and len(self.replicas) > 1:
            self._supervisor_task = asyncio.create_task(self._supervise())

    async def stop_supervisor(self) -> None:
        """Cancel the supervisor and in-flight respawns WITHOUT stopping
        the replicas. The graceful drain calls this before per-replica
        ``shutdown_drain`` so a respawn's device rebuild can't race the
        drain's offload/release on the same engine (serve/app.py
        ``drain_and_stop``). A ``revive_async`` rebuild already past its
        cancellation point finishes on its worker thread — harmless: a
        RESPAWNING replica holds no live sequences, and its cancelled
        task never runs ``_revive_commit``."""
        self._running = False
        for task in (*self._respawn_tasks,
                     *([self._supervisor_task] if self._supervisor_task else ())):
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        self._respawn_tasks.clear()
        self._supervisor_task = None

    async def stop(self) -> None:
        await self.stop_supervisor()
        for rep in self.replicas:
            await rep.scheduler.stop()

    async def _supervise(self) -> None:
        """Watch for OUT replicas and respawn them while the fleet keeps
        serving: revive the device state from a clean slate, run the
        serving layer's on_respawn hooks (prompt-head re-registration),
        then mark LIVE — the router folds its partitions back in."""
        while self._running:
            for rep in self.replicas:
                if rep.state == OUT:
                    rep.state = RESPAWNING
                    task = asyncio.get_running_loop().create_task(
                        self._respawn(rep)
                    )
                    self._respawn_tasks.add(task)
                    task.add_done_callback(self._respawn_tasks.discard)
            await asyncio.sleep(self.cfg.supervisor_interval_seconds)

    async def _respawn(self, rep: EngineReplica) -> None:
        delay = max(0.05, self.cfg.respawn_backoff_seconds)
        while self._running:
            try:
                # revive_async threads the device rebuild — seconds of KV
                # pool reallocation at real sizes — so the siblings' loops
                # (and the streams the drain just saved) keep serving
                ok = await rep.scheduler.revive_async()
            except Exception as e:
                logger.error("respawn of %s raised: %s", rep.replica_id, e)
                ok = False
            if ok:
                rep.registered_heads = set()
                for cb in list(self.on_respawn):
                    try:
                        result = cb(rep)
                        if asyncio.iscoroutine(result):
                            await result
                    except Exception as e:
                        logger.error("on_respawn hook failed for %s: %s",
                                     rep.replica_id, e)
                if getattr(rep.scheduler, "gave_up", False):
                    # the respawn itself re-wedged the engine: the
                    # on_respawn prompt-head re-registration drives real
                    # prefill rounds, and a flaky device can trip the
                    # breaker back to give-up while state is RESPAWNING —
                    # which _mark_out (LIVE-guarded) ignores. Marking LIVE
                    # here would route traffic to a known-wedged engine;
                    # stay RESPAWNING and retry with backoff instead.
                    logger.error(
                        "fleet: replica %s re-wedged during respawn "
                        "(give-up while RESPAWNING); retrying",
                        rep.replica_id,
                    )
                    ok = False
            if ok:
                rep.state = LIVE
                self._publish_live_gauge()
                self.metrics.inc("finchat_fleet_respawns_total")
                logger.info("fleet: replica %s respawned and LIVE",
                            rep.replica_id)
                return
            await asyncio.sleep(delay)
            delay = min(delay * 2, 10.0)
        rep.state = OUT  # shutting down mid-respawn: leave it marked out
