"""Disaggregated prefill/decode serving (ISSUE 17; ROBUSTNESS.md §6).

Prefill and decode have opposite resource shapes: prefill is a compute
burst that monopolizes the device for whole chunks, decode is a steady
trickle of small steps whose latency users feel per token. On a mixed
replica a long-prompt arrival stalls every in-flight stream for the
duration of its chunks. Role-typed pools split the two:

- ``fleet.roles`` assigns each replica ``prefill`` / ``decode`` /
  ``mixed``. The router's rendezvous hash runs over the SERVING pool
  (decode + mixed) only — prefill replicas never own conversations.
- When a serving replica admits a turn whose cold residue (prompt tokens
  not covered by a shared head, its RAM session entry, or a disk record)
  is at least one prefill chunk, the ``DisaggCoordinator`` first runs the
  prompt to completion on a prefill-pool replica (chunked, overlap- and
  ring-capable — it is an ordinary scheduler submission with
  ``max_new_tokens=1``), then hands the surviving KV to the serving
  replica over the EXISTING drain-handoff wire format
  (``export_session`` → ``import_session_entry``; ``kv_gap``/``kv_sink``
  travel, shared heads re-link against the importer's own registration).
  The handoff is a turn-start session migration — byte-identical by
  construction, same as a fleet drain.
- **Clean fallback**: an empty/drained/tripped prefill pool, a prefill
  error (including a breaker trip racing the pass — the tripped
  replica's drain sink may even deliver the bytes itself), or a refused
  import all just mean the serving replica prefills locally, exactly the
  mixed-serving behavior. Every fallback is counted by reason on
  ``finchat_disagg_fallbacks_total``.

The coordinator is attached (by ``EngineFleet``) ONLY to serving-pool
schedulers, so a prefill replica's own submissions can never recurse.
Prefill-pool placement reuses ``io/kafka.py partition_for_key`` — the
same CRC32 the broker and the router already use — so a conversation's
cold turns keep hitting the same prefill replica and its shared-head /
session state stays warm there between turns.
"""

from __future__ import annotations

import time

import numpy as np

from finchat_tpu.engine.sampler import SamplingParams
from finchat_tpu.io.kafka import partition_for_key
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.tracing import TRACER

logger = get_logger(__name__)

# replica pool roles (EngineReplica.role)
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)

# finchat_disagg_fallbacks_total reasons, pre-seeded per replica (R5):
# no_prefill_replica — prefill pool empty, drained, or all tripped
# prefill_error      — the prefill pass failed/produced nothing to export
# import_refused     — the serving replica refused the exported entry
# serving_pool_empty — every decode/mixed replica down: a prefill replica
#                      absorbed the routed message itself (serve/fleet.py)
FALLBACK_REASONS = ("no_prefill_replica", "prefill_error", "import_refused",
                    "serving_pool_empty")


def parse_roles(spec: str, n: int) -> list[str]:
    """``fleet.roles`` ("prefill,decode,decode,mixed") → one role per
    replica. Empty spec, or a spec that would leave NO serving replica
    (all prefill — a misconfiguration that could route nothing), falls
    back to all-mixed with a loud log. A short spec pads with mixed; a
    long one truncates."""
    if not spec.strip():
        return [ROLE_MIXED] * n
    roles = [r.strip().lower() or ROLE_MIXED for r in spec.split(",")]
    for r in roles:
        if r not in ROLES:
            raise ValueError(
                f"fleet.roles: unknown role {r!r} (expected one of {ROLES})"
            )
    roles = (roles + [ROLE_MIXED] * n)[:n]
    if all(r == ROLE_PREFILL for r in roles):
        logger.error("fleet.roles=%r leaves no serving replica; "
                     "running all replicas mixed instead", spec)
        return [ROLE_MIXED] * n
    return roles


class DisaggCoordinator:
    """Per-fleet: runs cold prompts on the prefill pool and hands the KV
    to the submitting serving replica before admission."""

    def __init__(self, fleet):
        self.fleet = fleet
        # conversation keys with a prefill pass in flight: a second turn
        # submitted concurrently proceeds without its own handoff rather
        # than duplicating the prefill work
        self._inflight: set[str] = set()
        self._n_passes = 0

    # --- pool views ------------------------------------------------------
    def prefill_pool(self) -> list:
        """Live, non-gave-up prefill replicas (drained/tripped excluded)."""
        return [
            r for r in self.fleet.live_replicas()
            if getattr(r, "role", ROLE_MIXED) == ROLE_PREFILL
            and not getattr(r.scheduler, "gave_up", False)
        ]

    # --- the handoff -----------------------------------------------------
    def _cold_residue(self, sched, conversation_id: str,
                      prompt_ids: list[int]) -> int:
        """Prompt tokens the serving replica would prefill COLD: total
        minus the last token (which decodes, never prefills warm) minus
        the deepest page-floored coverage from its shared heads and its
        session tiers. A disk/fabric record counts as full coverage —
        admission restores it locally and a handoff would be pure waste."""
        page = sched.engine.page_size
        _entry, covered = sched._match_prefix(prompt_ids)
        cache = sched.session_cache
        if cache is not None:
            if cache.get(conversation_id) is None:
                if cache.disk is not None and conversation_id in cache.disk:
                    return 0
            else:
                e = cache.get(conversation_id)
                m = min(e.n_tokens, len(prompt_ids) - 1)
                a = np.asarray(e.token_ids[:m], np.int32)
                b = np.asarray(prompt_ids[:m], np.int32)
                neq = np.nonzero(a != b)[0]
                common = int(neq[0]) if neq.size else m
                covered = max(covered, (common // page) * page)
        return len(prompt_ids) - 1 - covered

    async def maybe_prefill(self, sched, prompt_ids: list[int],
                            conversation_id: str,
                            trace_id: str | None = None) -> None:
        """Called by a serving scheduler's ``submit`` before admission.
        Best-effort by contract: every early return leaves the caller on
        the plain (mixed) path; nothing here may raise into submit."""
        if sched.session_cache is None:
            return  # no session tier = no wire format for the handoff
        residue = self._cold_residue(sched, conversation_id, prompt_ids)
        if residue < sched.engine.engine_cfg.prefill_chunk:
            return  # under one chunk of cold work: local prefill is fine
        metrics = sched.metrics
        pool = self.prefill_pool()
        if not pool:
            metrics.inc("finchat_disagg_fallbacks_total",
                        labels={"reason": "no_prefill_replica"})
            return
        if conversation_id in self._inflight:
            return
        self._inflight.add(conversation_id)
        t0 = time.perf_counter()
        try:
            rep = pool[partition_for_key(conversation_id, len(pool))]
            if rep.scheduler is sched:  # misconfigured double-attachment
                return
            payload = await self._prefill_pass(rep, prompt_ids,
                                               conversation_id, trace_id)
            if payload is None:
                metrics.inc("finchat_disagg_fallbacks_total",
                            labels={"reason": "prefill_error"})
                return
            # the existing drain-handoff wire format: cross-mode snapshots
            # and head-relink failures are refused (and counted) inside
            # import_session_entry itself
            try:
                ok = sched.import_session_entry(payload)
            except Exception as e:
                logger.error("disagg: import into %s failed for %s: %s",
                             sched.replica_id, conversation_id, e)
                ok = False
            src = rep.scheduler.session_cache
            if src is not None:
                if ok and src.fabric is not None:
                    # shared tier: the target's put just refreshed the
                    # record — drop only the source's RAM copy
                    src.drop_local(conversation_id)
                else:
                    src.discard(conversation_id)
            if not ok:
                metrics.inc("finchat_disagg_fallbacks_total",
                            labels={"reason": "import_refused"})
                return
            metrics.inc("finchat_disagg_handoffs_total")
            metrics.observe("finchat_disagg_handoff_seconds",
                            time.perf_counter() - t0)
            if TRACER.enabled:
                TRACER.event("disagg_handoff", trace_id, track="fleet",
                             args={"source": rep.replica_id,
                                   "target": sched.replica_id,
                                   "tokens": int(len(payload["token_ids"]))})
            logger.info("disagg: prefilled %s on %s, handed %d tokens to %s",
                        conversation_id, rep.replica_id,
                        len(payload["token_ids"]), sched.replica_id)
        except Exception as e:
            logger.error("disagg: handoff for %s failed: %s",
                         conversation_id, e)
            metrics.inc("finchat_disagg_fallbacks_total",
                        labels={"reason": "prefill_error"})
        finally:
            self._inflight.discard(conversation_id)

    async def _prefill_pass(self, rep, prompt_ids: list[int],
                            conversation_id: str,
                            trace_id: str | None) -> dict | None:
        """Run the prompt to completion on the prefill replica and export
        the retired session entry. An ordinary greedy submission with
        ``max_new_tokens=1``: retirement's ``_maybe_offload`` snapshots
        every page-whole prompt token into the replica's session cache
        (the one generated token rides past the page floor and is cut by
        the importer's divergence truncation on the real turn). The pass
        gets all of the prefill path's machinery for free — chunking,
        overlap coexistence, ring routing, bounded-KV eviction."""
        self._n_passes += 1
        psched = rep.scheduler
        try:
            handle = await psched.submit(
                f"__disagg_{self._n_passes}__", list(prompt_ids),
                SamplingParams(temperature=0.0, max_new_tokens=1),
                conversation_id=conversation_id, trace_id=trace_id,
            )
        except Exception as e:  # backpressure / length bound on the pool
            logger.warning("disagg: prefill submit on %s refused: %s",
                           rep.replica_id, e)
            return None
        while True:
            ev = await handle.events.get()
            if ev["type"] == "error":
                # a breaker trip mid-pass may have drained the handle to a
                # serving sibling — in that case the session bytes already
                # moved with it and the export below finds nothing, which
                # the caller counts as a fallback; the turn still serves
                logger.warning("disagg: prefill pass for %s errored: %s",
                               conversation_id, ev.get("message"))
                break
            if ev["type"] == "done":
                break
        if psched.session_cache is None:
            return None
        return psched.export_session(conversation_id)
