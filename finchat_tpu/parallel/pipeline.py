"""Pipeline parallelism over the ``pipe`` mesh axis (SURVEY §2.3 C4).

Until round 4 the ``pipe`` axis was pure surface — exposed in the mesh but
nothing could run at ``pipe > 1``. This module is the stage scheduler: a
GPipe-style microbatch pipeline expressed the TPU way, as a ``shard_map``
over the mesh with stage-to-stage activation transfer via ``ppermute`` —
point-to-point neighbor sends that ride DCN between hosts (mesh.py puts
``pipe`` right after ``data``).

The shard_map is ALL-manual: a partial-manual mapping (``axis_names=
{'pipe'}`` with data/model left GSPMD-auto) computes the identical forward
but its TRANSPOSE trips an XLA check failure in this toolchain ("Invalid
binary instruction opcode copy", hlo_instruction.cc:1585) — found while
bringing up the backward pass, round 4. In-stage TP therefore uses the
OTHER route that note anticipated: manual Megatron collectives in the
stage block (round 5) — layer weights arrive as column/row shards over
``model`` (``_pipeline_layer_specs``) and ``models/llama._layer`` psums
the two row-parallel projections over the axis, so a ``pipe x model``
mesh actually partitions both ways. In-stage DP shards the batch over
``data`` into the body (each data coordinate pipelines its own slice;
the shard_map transpose psums layer grads over data). PP x SP shards
the sequence over ``seq``: inside the manual region the ring body runs
DIRECTLY (no nested shard_map) with K/V rotating via ppermute("seq") —
see ``_sp_ring_attention``. All four axes compose in one step.

Layer placement falls out of the existing stacked-layer layout: every
``layers`` leaf is ``[L, ...]``, so sharding the leading axis over ``pipe``
(parallel/sharding.py) gives each stage a contiguous block of L/P layers
with no resharding — the same pytree serves the plain scanned forward
(pipe=1) and the pipeline.

Schedule: the classic forward-fill/drain loop. With P stages and M
microbatches, tick t of ``M + P - 1``:

  stage 0 ingests microbatch t (while t < M); every stage runs its local
  layer block on the activation it holds; activations hop one stage via
  ppermute; the last stage banks its output for microbatch t-(P-1).

Bubble fraction is (P-1)/(M+P-1) — callers pick ``n_micro >> P``. The loop
is a ``lax.scan`` so the whole pipeline is reverse-differentiable (ppermute
transposes to the reverse permutation), giving 1F1B-equivalent memory via
the usual remat-on-stage trade (``remat=True`` checkpoints each stage
block).

Composition note: the pipeline body runs cache-less attention (the
training / long-prefill shape) — full causal when ``seq == 1``, the
seq-sharded ring when ``seq > 1``.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from finchat_tpu.parallel.mesh import pcast, shard_map
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from finchat_tpu.models.llama import (
    LlamaConfig,
    _layer,
    lm_head,
    make_causal_attention,
    rms_norm,
)
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _stage_block(x, layers_local, positions, *, config, attention, remat,
                 tp_axis, tp_size, tp_overlap=False, tp_chunks=4,
                 qm_backend=None):
    """Run this stage's local layer block (scan over L/P layers)."""

    def body(x, scanned):
        layer_params, = scanned
        x, _ = _layer(
            x, layer_params, None, jnp.int32(0),
            positions=positions, config=config, attention=attention,
            tp_axis=tp_axis, tp_size=tp_size,
            tp_overlap=tp_overlap, tp_chunks=tp_chunks,
            qm_backend=qm_backend,
        )
        return x, None

    if remat:
        body = jax.checkpoint(body)
    x, _ = lax.scan(body, x, (layers_local,))
    return x


def _pipeline_body(
    layers_local: dict[str, Any],
    x: jax.Array,  # [B(/data), S, D] embedded input (replicated over pipe)
    positions: jax.Array,  # [B, S]
    *,
    config: LlamaConfig,
    n_micro: int,
    n_stages: int,
    attention,
    remat: bool,
    tp_axis,
    tp_size: int,
    tp_overlap: bool,
    tp_chunks: int,
    qm_backend,
    carry_varying: tuple,
):
    """Per-device pipeline schedule under shard_map (manual axis: pipe)."""
    B, S, D = x.shape
    mb = B // n_micro
    stage = lax.axis_index("pipe")
    is_first = stage == 0
    is_last = stage == n_stages - 1
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    # the carries vary over pipe (per-stage) plus whatever axes the
    # activations shard over (data / seq), passed in by the caller
    held0 = pcast(jnp.zeros((mb, S, D), x.dtype), carry_varying, to="varying")
    out0 = pcast(jnp.zeros((B, S, D), x.dtype), carry_varying, to="varying")

    def tick(carry, t):
        held, outputs = carry
        # stage 0 ingests microbatch t (clamped; junk past M never reaches
        # the last stage before the loop ends)
        start = jnp.minimum(t, n_micro - 1) * mb
        ingest = lax.dynamic_slice_in_dim(x, start, mb, axis=0)
        act = jnp.where(is_first, ingest, held)
        # NOTE: every stage must use the positions of the microbatch it is
        # currently processing — stage s at tick t holds microbatch t-s.
        # With per-row position offsets this matters; slice with the same
        # clamp as the ingest and shift by the stage index.
        pos_start = jnp.clip(t - stage, 0, n_micro - 1) * mb
        pos_mb = lax.dynamic_slice_in_dim(positions, pos_start, mb, axis=0)
        act = _stage_block(
            act, layers_local, pos_mb,
            config=config, attention=attention, remat=remat,
            tp_axis=tp_axis, tp_size=tp_size,
            tp_overlap=tp_overlap, tp_chunks=tp_chunks,
            qm_backend=qm_backend,
        )
        # bank the last stage's finished microbatch t-(P-1)
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1) * mb
        prev = lax.dynamic_slice_in_dim(outputs, out_idx, mb, axis=0)
        bank = jnp.where(jnp.logical_and(is_last, t >= n_stages - 1), act, prev)
        outputs = lax.dynamic_update_slice_in_dim(outputs, bank, out_idx, axis=0)
        # hop to the next stage (the last stage's act is not forwarded)
        held = lax.ppermute(act, "pipe", perm)
        return (held, outputs), None

    (_, outputs), _ = lax.scan(
        tick, (held0, out0), jnp.arange(n_micro + n_stages - 1)
    )
    # stack per-stage outputs on a leading pipe axis; caller takes the last
    return outputs[None]


# Megatron split of a stage's layer leaves (leading dim is the stacked
# layer axis, sharded over pipe): column-parallel out dims, row-parallel
# in dims; everything else (norms, MoE leaves) replicated in-stage
_TP_COL = ("attn_q", "attn_k", "attn_v", "mlp_gate", "mlp_up")
_TP_ROW = ("attn_o", "mlp_down")


def _stage_tp(config: LlamaConfig, mesh: Mesh) -> int:
    """In-stage TP degree: the mesh's ``model`` extent when the head /
    hidden dims divide it (and the model is dense); 1 (replicated, with a
    warning) otherwise — matching v1 behavior for odd shapes."""
    tp = mesh.shape.get("model", 1)
    if tp == 1:
        return 1
    ok = (
        not config.n_experts
        and config.n_heads % tp == 0
        and config.n_kv_heads % tp == 0
        and config.hidden_dim % tp == 0
    )
    if not ok:
        logger.warning(
            "pipeline in-stage TP disabled: model axis %d does not divide "
            "heads/kv/hidden (%d/%d/%d) or the model is MoE; stages run "
            "replicated over model",
            tp, config.n_heads, config.n_kv_heads, config.hidden_dim,
        )
        return 1
    return tp


def _pipeline_layer_specs(layers: dict[str, Any], tp: int) -> dict[str, Any]:
    def spec(name: str) -> P:
        if tp > 1 and name in _TP_COL:
            return P("pipe", None, "model")
        if tp > 1 and name in _TP_ROW:
            return P("pipe", "model", None)
        return P("pipe")

    return {name: spec(name) for name in layers}


def _sp_ring_attention(varying: tuple, n_blocks: int):
    """Stage-block attention for PP x SP: the sequence dim arrives
    already sharded over ``seq`` (a manual axis of the enclosing
    shard_map), so the ring body runs DIRECTLY — no nested shard_map —
    with K/V blocks rotating via ppermute("seq")."""
    from finchat_tpu.ops.ring_attention import _ring_body

    def attention(q, k, v, cache, layer_idx):
        out = _ring_body(
            q, k, v, axis="seq", varying=varying, n_blocks=n_blocks,
            causal=True, scale=q.shape[-1] ** -0.5,
        )
        return out, cache

    return attention


def pipeline_forward(
    params: dict[str, Any],
    tokens: jax.Array,  # [B, S] int32
    positions: jax.Array,  # [B, S] int32
    *,
    config: LlamaConfig,
    mesh: Mesh,
    n_micro: int,
    attn_backend: str = "ref",
    remat: bool = True,
    tp_overlap: bool = False,
    tp_chunks: int = 4,
    qm_backend: str | None = None,
) -> jax.Array:
    """Full forward through the stage pipeline; returns logits [B,S,vocab].

    Requires ``n_layers % pipe == 0`` and ``B % n_micro == 0``. Embedding,
    final norm, and the LM head run replicated outside the pipeline (they
    are small next to the layer stack). ``tp_overlap`` (engine.tp_overlap
    / FINCHAT_TP_OVERLAP) switches the in-stage row-parallel outputs from
    the serial layer-end psum to the chunked collective–compute overlap
    schedule (ops/tp_overlap.py) — byte-identical per element, engaged
    only when the model axis is actually active."""
    n_stages = mesh.shape["pipe"]
    assert config.n_layers % n_stages == 0, (config.n_layers, n_stages)
    # in-stage DP: the batch dim shards over `data` INTO the pipeline
    # body when it divides (each data coordinate pipelines its own batch
    # slice; the scan/ppermute/psum transpose sums layer grads over data
    # automatically). Falls back to replicated batch otherwise.
    dp = mesh.shape.get("data", 1)
    if tokens.shape[0] % (dp * n_micro):
        logger.warning(
            "pipeline in-stage DP disabled: batch %d does not split into "
            "data=%d x n_micro=%d; the data axis runs replicated",
            tokens.shape[0], dp, n_micro,
        )
        dp = 1
    assert tokens.shape[0] % (dp * n_micro) == 0, (tokens.shape, dp, n_micro)
    # PP x SP: the sequence dim shards over `seq` into the body when it
    # divides; the stage block then ring-attends (K/V rotate the seq
    # ring) instead of full-sequence attention, so per-device activations
    # are O(S/seq) on top of the microbatch split.
    sp = mesh.shape.get("seq", 1)
    if tokens.shape[1] % sp:
        logger.warning(
            "pipeline in-stage SP disabled: seq len %d not divisible by "
            "seq axis %d; the seq axis runs replicated",
            tokens.shape[1], sp,
        )
        sp = 1
    if sp > 1 and attn_backend != "ref":
        # the SP stage block runs the fp32 ring body directly (it must —
        # the seq dim is already sharded in the manual region); other
        # backends have no seq-sharded stage variant
        logger.warning(
            "pipeline SP stage block uses the ring attention body; "
            "attn_backend=%r is ignored inside the pipeline", attn_backend,
        )
    tp = _stage_tp(config, mesh)
    tp_axis = "model" if tp > 1 else None

    dp_axes = ("data",) if dp > 1 else ()
    seq_axes = ("seq",) if sp > 1 else ()
    x_spec = P(dp_axes or None, "seq" if sp > 1 else None)
    if sp > 1:
        # activations inside the body vary over every engaged axis; the
        # ring accumulators must be born with the same varying set
        act_varying = dp_axes + ("pipe",) + seq_axes + (("model",) if tp > 1 else ())
        attention = _sp_ring_attention(act_varying, sp)
    else:
        attention = make_causal_attention(attn_backend)

    x = params["embed"][tokens]
    layer_specs = _pipeline_layer_specs(params["layers"], tp)
    fn = shard_map(
        partial(
            _pipeline_body,
            config=config, n_micro=n_micro, n_stages=n_stages,
            attention=attention, remat=remat, tp_axis=tp_axis, tp_size=tp,
            tp_overlap=tp_overlap and tp > 1, tp_chunks=tp_chunks,
            qm_backend=qm_backend,
            carry_varying=dp_axes + ("pipe",) + seq_axes,
        ),
        mesh=mesh,
        in_specs=(layer_specs, x_spec, x_spec),
        out_specs=P("pipe", *x_spec),
    )
    stacked = fn(params["layers"], x, positions)  # [pipe, B, S, D]
    x = stacked[-1]

    x = rms_norm(x, params["norm"], config.norm_eps)
    return lm_head(params, x, config=config)


def make_pipeline_train_step(
    config: LlamaConfig,
    optimizer,
    mesh: Mesh,
    *,
    n_micro: int,
    attn_backend: str = "ref",
    remat: bool = True,
    tp_overlap: bool = False,
    tp_chunks: int = 4,
):
    """Jitted train step running the forward through the stage pipeline.

    The backward pass re-traverses the schedule in reverse (scan transpose;
    ppermute transposes to the reverse hop), so gradients for each stage's
    layers accumulate on that stage — no parameter resharding. Params must
    be placed with ``shard_params_for_pipeline``.
    """
    import optax

    from finchat_tpu.train.train_step import TrainState

    def loss_fn(params, tokens):
        B, S = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(S), (B, S))
        logits = pipeline_forward(
            params, tokens, positions,
            config=config, mesh=mesh, n_micro=n_micro,
            attn_backend=attn_backend, remat=remat,
            tp_overlap=tp_overlap, tp_chunks=tp_chunks,
        )
        targets = tokens[:, 1:]
        ce = optax.softmax_cross_entropy_with_integer_labels(logits[:, :-1, :], targets)
        return ce.mean()

    @partial(jax.jit, donate_argnums=(0,))
    def train_step(state: "TrainState", tokens: jax.Array):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens)
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params=params, opt_state=opt_state, step=state.step + 1), loss

    return train_step


def shard_params_for_pipeline(params: dict[str, Any], mesh: Mesh,
                              config: LlamaConfig | None = None) -> dict[str, Any]:
    """Place params with the stacked layer axis sharded over ``pipe`` and
    — when ``config`` is given and divisible — the Megatron dims over
    ``model`` (matching the pipeline's all-manual in_specs exactly, so
    entry incurs no resharding); embed/norm/head replicated."""
    from finchat_tpu.parallel.sharding import shard_params

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    tp = _stage_tp(config, mesh) if config is not None else 1
    shardings: dict[str, Any] = {
        "embed": ns(),
        "layers": {
            name: NamedSharding(mesh, spec)
            for name, spec in _pipeline_layer_specs(params["layers"], tp).items()
        },
        "norm": ns(),
    }
    if "lm_head" in params:
        shardings["lm_head"] = ns()
    return shard_params(params, shardings)
