"""Sharding rules: logical param/state layout → mesh placement.

Megatron-style TP expressed as GSPMD constraints — we annotate the weights
and let XLA's SPMD partitioner insert the collectives (psum after
row-parallel matmuls etc.), instead of hand-writing NCCL calls the way
GPU frameworks do:

- attention q/k/v projections column-parallel over heads (``model`` axis),
  output projection row-parallel → one all-reduce;
- MLP gate/up column-parallel, down row-parallel → one all-reduce;
- embeddings + lm_head feature/vocab sharded; norms replicated;
- KV-cache pages sharded over KV heads on ``model`` (matches the k/v
  projection sharding, so cache writes are local);
- batch-bearing engine state sharded on ``data`` where useful; page tables
  and lengths replicated (they are tiny and host-updated).

Parity note: the reference has no parallelism to mirror (SURVEY §2.3); this
module IS the new framework surface specified there.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def llama_param_shardings(mesh: Mesh) -> dict[str, Any]:
    """PartitionSpec tree matching models/llama.py:init_params layout.

    Leading axis of every ``layers`` leaf is the stacked layer axis — never
    sharded (it is scanned over)."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        # replicated: a feature- or vocab-sharded table makes the token
        # gather's output sharding ambiguous under GSPMD (needs an explicit
        # out_sharding at the lookup); revisit when embed HBM matters.
        "embed": ns(None, None),
        "layers": {
            "attn_q": ns(None, None, "model"),  # [L, D, H*hd] column-parallel
            "attn_k": ns(None, None, "model"),
            "attn_v": ns(None, None, "model"),
            "attn_o": ns(None, "model", None),  # [L, H*hd, D] row-parallel
            "mlp_gate": ns(None, None, "model"),
            "mlp_up": ns(None, None, "model"),
            "mlp_down": ns(None, "model", None),
            # MoE (models with n_experts > 0): EP over `expert` on the
            # leading expert dim, TP over `model` on the hidden dim — the
            # expert-sum becomes a psum over EP shards (GSPMD inserts it)
            "router": ns(None, None, None),  # fp32 routing, replicated
            "moe_gate": ns(None, "expert", None, "model"),  # [L, E, D, F]
            "moe_up": ns(None, "expert", None, "model"),
            "moe_down": ns(None, "expert", "model", None),  # [L, E, F, D]
            "ln_attn": ns(None, None),
            "ln_mlp": ns(None, None),
        },
        "norm": ns(None),
        "lm_head": ns(None, "model"),  # vocab-sharded logits
    }


def decode_state_shardings(mesh: Mesh, n_kv_heads: int | None = None) -> dict[str, Any]:
    """Shardings for engine.DecodeState fields (see engine/engine.py).

    ``n_kv_heads`` guards the fused-dim split: sharding [.., Hkv*hd] on
    ``model`` is only a whole-KV-head split (the locality the Pallas paged
    kernel's per-head value slices rely on) when the model axis divides
    Hkv. The fused dim often divides NUMERICALLY even when the head count
    doesn't (model=8, Hkv=4, hd=64 → 256/8 splits mid-head), so divisibility
    of the byte count is not enough — pass the head count and the pages
    replicate when it doesn't divide."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    kv_whole_heads = (
        n_kv_heads is None or n_kv_heads % mesh.shape.get("model", 1) == 0
    )
    if not kv_whole_heads:
        logger.warning(
            "model axis %d does not divide n_kv_heads %s; replicating KV pages",
            mesh.shape.get("model", 1), n_kv_heads,
        )
    kv_spec = ns(None, None, None, "model") if kv_whole_heads else ns(None, None, None, None)
    # int8-KV scale arrays [L, P, pad8(Hkv), PS]: the head ROW dim (2)
    # splits over the model axis in the same whole-KV-head blocks the fused
    # page minor dim does — but only when the sublane padding can't
    # interleave with the split (Hkv % 8 == 0 makes pad8(Hkv) == Hkv, so row
    # blocks == head blocks and the placement is communication-free,
    # matching Llama-3-8B/70B's Hkv=8). Otherwise they replicate: scales
    # are ~6% of the pages' bytes, so replication is cheap and strictly
    # better than a misaligned shard that GSPMD would repair with gathers.
    # When kv_quant is off these leaves are (1,1,1,1) placeholders and
    # _fit_sharding quietly replicates them.
    scale_spec = (
        ns(None, None, "model", None)
        if kv_whole_heads and n_kv_heads is not None and n_kv_heads % 8 == 0
        else ns(None, None, None, None)
    )
    return {
        # [L, pages, page_size, Hkv*hd] — the fused KV-head dim on the model
        # axis (head-major within the fused dim, so a model-axis shard is a
        # whole number of KV heads — matching the k/v projection sharding,
        # keeping cache writes local)
        "k_pages": kv_spec,
        "v_pages": kv_spec,
        "k_scales": scale_spec,
        "v_scales": scale_spec,
        "page_table": ns(None, None),
        "context_lens": ns(None),
        "last_tokens": ns(None),
        "kv_gaps": ns(None),
        "rng": ns(),
    }


# Tensors above this size refuse to silently replicate when their sharded
# dim doesn't divide the mesh axis — at that scale replication means HBM
# blow-up on real checkpoints and the config error must fail fast. Small
# (debug-model) tensors replicate with a warning so tiny presets run on any
# mesh.
_REPLICATE_LIMIT_BYTES = 256 * 1024 * 1024


def _fit_sharding(
    sharding: NamedSharding, shape: tuple[int, ...], nbytes: int
) -> NamedSharding:
    """Drop (replicate) any spec axis whose mesh extent does not divide the
    array dimension; raise instead when the tensor is too large to replicate
    safely. Production-sized configs divide evenly and are untouched."""
    mesh = sharding.mesh
    spec = list(sharding.spec) + [None] * (len(shape) - len(sharding.spec))
    fitted = []
    for dim, axes in zip(shape, spec):
        if axes is None:
            fitted.append(None)
            continue
        extent = 1
        for a in (axes if isinstance(axes, tuple) else (axes,)):
            extent *= mesh.shape[a]
        if dim % extent:
            if nbytes > _REPLICATE_LIMIT_BYTES:
                raise ValueError(
                    f"dim of size {dim} (tensor shape {shape}, {nbytes} bytes) is not "
                    f"divisible by mesh axes {axes!r} = {extent}; refusing to replicate "
                    "a tensor this large — fix the mesh/model config"
                )
            if dim > 1:  # size-1 dims (placeholder leaves) replicate silently
                logger.warning(
                    "replicating dim of size %d (not divisible by mesh axes %r = %d)",
                    dim, axes, extent,
                )
            fitted.append(None)
        else:
            fitted.append(axes)
    return NamedSharding(mesh, P(*fitted))


def _leaf_nbytes(x) -> int:
    """``x.nbytes``, tolerating jax 0.4 PRNG-key arrays (whose extended
    dtype leaves ``nbytes`` abstract there) — the size only gates the
    large-tensor replication refusal, and key leaves are tiny."""
    try:
        return int(x.nbytes)
    except Exception:
        n = 1
        for d in x.shape:
            n *= int(d)
        return n * int(getattr(x.dtype, "itemsize", None) or 4)


def shard_params(params: dict[str, Any], shardings: dict[str, Any]) -> dict[str, Any]:
    """Place a (host or single-device) param tree onto the mesh. Sharding
    entries with no matching param (e.g. ``lm_head`` under tied embeddings,
    MoE specs on a dense model) are pruned at every dict level; non-dividing
    dims are replicated."""

    def prune(spec, tree):
        if isinstance(spec, dict) and isinstance(tree, dict):
            return {k: prune(spec[k], v) for k, v in tree.items()}
        return spec

    from finchat_tpu.models.quant import Q4Tensor, QTensor

    def place(x, s):
        if isinstance(x, QTensor):
            # pre-quantized leaf (streaming int8 load): q takes the weight's
            # spec; the per-output-column scale [..., N] drops the spec's
            # contraction axis (-2)
            spec = list(s.spec) + [None] * (x.q.ndim - len(s.spec))
            scale_s = NamedSharding(s.mesh, P(*spec[:-2], spec[-1]))
            return QTensor(
                q=jax.device_put(x.q, _fit_sharding(s, x.q.shape, _leaf_nbytes(x.q))),
                scale=jax.device_put(
                    x.scale, _fit_sharding(scale_s, x.scale.shape, _leaf_nbytes(x.scale))
                ),
            )
        if isinstance(x, Q4Tensor):
            # int4: q is the weight spec over the PACKED [.., K//2, N]
            # layout (K-axis shards that stop dividing simply replicate via
            # _fit_sharding); the per-group scale [..., G, N] keeps the
            # output axis and replicates the group axis
            spec = list(s.spec) + [None] * (x.q.ndim - len(s.spec))
            scale_s = NamedSharding(s.mesh, P(*spec[:-2], None, spec[-1]))
            return Q4Tensor(
                q=jax.device_put(x.q, _fit_sharding(s, x.q.shape, _leaf_nbytes(x.q))),
                scale=jax.device_put(
                    x.scale, _fit_sharding(scale_s, x.scale.shape, _leaf_nbytes(x.scale))
                ),
            )
        return jax.device_put(x, _fit_sharding(s, x.shape, _leaf_nbytes(x)))

    pruned = prune(shardings, params)
    return jax.tree.map(
        place, params, pruned,
        is_leaf=lambda x: isinstance(x, (QTensor, Q4Tensor)),
    )


def shard_decode_state(state, mesh: Mesh, n_kv_heads: int | None = None):
    """Place an engine DecodeState onto the mesh."""
    import dataclasses

    sh = decode_state_shardings(mesh, n_kv_heads)
    return dataclasses.replace(
        state,
        **{
            f: jax.device_put(
                getattr(state, f),
                _fit_sharding(sh[f], getattr(state, f).shape, _leaf_nbytes(getattr(state, f))),
            )
            for f in sh
        },
    )
