"""Sharding rules: logical param/state layout → mesh placement.

Megatron-style TP expressed as GSPMD constraints — we annotate the weights
and let XLA's SPMD partitioner insert the collectives (psum after
row-parallel matmuls etc.), instead of hand-writing NCCL calls the way
GPU frameworks do:

- attention q/k/v projections column-parallel over heads (``model`` axis),
  output projection row-parallel → one all-reduce;
- MLP gate/up column-parallel, down row-parallel → one all-reduce;
- embeddings + lm_head feature/vocab sharded; norms replicated;
- KV-cache pages sharded over KV heads on ``model`` (matches the k/v
  projection sharding, so cache writes are local);
- batch-bearing engine state sharded on ``data`` where useful; page tables
  and lengths replicated (they are tiny and host-updated).

Parity note: the reference has no parallelism to mirror (SURVEY §2.3); this
module IS the new framework surface specified there.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def llama_param_shardings(mesh: Mesh) -> dict[str, Any]:
    """PartitionSpec tree matching models/llama.py:init_params layout.

    Leading axis of every ``layers`` leaf is the stacked layer axis — never
    sharded (it is scanned over)."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        # replicated: a feature- or vocab-sharded table makes the token
        # gather's output sharding ambiguous under GSPMD (needs an explicit
        # out_sharding at the lookup); revisit when embed HBM matters.
        "embed": ns(None, None),
        "layers": {
            "attn_q": ns(None, None, "model"),  # [L, D, H*hd] column-parallel
            "attn_k": ns(None, None, "model"),
            "attn_v": ns(None, None, "model"),
            "attn_o": ns(None, "model", None),  # [L, H*hd, D] row-parallel
            "mlp_gate": ns(None, None, "model"),
            "mlp_up": ns(None, None, "model"),
            "mlp_down": ns(None, "model", None),
            "ln_attn": ns(None, None),
            "ln_mlp": ns(None, None),
        },
        "norm": ns(None),
        "lm_head": ns(None, "model"),  # vocab-sharded logits
    }


def decode_state_shardings(mesh: Mesh) -> dict[str, Any]:
    """Shardings for engine.DecodeState fields (see engine/engine.py)."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        # [L, pages, page_size, Hkv, hd] — KV heads on the model axis
        "k_pages": ns(None, None, None, "model", None),
        "v_pages": ns(None, None, None, "model", None),
        "page_table": ns(None, None),
        "context_lens": ns(None),
        "last_tokens": ns(None),
        "rng": ns(),
    }


def shard_params(params: dict[str, Any], shardings: dict[str, Any]) -> dict[str, Any]:
    """Place a (host or single-device) param tree onto the mesh. Sharding
    entries with no matching param (e.g. ``lm_head`` under tied embeddings)
    are ignored."""
    pruned = {k: v for k, v in shardings.items() if k in params}
    return jax.tree.map(jax.device_put, params, pruned)


def shard_decode_state(state, mesh: Mesh):
    """Place an engine DecodeState onto the mesh."""
    import dataclasses

    sh = decode_state_shardings(mesh)
    return dataclasses.replace(
        state,
        **{f: jax.device_put(getattr(state, f), sh[f]) for f in sh},
    )
