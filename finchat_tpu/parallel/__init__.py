from finchat_tpu.parallel.mesh import MeshSpec, build_mesh
from finchat_tpu.parallel.sharding import (
    llama_param_shardings,
    decode_state_shardings,
    shard_params,
)

__all__ = [
    "MeshSpec",
    "build_mesh",
    "llama_param_shardings",
    "decode_state_shardings",
    "shard_params",
]
