"""Device mesh construction (SURVEY §5.8 model plane).

Axis convention (scaling-book style):
- ``data``   — batch/DP; gradients all-reduce here.
- ``pipe``   — pipeline parallelism; stages exchange activations point-to-
  point via the GPipe-style microbatch scheduler in parallel/pipeline.py
  (layer stack sharded by stage over this axis).
- ``model``  — tensor parallelism; attention heads + MLP hidden sharded.
- ``seq``    — sequence/context parallelism (ring attention rides this).
- ``expert`` — expert parallelism (MoE models; axis exposed, size 1 today).

ICI/DCN note: axis ORDER matters on real slices — ``jax.make_mesh`` puts the
fastest-varying (last) axis on the innermost ICI ring, so ``model`` (the
chattiest: 2 all-reduces/layer) is last; ``data`` (one gradient reduce per
step, DCN-tolerant) is first and lands across slices/hosts; ``pipe`` sits
right after ``data`` (stage hops are infrequent point-to-point sends and
tolerate DCN).

Multi-host: call ``initialize_distributed()`` once per process before
building the mesh; jax then sees the global device set.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
from jax.sharding import Mesh

from finchat_tpu.utils.config import MeshConfig
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

AXES = ("data", "pipe", "seq", "expert", "model")

# --- jax version compat (this image runs 0.4.x; jax 0.5 moved things) ----
# ONE seam for the whole repo: ops/ and parallel/ import shard_map from
# here instead of reaching for the 0.5-only ``jax.shard_map`` alias.
try:
    shard_map = jax.shard_map  # jax >= 0.5
except AttributeError:  # pragma: no cover - depends on image
    from functools import partial as _partial

    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

    # 0.4's replication checker predates the varying-type machinery: it
    # rejects lax.cond whose branches differ in inferred replication (the
    # ring kernel's causal block skip) and its collective rewrites corrupt
    # multi-axis compositions. The bodies here manage their own
    # replication (explicit pcast/psum), so disable the checker — the
    # exact workaround jax's own error message prescribes.
    shard_map = _partial(_shard_map, check_rep=False)

try:
    pcast = jax.lax.pcast  # jax >= 0.7 explicit varying-type casts
except AttributeError:  # pragma: no cover - depends on image
    def pcast(x, axis_name, *, to):  # type: ignore[misc]
        """No-op stand-in: pre-0.7 shard_map has no varying/replicated
        value typing, so the cast is purely a type-level annotation there
        — numerically identity on every jax version."""
        del axis_name, to
        return x


def make_abstract_mesh(sizes: tuple, names: tuple):
    """``jax.sharding.AbstractMesh`` across the 0.4→0.5 signature change:
    0.5+ takes ``(axis_sizes, axis_names)``; 0.4 takes one
    ``((name, size), ...)`` shape tuple. Shape-level sharding checks
    (tests/test_parallel.py) build their device-free meshes through here."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(tuple(sizes), tuple(names))
    except TypeError:  # jax 0.4: zips names with sizes itself
        return AbstractMesh(tuple(zip(names, sizes)))


@dataclass(frozen=True)
class MeshSpec:
    data: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1
    model: int = -1  # -1 = absorb all remaining devices

    @classmethod
    def from_config(cls, cfg: MeshConfig) -> "MeshSpec":
        return cls(data=cfg.data, pipe=cfg.pipe, seq=cfg.seq,
                   expert=cfg.expert, model=cfg.model)

    def resolve(self, n_devices: int) -> tuple[int, ...]:
        sizes = [self.data, self.pipe, self.seq, self.expert, self.model]
        free = [i for i, s in enumerate(sizes) if s == -1]
        fixed = 1
        for s in sizes:
            if s != -1:
                fixed *= s
        if len(free) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if free:
            if n_devices % fixed:
                raise ValueError(f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[free[0]] = n_devices // fixed
        total = 1
        for s in sizes:
            total *= s
        if total != n_devices:
            raise ValueError(f"mesh {dict(zip(AXES, sizes))} needs {total} devices, have {n_devices}")
        return tuple(sizes)


def build_mesh(spec: MeshSpec | None = None, devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    # Auto axis types = classic GSPMD propagation (the model code stays
    # sharding-agnostic; XLA infers intermediate shardings + collectives).
    # ``AxisType`` only exists from jax 0.5 — older jax has no explicit
    # axis-type machinery and every axis IS Auto, so omitting the kwarg
    # builds the identical mesh there.
    axis_type = getattr(jax.sharding, "AxisType", None)
    kwargs = {"axis_types": (axis_type.Auto,) * len(AXES)} if axis_type else {}
    mesh = jax.make_mesh(sizes, AXES, devices=devices, **kwargs)
    logger.info("mesh: %s over %d devices", dict(zip(AXES, sizes)), len(devices))
    return mesh


def initialize_distributed(coordinator: str | None = None, num_processes: int | None = None, process_id: int | None = None) -> None:
    """Multi-host init (jax.distributed); call before any backend use on
    every host of a multi-host slice/DCN job."""
    kwargs = {}
    if coordinator:
        kwargs = dict(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    jax.distributed.initialize(**kwargs)
    logger.info(
        "distributed initialized: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
