from finchat_tpu.io.schemas import (
    ChatMessage,
    complete_chunk,
    error_chunk,
    response_chunk,
    timeout_chunk,
)
from finchat_tpu.io.kafka import InMemoryBroker, KafkaClient
from finchat_tpu.io.store import ConversationStore, InMemoryStore, render_context

__all__ = [
    "ChatMessage",
    "response_chunk",
    "complete_chunk",
    "error_chunk",
    "timeout_chunk",
    "KafkaClient",
    "InMemoryBroker",
    "ConversationStore",
    "InMemoryStore",
    "render_context",
]
