"""Answered-message journal (ISSUE 7; ROBUSTNESS.md §5).

The at-least-once plane (PR 5/6) dedupes redelivered ``message_id``s
through an in-memory ring — which dies with the process, so a crash plus
Kafka redelivery of an answered-but-uncommitted message could double-answer
a conversation (the trade ROBUSTNESS.md used to document). This journal
closes it:

- ``append(message_id)`` writes one checksummed line and fsyncs BEFORE the
  app commits the message's Kafka offset (serve/app.py ``_done``). The
  ordering is the whole contract: if the process dies between the answer
  and the commit, the redelivered message finds its id in the replayed
  journal and is skipped; if it dies between the fsync and the answer's
  last produce... there is no such window — the id is appended only after
  the stream COMPLETED.
- Failed / shed / timed-out ids are never journaled (the app journals only
  answered ones), so a producer retrying a retryable error is reprocessed.
- ``replay()`` at startup parses the journal, skipping corrupt records
  (a torn final line after a crash is expected; each skip is counted, the
  rest of the file is still honored — never a crash, never a lost id that
  parsed), compacts the file to the most recent ``keep`` distinct ids
  (matching the dedupe ring's bound — older ids have aged out of the ring
  anyway), and returns them for the caller to seed the fleet-wide
  ``DedupeRing`` (serve/fleet.py).

Line format: ``v1 <crc32 hex> <json message_id>\\n`` — the CRC covers the
JSON payload, so a half-written or bit-flipped line never replays as a
different id.
"""

from __future__ import annotations

import json
import os
import zlib
from pathlib import Path

from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

_BAD = object()


class AnsweredJournal:
    """Append-only, fsync-before-commit record of answered message ids."""

    FILENAME = "answered.journal"

    def __init__(self, dir_path: str, *, fsync: bool = True, keep: int = 1024,
                 metrics=None):
        self.dir = Path(dir_path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.path = self.dir / self.FILENAME
        self.fsync = fsync
        self.keep = keep
        self.metrics = metrics if metrics is not None else METRICS
        self._fh = None
        # in-process compaction bound: the ring only ever holds ``keep``
        # ids, so a journal much larger than that is pure dead weight
        self._appends_since_compact = 0

    # --- record codec ----------------------------------------------------
    @staticmethod
    def _encode(message_id) -> bytes:
        payload = json.dumps(message_id).encode()
        return b"v1 %08x " % zlib.crc32(payload) + payload + b"\n"

    @staticmethod
    def _decode(line: bytes):
        """The id, or the ``_BAD`` sentinel for a corrupt/torn record."""
        parts = line.split(b" ", 2)
        if len(parts) != 3 or parts[0] != b"v1":
            return _BAD
        try:
            if int(parts[1], 16) != zlib.crc32(parts[2]):
                return _BAD
            return json.loads(parts[2].decode())
        except (ValueError, UnicodeDecodeError):
            return _BAD

    # --- write path ------------------------------------------------------
    def append(self, message_id) -> bool:  # finchat-lint: disable=event-loop-blocking -- fsync-BEFORE-commit IS the at-least-once contract (ROBUSTNESS §5); one ~50-byte line per answered message, journal.fsync=false is the relief valve
        """Durably record an ANSWERED id. Best-effort by contract: a
        failure (disk full, injected ``journal.append`` fault) logs and
        returns False — the answer already streamed, and refusing to
        commit over a journal error would wedge the partition; the cost
        of the miss is one possible duplicate answer after a crash,
        exactly the pre-journal trade."""
        try:
            inject("journal.append", message_id=message_id)
            if self._fh is None:
                self._fh = open(self.path, "ab")
            self._fh.write(self._encode(message_id))
            self._fh.flush()
            if self.fsync:
                os.fsync(self._fh.fileno())
        except Exception as e:
            logger.error("answered journal: append of %r failed: %s",
                         message_id, e)
            self.metrics.inc("finchat_durability_journal_append_failures_total")
            return False
        self.metrics.inc("finchat_durability_journal_appends_total")
        self._appends_since_compact += 1
        if self._appends_since_compact >= 8 * self.keep:
            self._compact()
        return True

    # --- startup / maintenance -------------------------------------------
    def _read(self) -> list:
        """Parse every intact record in file order; corrupt ones are
        skipped and counted (a torn tail after a crash is the normal
        case, a corrupt middle record the injected one)."""
        if not self.path.exists():
            return []
        ids: list = []
        corrupt = 0
        for line in self.path.read_bytes().split(b"\n"):
            if not line:
                continue
            mid = self._decode(line)
            if mid is _BAD:
                corrupt += 1
                continue
            ids.append(mid)
        if corrupt:
            logger.warning(
                "answered journal: skipped %d corrupt record(s) at %s "
                "(torn tail after a crash is expected; the intact records "
                "still replay)", corrupt, self.path,
            )
            self.metrics.inc("finchat_durability_quarantines_total", corrupt)
        return ids

    @staticmethod
    def _last_distinct(ids: list, keep: int) -> list:
        """Most recent ``keep`` distinct ids, oldest-first (a re-answered
        retry's LATEST append wins its slot, matching ring recency)."""
        seen: dict = {}
        for i, mid in enumerate(ids):
            seen[json.dumps(mid)] = i
        order = sorted(seen.values())[-keep:]
        return [ids[i] for i in order]

    def _rewrite(self, ids: list) -> None:  # finchat-lint: disable=event-loop-blocking -- compaction rewrites <= keep (~1024) 50-byte lines once per 8*keep appends; amortized microseconds per answer, and the fsync-before-commit ordering must hold through it
        tmp = self.path.with_suffix(".tmp")
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        with open(tmp, "wb") as f:
            for mid in ids:
                f.write(self._encode(mid))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._appends_since_compact = 0

    def _compact(self) -> None:
        try:
            self._rewrite(self._last_distinct(self._read(), self.keep))
        except Exception as e:
            logger.error("answered journal: compaction failed: %s", e)

    def replay(self) -> list:
        """Startup: the most recent ``keep`` distinct answered ids,
        oldest-first — seed them into the dedupe ring in order so ring
        recency matches journal recency. Also compacts the file (drops
        aged-out ids and the torn tail)."""
        ids = self._last_distinct(self._read(), self.keep)
        try:
            self._rewrite(ids)
        except Exception as e:
            logger.error("answered journal: post-replay compaction failed: %s", e)
        if ids:
            self.metrics.inc("finchat_durability_journal_replayed_total", len(ids))
            logger.info("answered journal: replayed %d answered message id(s) "
                        "into the dedupe ring", len(ids))
        return ids

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
