"""Answered-message journal (ISSUE 7, per-partition since ISSUE 20;
ROBUSTNESS.md §5, §7).

The at-least-once plane (PR 5/6) dedupes redelivered ``message_id``s
through an in-memory ring — which dies with the process, so a crash plus
Kafka redelivery of an answered-but-uncommitted message could double-answer
a conversation (the trade ROBUSTNESS.md used to document). This journal
closes it:

- ``append(message_id, partition=p)`` writes one checksummed line to the
  PARTITION's file and fsyncs BEFORE the app commits the message's Kafka
  offset (serve/app.py ``_done``). The ordering is the whole contract: if
  the process dies between the answer and the commit, the redelivered
  message finds its id in the replayed journal and is skipped; if it dies
  between the fsync and the answer's last produce... there is no such
  window — the id is appended only after the stream COMPLETED.
- Failed / shed / timed-out ids are never journaled (the app journals only
  answered ones), so a producer retrying a retryable error is reprocessed.
- ``replay()`` at startup parses the journal files, skipping corrupt
  records (a torn final line after a crash is expected; each skip is
  counted, the rest of the file is still honored — never a crash, never a
  lost id that parsed), compacts the files to the most recent ``keep``
  distinct ids (matching the dedupe ring's bound — older ids have aged
  out of the ring anyway), and returns them for the caller to seed the
  fleet-wide ``DedupeRing`` (serve/fleet.py).

**Per-partition layout (ISSUE 20).** One file per Kafka partition
(``answered-p0007.journal``): journal ownership aligns with partition
ownership, so when a host dies and a survivor adopts its partitions the
adopter calls ``replay(partitions=inherited, compact=False)`` and seeds
exactly the inherited partitions' answered ids into its ring — no global
journal to merge, no double-answer after a host-level kill -9. The legacy
single-file layout (``answered.journal``) is migrated into per-partition
files on first startup (one-way, logged): each legacy id lands in the
partition the broker's ``partition_for_key`` would assign its JSON form,
which is exactly where a redelivery of that id will be consumed.

Line format: ``v2 <crc32 hex> <seq hex16> <json message_id>\\n`` — the
CRC covers ``<seq hex16> <json>``, so a half-written or bit-flipped line
(including a flipped seq) never replays as a different record. ``seq`` is
a monotonic per-writer append stamp (``max(counter, time_ns)``) used to
interleave MULTIPLE partition files by true append order at replay:
without it, concatenating adopted journals file-by-file would let an old
partition's stale ids crowd a recent partition's answered ids out of the
``keep`` window and age a still-hot id out of the ring early (the ISSUE
20 bugfix). Legacy ``v1 <crc32 hex> <json>`` lines still decode, with
``seq=0`` — they sort before every v2 line, preserving their
oldest-first standing.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from pathlib import Path

from finchat_tpu.utils.faults import inject
from finchat_tpu.utils.logging import get_logger
from finchat_tpu.utils.metrics import METRICS

logger = get_logger(__name__)

_BAD = object()


def partition_filename(partition: int) -> str:
    return "answered-p%04d.journal" % partition


class AnsweredJournal:
    """Append-only, fsync-before-commit record of answered message ids,
    one file per owned Kafka partition."""

    FILENAME = "answered.journal"  # legacy single-file layout (pre-ISSUE 20)

    def __init__(self, dir_path: str, *, fsync: bool = True, keep: int = 1024,
                 metrics=None, num_partitions: int = 1):
        self.dir = Path(dir_path)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.fsync = fsync
        self.keep = keep
        self.num_partitions = max(1, int(num_partitions))
        self.metrics = metrics if metrics is not None else METRICS
        self._files: dict[int, object] = {}  # partition -> open handle
        # per-writer monotonic append stamp; seeded from the wall clock so
        # stamps stay ordered across restarts too (approximate across
        # hosts, exact per writer — good enough to interleave adopted
        # journals by append order)
        self._seq = 0
        # in-process compaction bound: the ring only ever holds ``keep``
        # ids, so journals much larger than that are pure dead weight
        self._appends_since_compact = 0
        self._migrate_legacy()

    # --- record codec ----------------------------------------------------
    def _next_seq(self) -> int:
        seq = max(self._seq, time.time_ns())
        self._seq = seq + 1
        return seq

    @staticmethod
    def _encode(message_id, seq: int) -> bytes:
        body = b"%016x " % seq + json.dumps(message_id).encode()
        return b"v2 %08x " % zlib.crc32(body) + body + b"\n"

    @staticmethod
    def _decode(line: bytes):
        """``(seq, id)``, or the ``_BAD`` sentinel for a corrupt/torn
        record. v1 lines (no seq stamp) decode with ``seq=0``."""
        parts = line.split(b" ", 2)
        if len(parts) != 3 or parts[0] not in (b"v1", b"v2"):
            return _BAD
        try:
            if int(parts[1], 16) != zlib.crc32(parts[2]):
                return _BAD
            if parts[0] == b"v1":
                return (0, json.loads(parts[2].decode()))
            seq_hex, _, payload = parts[2].partition(b" ")
            if len(seq_hex) != 16 or not payload:
                return _BAD
            return (int(seq_hex, 16), json.loads(payload.decode()))
        except (ValueError, UnicodeDecodeError):
            return _BAD

    # --- file layout ------------------------------------------------------
    def _part_path(self, partition: int) -> Path:
        return self.dir / partition_filename(partition)

    def partitions_on_disk(self) -> list[int]:
        """Partition indices that have a journal file (sorted)."""
        out = []
        for p in self.dir.glob("answered-p*.journal"):
            try:
                out.append(int(p.stem[len("answered-p"):]))
            except ValueError:
                continue
        return sorted(out)

    # --- legacy migration (ISSUE 20 satellite) ----------------------------
    def _migrate_legacy(self) -> None:
        """One-way: split a pre-ISSUE-20 single ``answered.journal`` into
        per-partition files on first startup. Each id is placed where the
        broker's CRC32 partitioner puts its JSON form, so the partition
        that will see the redelivery owns the dedupe line. Stamped with
        fresh increasing seqs in file order (they are the oldest records,
        and order among them is preserved); the legacy file is removed
        only after the split files are durably written."""
        legacy = self.dir / self.FILENAME
        if not legacy.exists():
            return
        try:
            from finchat_tpu.io.kafka import partition_for_key
            records = self._read_file(legacy)
            buckets: dict[int, list] = {}
            for _seq, mid in records:
                part = partition_for_key(json.dumps(mid), self.num_partitions)
                buckets.setdefault(part, []).append((self._next_seq(), mid))
            for part, pairs in sorted(buckets.items()):
                existing = self._read_file(self._part_path(part))
                # existing per-partition lines are NEWER than legacy ones
                self._rewrite(part, pairs + existing)
            os.unlink(legacy)
            logger.info(
                "answered journal: migrated legacy %s (%d record(s)) into "
                "%d per-partition file(s) — one-way", legacy, len(records),
                len(buckets),
            )
        except Exception as e:
            logger.error("answered journal: legacy migration failed "
                         "(will retry next startup): %s", e)

    # --- write path ------------------------------------------------------
    def append(self, message_id, partition: int = 0) -> bool:  # finchat-lint: disable=event-loop-blocking -- fsync-BEFORE-commit IS the at-least-once contract (ROBUSTNESS §5); one ~70-byte line per answered message, journal.fsync=false is the relief valve
        """Durably record an ANSWERED id under its partition's file.
        Best-effort by contract: a failure (disk full, injected
        ``journal.append`` fault) logs and returns False — the answer
        already streamed, and refusing to commit over a journal error
        would wedge the partition; the cost of the miss is one possible
        duplicate answer after a crash, exactly the pre-journal trade."""
        partition = max(0, int(partition))
        try:
            inject("journal.append", message_id=message_id,
                   partition=partition)
            fh = self._files.get(partition)
            if fh is None:
                fh = self._files[partition] = open(self._part_path(partition),
                                                  "ab")
            fh.write(self._encode(message_id, self._next_seq()))
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
        except Exception as e:
            logger.error("answered journal: append of %r (p%d) failed: %s",
                         message_id, partition, e)
            self.metrics.inc("finchat_durability_journal_append_failures_total")
            return False
        self.metrics.inc("finchat_durability_journal_appends_total")
        self._appends_since_compact += 1
        if self._appends_since_compact >= 8 * self.keep:
            self._compact()
        return True

    # --- startup / maintenance -------------------------------------------
    def _read_file(self, path: Path) -> list:
        """Parse every intact ``(seq, id)`` in file order; corrupt records
        are skipped and counted (a torn tail after a crash is the normal
        case, a corrupt middle record the injected one)."""
        if not path.exists():
            return []
        pairs: list = []
        corrupt = 0
        for line in path.read_bytes().split(b"\n"):
            if not line:
                continue
            rec = self._decode(line)
            if rec is _BAD:
                corrupt += 1
                continue
            pairs.append(rec)
        if corrupt:
            logger.warning(
                "answered journal: skipped %d corrupt record(s) at %s "
                "(torn tail after a crash is expected; the intact records "
                "still replay)", corrupt, path,
            )
            self.metrics.inc("finchat_durability_quarantines_total", corrupt)
        return pairs

    @staticmethod
    def _last_distinct(pairs: list, keep: int) -> list:
        """Most recent ``keep`` distinct ``(seq, id)``s, oldest-first (a
        re-answered retry's LATEST append wins its slot, matching ring
        recency). ``pairs`` must already be in merged append order."""
        seen: dict = {}
        for i, (_seq, mid) in enumerate(pairs):
            seen[json.dumps(mid)] = i
        order = sorted(seen.values())[-keep:]
        return [pairs[i] for i in order]

    def _scan(self, partitions: list[int]) -> dict[int, list]:
        """One read pass: ``{partition: [(seq, id)]}`` intact records
        (corrupt-line counting happens exactly once per file per scan)."""
        return {p: self._read_file(self._part_path(p)) for p in partitions}

    @staticmethod
    def _merged(per_part: dict[int, list]) -> list:
        """The scanned records interleaved by the seq stamp into true
        append order (stable: equal seqs — every v1 line — keep
        per-partition file order, grouped by partition)."""
        pairs: list = []
        for part in sorted(per_part):
            pairs.extend(per_part[part])
        pairs.sort(key=lambda rec: rec[0])
        return pairs

    def _rewrite(self, partition: int, pairs: list) -> None:  # finchat-lint: disable=event-loop-blocking -- compaction rewrites <= keep (~1024) 70-byte lines once per 8*keep appends; amortized microseconds per answer, and the fsync-before-commit ordering must hold through it
        path = self._part_path(partition)
        tmp = path.with_suffix(".tmp")
        fh = self._files.pop(partition, None)
        if fh is not None:
            fh.close()
        with open(tmp, "wb") as f:
            for seq, mid in pairs:
                f.write(self._encode(mid, seq))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def _compact(self, partitions: list[int] | None = None,
                 per_part: dict[int, list] | None = None) -> None:
        """Compact the given partitions (default: the ones this instance
        has written — never a partition some OTHER host owns) down to the
        globally most recent ``keep`` distinct ids. ``per_part`` lets a
        caller that already scanned the files (replay) skip the re-read."""
        try:
            parts = sorted(self._files) if partitions is None else partitions
            if not parts:
                return
            if per_part is None:
                per_part = self._scan(parts)
            survivors = self._last_distinct(self._merged(per_part), self.keep)
            live_keys = {json.dumps(mid) for _seq, mid in survivors}
            for part in parts:
                self._rewrite(part, [rec for rec in per_part.get(part, [])
                                     if json.dumps(rec[1]) in live_keys])
            self._appends_since_compact = 0
        except Exception as e:
            logger.error("answered journal: compaction failed: %s", e)

    def replay(self, partitions: list[int] | None = None,
               compact: bool = True) -> list:
        """The most recent ``keep`` distinct answered ids of the given
        partitions (default: every partition file on disk), oldest-first
        in true cross-file append order — seed them into the dedupe ring
        in order so ring recency matches journal recency. Startup callers
        leave ``compact=True`` (drops aged-out ids and the torn tail);
        partition ADOPTION passes ``compact=False`` — the adopter reads
        journals it is only just inheriting and must not rewrite them
        while the ownership handoff races."""
        parts = self.partitions_on_disk() if partitions is None else sorted(partitions)
        per_part = self._scan(parts)
        survivors = self._last_distinct(self._merged(per_part), self.keep)
        if survivors:
            self._seq = max(self._seq, survivors[-1][0] + 1)
        if compact:
            self._compact(parts, per_part)
        ids = [mid for _seq, mid in survivors]
        if ids:
            self.metrics.inc("finchat_durability_journal_replayed_total",
                             len(ids))
            logger.info("answered journal: replayed %d answered message "
                        "id(s) from %d partition file(s) into the dedupe "
                        "ring", len(ids), len(parts))
        return ids

    def close(self) -> None:
        for fh in self._files.values():
            fh.close()
        self._files.clear()
