"""Conversation store (Mongo semantics).

Preserves the reference's data model and behavior (``database.py``):

- db ``conversations``, collections ``contexts`` / ``messages``
  (database.py:11-13, config.py:32-33).
- ``get_context`` renders the context doc into the exact first-person
  natural-language block of database.py:56-68 and returns
  ``(context, user_id)``; missing doc or missing user_id raises
  (database.py:26-31).
- ``get_history`` returns turns sorted by ascending timestamp and RAISES if
  empty (database.py:77-79) — first-turn-with-no-history is a hard error
  path upstream (the app writes the user message before publishing to
  Kafka).
- ``save_ai_message`` inserts ``{conversation_id, sender: "AIMessage",
  user_id, message, timestamp:int}`` (database.py:95-101).

Backends: ``InMemoryStore`` (in-process, honest-async) and ``MongoStore``
(motor-less: pymongo run in a thread executor so the event loop never blocks
— fixing the reference's sync-in-async hazard, SURVEY §2.3).
"""

from __future__ import annotations

import asyncio
import time
from typing import Any, Protocol  # noqa: F401  (asyncio used by MongoStore)

from finchat_tpu.io.schemas import AI_SENDER, USER_SENDER, ChatMessage
from finchat_tpu.utils.config import (
    CONTEXT_COLLECTION_NAME,
    MESSAGE_COLLECTION_NAME,
    StoreConfig,
)
from finchat_tpu.utils.logging import get_logger

logger = get_logger(__name__)

try:  # optional backend
    import pymongo  # type: ignore
    import certifi  # type: ignore

    HAVE_PYMONGO = True
except ImportError:  # pragma: no cover - depends on image
    pymongo = None
    certifi = None
    HAVE_PYMONGO = False


def render_context(context_doc: dict[str, Any]) -> str:
    """Render a context document to the user-context block.

    Byte-for-byte the format of reference database.py:56-68, including the
    account normalization defaults of database.py:34-53.
    """
    accounts = []
    for a in context_doc.get("accounts") or []:
        balance = a.get("balances", {}) or {}
        accounts.append(
            {
                "official_name": a.get("official_name", "Unnamed Account"),
                "current": balance.get("current", 0.0),
                "iso_currency_code": balance.get("iso_currency_code", ""),
            }
        )

    context = (
        f"My name is {context_doc['name']}.\n"
        f"I make {context_doc['income']} dollars a month.\n"
        f"I want to save {context_doc['savings_goal']} a month.\n\n"
    )

    context += "Here is a list of my current account balances:\n"
    for account in accounts:
        context += f"{account['official_name']} : {account['current']} {account['iso_currency_code']}\n"

    context += "Here is a list of my recurring monthly expenses:\n"
    for expense in context_doc.get("additional_monthly_expenses") or []:
        context += f"Name: {expense['name']} | Amount: {expense['amount']}"
        if expense["description"] != "":
            context += f" | Description: {expense['description']}"
        context += "\n"

    return context


class ConversationStore(Protocol):
    async def check_connection(self) -> None: ...

    async def get_context(self, conversation_id: str) -> tuple[str, str]: ...

    async def get_history(self, conversation_id: str) -> list[ChatMessage]: ...

    async def save_ai_message(self, conversation_id: str, message: str, user_id: str) -> None: ...


class InMemoryStore:
    """In-process store with the Mongo-backed behavior above. Also the test
    fixture surface: ``upsert_context`` / ``add_user_message`` seed state."""

    def __init__(self, config: StoreConfig | None = None):
        self.config = config or StoreConfig()
        # Single-threaded event-loop access; no await inside any mutation,
        # so no lock is needed (and none is pretended).
        self._contexts: dict[str, dict[str, Any]] = {}
        self._messages: list[dict[str, Any]] = []

    async def check_connection(self) -> None:
        logger.info("In-memory store ready")

    async def get_context(self, conversation_id: str) -> tuple[str, str]:
        context_doc = self._contexts.get(conversation_id)
        if not context_doc:
            raise LookupError(f"No context found for conversation_id: {conversation_id}")
        user_id = context_doc.get("user_id", "")
        if not user_id:
            raise LookupError(f"No user_id found in context for conversation_id: {conversation_id}")
        return render_context(context_doc), user_id

    async def get_history(self, conversation_id: str) -> list[ChatMessage]:
        rows = sorted(
            (m for m in self._messages if m["conversation_id"] == conversation_id),
            key=lambda m: m["timestamp"],
        )
        if not rows:
            raise LookupError(f"No chat history found for conversation_id: {conversation_id}")
        return [
            ChatMessage(
                sender=m["sender"],
                message=m["message"],
                user_id=m.get("user_id", ""),
                conversation_id=conversation_id,
                timestamp=m["timestamp"],
            )
            for m in rows
        ]

    async def save_ai_message(self, conversation_id: str, message: str, user_id: str) -> None:
        self._messages.append(
            {
                "conversation_id": conversation_id,
                "sender": AI_SENDER,
                "user_id": user_id,
                "message": message,
                "timestamp": int(time.time()),
            }
        )

    # --- seeding helpers (used by tests and the dev harness) -------------
    def upsert_context(self, conversation_id: str, context_doc: dict[str, Any]) -> None:
        self._contexts[conversation_id] = {"conversation_id": conversation_id, **context_doc}

    def add_user_message(self, conversation_id: str, message: str, user_id: str, timestamp: int | None = None) -> None:
        self._messages.append(
            {
                "conversation_id": conversation_id,
                "sender": USER_SENDER,
                "user_id": user_id,
                "message": message,
                "timestamp": int(time.time()) if timestamp is None else timestamp,
            }
        )


class MongoStore:
    """pymongo-backed store. All blocking driver calls run in the default
    thread executor, keeping the event loop honest (the reference calls sync
    pymongo directly inside ``async def`` — database.py:25,77,95)."""

    def __init__(self, config: StoreConfig):
        if not HAVE_PYMONGO:  # pragma: no cover
            raise RuntimeError("store.backend=mongo but pymongo is not installed")
        self.config = config
        self._client = pymongo.MongoClient(config.mongodb_uri, tls=True, tlsCAFile=certifi.where())
        db = self._client[config.database_name]
        self._contexts = db[CONTEXT_COLLECTION_NAME]
        self._messages = db[MESSAGE_COLLECTION_NAME]

    async def _run(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(None, fn, *args)

    async def check_connection(self) -> None:
        try:
            await self._run(self._client.admin.command, "ping")
            logger.info("MongoDB connection successful!")
        except Exception as e:
            logger.error("MongoDB connection failed: %s", e)
            raise RuntimeError(f"MongoDB connection failed: {e}") from e

    async def get_context(self, conversation_id: str) -> tuple[str, str]:
        context_doc = await self._run(self._contexts.find_one, {"conversation_id": conversation_id})
        if not context_doc:
            raise LookupError(f"No context found for conversation_id: {conversation_id}")
        user_id = context_doc.get("user_id", "")
        if not user_id:
            raise LookupError(f"No user_id found in context for conversation_id: {conversation_id}")
        return render_context(context_doc), user_id

    async def get_history(self, conversation_id: str) -> list[ChatMessage]:
        def _fetch():
            return list(self._messages.find({"conversation_id": conversation_id}).sort("timestamp", 1))

        rows = await self._run(_fetch)
        if not rows:
            raise LookupError(f"No chat history found for conversation_id: {conversation_id}")
        return [
            ChatMessage(
                sender=m["sender"],
                message=m["message"],
                user_id=m.get("user_id", ""),
                conversation_id=conversation_id,
                timestamp=m["timestamp"],
            )
            for m in rows
        ]

    async def save_ai_message(self, conversation_id: str, message: str, user_id: str) -> None:
        doc = {
            "conversation_id": conversation_id,
            "sender": AI_SENDER,
            "user_id": user_id,
            "message": message,
            "timestamp": int(time.time()),
        }
        await self._run(self._messages.insert_one, doc)


def make_store(config: StoreConfig) -> ConversationStore:
    if config.backend == "mongo":
        return MongoStore(config)
    return InMemoryStore(config)
